#!/usr/bin/env python
"""fleet_top — one line per replica from a fleet exporter.

Read-only: polls ``GET /fleet/capacity`` (the capacity books every
replica publishes — health, headroom, TTFT forecast, affinity-sketch
size), ``GET /fleet/metrics.json`` (per-source goodput gauges) and,
when the process runs a ``runtime/router.FleetRouter``,
``GET /fleet/placements`` (the router's decision ring) from one
``serve_metrics`` exporter and renders the router's-eye view:

    KEY                ROLE    VIA    AGE   HEALTH    SLOTS  PAGES  QUEUE  TTFT-FC  CAL   GOODPUT  SKETCH  ROUTE
    decode:w0:4242     decode  telem  0.2s  ok         3/8    118   0.12   0.012s   0.94  1832.4   12      9x aff:96

The ROUTE column is why the router last picked the replica (placement
count, last decision's affinity-hit tokens / forecast) — "-" when no
router publishes placements. ``--sort`` reorders by what an operator
is hunting: ``health`` (worst first), ``forecast`` (slowest TTFT
estimate first), ``affinity`` (hottest sketch first).

No dependencies beyond the standard library (urllib), no mutation —
safe to point at a live deployment.

Usage::

    python scripts/fleet_top.py --url http://127.0.0.1:9100 \
        [--interval 2.0] [--once] [--sort health|forecast|affinity]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def _fetch(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.loads(r.read().decode())


def _fmt_headroom(hr: dict) -> tuple[str, str, str]:
    """(slots, pages, queue) columns; '-' when a tier doesn't book
    that resource (stage workers have no slots, dense replicas no
    pages)."""
    if "slots_total" in hr:
        slots = f"{hr.get('slots_free', 0)}/{hr.get('slots_total', 0)}"
    elif "stages" in hr:
        slots = f"st:{hr['stages']}"
    else:
        slots = "-"
    pages = (
        str(hr.get("pages_free", "-")) if "pages_total" in hr else "-"
    )
    if "queue_frac" in hr:
        queue = f"{hr['queue_frac']:.2f}"
    elif "queue_depth" in hr or "backlog" in hr:
        queue = str(hr.get("queue_depth", hr.get("backlog", 0)))
    else:
        queue = "-"
    return slots, pages, queue


def _route_col(key: str, placements: dict) -> tuple[str, int]:
    """The router-decision column for one capacity key: how many of
    the ring's placements landed on this replica and the last
    decision's why. Router decisions name replicas by their short
    name; capacity keys carry it as a ``decode:<name>`` segment (lease
    keys verbatim, telemetry keys role:worker:pid)."""
    decisions = placements.get("decisions") or ()
    count, last = 0, None
    for d in decisions:
        name = d.get("replica")
        if not name:
            continue
        if f"decode:{name}" in key or key.endswith(f":{name}"):
            count += 1
            last = d
    if last is None:
        return "-", 0
    why = last.get("why") or {}
    aff = int(why.get("affinity_tokens", 0))
    if aff > 0:
        return f"{count}x aff:{aff}", count
    fc = float(why.get("forecast_s", 0.0) or 0.0)
    if fc > 0:
        return f"{count}x fc:{fc:.3f}", count
    return f"{count}x load", count


#: ok sorts after degraded/critical when hunting trouble.
_HEALTH_RANK = {"critical": 0, "degraded": 1, "unknown": 2, "ok": 3}


def _rows(
    caps: dict, fleet: dict, placements: dict, sort: str = "key"
) -> list[tuple]:
    goodput = {
        key: src.get("gauges", {}).get("continuous.goodput_tokens_s")
        for key, src in fleet.get("sources", {}).items()
    }
    rows = []
    for key in sorted(caps.get("replicas", ())):
        rep = caps["replicas"][key]
        book = rep.get("book", {})
        fc = book.get("forecast", {})
        slots, pages, queue = _fmt_headroom(book.get("headroom", {}))
        # A replica's submit-time forecast for a bucket-8 cold prompt:
        # bias * (queue wait + a mid bucket wall + tick gap) — enough
        # to compare replicas at a glance.
        walls = fc.get("walls", {})
        wall = next(iter(sorted(walls.values())), 0.0) if walls else 0.0
        est = fc.get("bias", 1.0) * (
            fc.get("queue_wait_s", 0.0) + wall + fc.get("tick_gap_s", 0.0)
        )
        gp = goodput.get(key)
        health = str(book.get("health", "?"))
        sketch_n = len(book.get("sketch", {}).get("entries", ()))
        route, _ = _route_col(key, placements)
        sort_key = {
            "key": key,
            # Worst health first, staleness breaking ties (an aged
            # "ok" book deserves a look before a fresh one).
            "health": (
                _HEALTH_RANK.get(health, 2),
                -float(rep.get("age_s", 0.0)),
            ),
            "forecast": -est,  # slowest replica first
            "affinity": -sketch_n,  # hottest sketch first
        }[sort]
        rows.append((sort_key, (
            key[:24],
            str(rep.get("role", "?"))[:8],
            {"telemetry": "telem"}.get(rep.get("via"), rep.get("via")),
            f"{rep.get('age_s', 0.0):.1f}s",
            health,
            slots,
            pages,
            queue,
            f"{est:.3f}s" if est > 0 else "-",
            (
                f"{fc['calibration']:.2f}"
                if fc.get("samples") else "-"
            ),
            f"{gp:.1f}" if gp is not None else "-",
            str(sketch_n),
            route,
        )))
    rows.sort(key=lambda t: t[0])
    return [r for _, r in rows]


_HDR = (
    "KEY", "ROLE", "VIA", "AGE", "HEALTH", "SLOTS", "PAGES",
    "QUEUE", "TTFT-FC", "CAL", "GOODPUT", "SKETCH", "ROUTE",
)
_W = (24, 8, 6, 7, 9, 7, 6, 6, 8, 5, 9, 6, 12)


def _render(rows: list[tuple]) -> str:
    lines = ["  ".join(h.ljust(w) for h, w in zip(_HDR, _W))]
    for row in rows:
        lines.append(
            "  ".join(str(c).ljust(w) for c, w in zip(row, _W))
        )
    if not rows:
        lines.append("(no capacity books published yet)")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--url", default="http://127.0.0.1:9100",
        help="exporter base URL (serve_metrics address)",
    )
    ap.add_argument(
        "--interval", type=float, default=2.0,
        help="seconds between polls",
    )
    ap.add_argument(
        "--once", action="store_true",
        help="print one snapshot and exit (no screen clearing)",
    )
    ap.add_argument(
        "--sort", default="key",
        choices=("key", "health", "forecast", "affinity"),
        help="row order: lexical key (default), worst health first, "
        "slowest TTFT forecast first, or hottest affinity sketch "
        "first",
    )
    args = ap.parse_args(argv)
    base = args.url.rstrip("/")
    while True:
        try:
            caps = _fetch(base + "/fleet/capacity")
            fleet = _fetch(base + "/fleet/metrics.json")
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"fleet_top: {base}: {e}", file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        try:
            placements = _fetch(base + "/fleet/placements")
        except (urllib.error.URLError, OSError, ValueError):
            placements = {}  # no router in this process: 404 is fine
        out = _render(_rows(caps, fleet, placements, args.sort))
        if args.once:
            print(out)
            return 0
        # Home + clear-to-end, not full clear: no flicker on repaint.
        sys.stdout.write("\x1b[H\x1b[J" + out + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
