#!/usr/bin/env bash
# Tier-1 verify wrapper: runs the ROADMAP.md tier-1 command VERBATIM
# (kept in one place so docs, CI and humans stop copy-pasting it), then
# optionally the perf-regression gate.
#
# Usage:
#   scripts/tier1.sh           # tier-1 tests only (exit = pytest rc)
#   scripts/tier1.sh --gate    # tests, then benchmarks/ci_gate.py
#                              # against benchmarks/baselines/seed.json
#
# The gate is opt-in because it runs the micro-benchmark suite (a few
# minutes of CPU) and its wall-clock metrics want an otherwise idle
# machine; the tests alone are the mandatory bar.

set -u
cd "$(dirname "$0")/.."

GATE=0
for a in "$@"; do
  [ "$a" = "--gate" ] && GATE=1
done

# ROADMAP.md "Tier-1 verify" — verbatim (it ends in `exit $rc`, so it
# runs in a subshell and its exit status is captured here).
bash -c "set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=\${PIPESTATUS[0]}; echo DOTS_PASSED=\$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?\$' /tmp/_t1.log | tr -cd . | wc -c); exit \$rc"
rc=$?
if [ "$rc" -ne 0 ]; then
  echo "tier1.sh: tier-1 tests FAILED (rc=$rc)" >&2
  exit "$rc"
fi

if [ "$GATE" = "1" ]; then
  echo "tier1.sh: running perf-regression gate" >&2
  python benchmarks/ci_gate.py --baseline benchmarks/baselines/seed.json
  rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "tier1.sh: perf gate FAILED (rc=$rc)" >&2
    exit "$rc"
  fi
fi
exit 0
