"""TTL-lease worker membership registry.

TPU-native re-expression of the reference's etcd control plane: workers own
key ``/workers/<ip>`` with a lease (``/root/reference/src/node_state.py:
16-20``), the dispatcher reads the live pool at startup
(``src/dispatcher.py:285-289``) and watches it continuously
(``_worker_monitor``, call site ``:276``, body lost). Here membership is an
in-process KV with TTL leases and watch callbacks — the dispatcher-side
view is identical whether heartbeats arrive from an in-process worker
thread (single-host: devices as workers) or, later, from a remote host over
the comm transport.
"""

from __future__ import annotations

import threading
import time
from collections.abc import Callable

from adapt_tpu.utils.logging import get_logger

log = get_logger("registry")


class WorkerRegistry:
    """KV membership with TTL leases, expiry reaper, and join/leave watches."""

    def __init__(self, default_ttl_s: float = 2.0, reap_period_s: float = 0.1):
        self._lock = threading.Lock()
        self._leases: dict[str, float] = {}  # worker_id -> expiry time
        self._meta: dict[str, dict] = {}
        self._watchers: list[Callable[[str, str], None]] = []
        self._default_ttl = default_ttl_s
        self._reap_period = reap_period_s
        self._stop = threading.Event()
        self._reaper: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "WorkerRegistry":
        if self._reaper is None:
            self._reaper = threading.Thread(
                target=self._reap_loop, name="registry-reaper", daemon=True
            )
            self._reaper.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._reaper is not None:
            self._reaper.join(timeout=2.0)
            self._reaper = None

    # -- worker API (reference: node-side etcd writes) ----------------------

    def register(
        self, worker_id: str, meta: dict | None = None, ttl_s: float | None = None
    ) -> None:
        with self._lock:
            fresh = worker_id not in self._leases
            self._leases[worker_id] = time.monotonic() + (
                ttl_s or self._default_ttl
            )
            self._meta[worker_id] = dict(meta or {})
            watchers = list(self._watchers) if fresh else []
        for cb in watchers:
            cb("join", worker_id)
        if fresh:
            log.info("worker joined: %s", worker_id)

    def heartbeat(self, worker_id: str, ttl_s: float | None = None) -> bool:
        """Renew a lease; returns False if the lease already expired (the
        worker must re-register — mirrors etcd lease keepalive semantics)."""
        with self._lock:
            if worker_id not in self._leases:
                return False
            self._leases[worker_id] = time.monotonic() + (
                ttl_s or self._default_ttl
            )
            return True

    def deregister(self, worker_id: str) -> None:
        self._expire([worker_id], reason="deregister")

    # -- dispatcher API (reference: _get_available_workers / _worker_monitor)

    def alive(self) -> list[str]:
        now = time.monotonic()
        with self._lock:
            return [w for w, exp in self._leases.items() if exp > now]

    def meta(self, worker_id: str) -> dict:
        with self._lock:
            return dict(self._meta.get(worker_id, {}))

    def watch(self, callback: Callable[[str, str], None]) -> None:
        """callback(event, worker_id) with event in {'join', 'leave'}."""
        with self._lock:
            self._watchers.append(callback)

    def wait_for_workers(self, n: int, timeout_s: float) -> bool:
        """Bounded startup wait (reference: 5 s then clean shutdown,
        ``src/dispatcher.py:282-295``)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if len(self.alive()) >= n:
                return True
            time.sleep(0.02)
        return len(self.alive()) >= n

    # -- internals ----------------------------------------------------------

    def _expire(self, worker_ids: list[str], reason: str) -> None:
        fired = []
        with self._lock:
            for w in worker_ids:
                if w in self._leases:
                    del self._leases[w]
                    self._meta.pop(w, None)
                    fired.append(w)
            watchers = list(self._watchers)
        for w in fired:
            log.info("worker left (%s): %s", reason, w)
            for cb in watchers:
                cb("leave", w)

    def _reap_loop(self) -> None:
        while not self._stop.wait(self._reap_period):
            now = time.monotonic()
            with self._lock:
                dead = [w for w, exp in self._leases.items() if exp <= now]
            if dead:
                self._expire(dead, reason="lease expired")
