"""TTL-lease worker membership registry.

TPU-native re-expression of the reference's etcd control plane: workers own
key ``/workers/<ip>`` with a lease (``/root/reference/src/node_state.py:
16-20``), the dispatcher reads the live pool at startup
(``src/dispatcher.py:285-289``) and watches it continuously
(``_worker_monitor``, call site ``:276``, body lost). Here membership is an
in-process KV with TTL leases and watch callbacks — the dispatcher-side
view is identical whether heartbeats arrive from an in-process worker
thread (single-host: devices as workers) or, later, from a remote host over
the comm transport.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections.abc import Callable

from adapt_tpu.utils.logging import get_logger

log = get_logger("registry")


class WorkerRegistry:
    """KV membership with TTL leases, expiry reaper, and join/leave watches."""

    def __init__(self, default_ttl_s: float = 2.0, reap_period_s: float = 0.1):
        self._lock = threading.Lock()
        self._leases: dict[str, float] = {}  # worker_id -> expiry time
        self._meta: dict[str, dict] = {}
        # Lease ownership: each register() bumps the id's token. A holder
        # that passes its token to deregister() can only revoke its OWN
        # lease — a stale connection dying late cannot evict the
        # replacement that re-registered under the same worker id (etcd
        # lease-id semantics: the key outlives any one lease holder).
        self._tokens: dict[str, int] = {}
        self._token_counter = 0
        self._watchers: list[Callable[[str, str], None]] = []
        self._default_ttl = default_ttl_s
        self._reap_period = reap_period_s
        self._stop = threading.Event()
        self._reaper: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "WorkerRegistry":
        if self._reaper is None:
            self._reaper = threading.Thread(
                target=self._reap_loop, name="registry-reaper", daemon=True
            )
            self._reaper.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._reaper is not None:
            self._reaper.join(timeout=2.0)
            self._reaper = None

    # -- worker API (reference: node-side etcd writes) ----------------------

    def register(
        self, worker_id: str, meta: dict | None = None, ttl_s: float | None = None
    ) -> int:
        """Create/renew a lease; returns an ownership token for
        :meth:`deregister` (latest registration wins the id)."""
        with self._lock:
            fresh = worker_id not in self._leases
            self._leases[worker_id] = time.monotonic() + (
                ttl_s or self._default_ttl
            )
            self._meta[worker_id] = dict(meta or {})
            self._token_counter += 1
            token = self._token_counter
            self._tokens[worker_id] = token
            watchers = list(self._watchers) if fresh else []
        for cb in watchers:
            try:
                cb("join", worker_id)
            except Exception:  # noqa: BLE001 — a watcher bug must not
                log.exception("join watcher failed")  # break membership
        if fresh:
            log.info("worker joined: %s", worker_id)
        return token

    def heartbeat(self, worker_id: str, ttl_s: float | None = None) -> bool:
        """Renew a lease; returns False if the lease already expired (the
        worker must re-register — mirrors etcd lease keepalive semantics)."""
        with self._lock:
            if worker_id not in self._leases:
                return False
            self._leases[worker_id] = time.monotonic() + (
                ttl_s or self._default_ttl
            )
            return True

    def deregister(self, worker_id: str, token: int | None = None) -> None:
        """Remove a lease. With ``token``, only if the caller still owns
        the id — a late deregister from a superseded holder is a no-op.
        (The ownership check happens under the same lock that deletes, so
        a replacement registering between check and delete cannot be
        evicted by the stale holder.)"""
        self._expire([worker_id], reason="deregister", token=token)

    # -- dispatcher API (reference: _get_available_workers / _worker_monitor)

    def alive(self, role: str | None = None) -> list[str]:
        """Live lease holders. ``role`` filters by the lease's
        ``meta["role"]`` tag — the worker-pool partitioning knob the
        disaggregated serving tier uses (``runtime/disagg`` registers
        its prefill pool under ``role="prefill"``, so the pipeline
        dispatcher's acquisition and the placement policy read disjoint
        pools off ONE membership registry). ``role=None`` returns
        every live lease, tagged or not (the pre-role behavior)."""
        now = time.monotonic()
        with self._lock:
            return [
                w
                for w, exp in self._leases.items()
                if exp > now
                and (role is None or self._meta[w].get("role") == role)
            ]

    def alive_untagged(self) -> list[str]:
        """Live leases with NO role tag — the pool general schedulers
        (the pipeline dispatcher's ``_acquire``) may draw from. One
        lock hold, unlike filtering ``alive()`` through per-worker
        :meth:`role` calls on the dispatch hot path."""
        now = time.monotonic()
        with self._lock:
            return [
                w
                for w, exp in self._leases.items()
                if exp > now and self._meta[w].get("role") is None
            ]

    def alive_meta(self) -> dict[str, dict]:
        """Every live lease's metadata in ONE lock hold
        (``{worker_id: meta copy}``) — the telemetry federation
        poller's scan (``utils.telemetry.FederatedStore.poll_registry``
        reads each lease's ``meta["telemetry"]`` pull URL), and any
        other reader that would otherwise pay a lock acquisition per
        worker via :meth:`meta`."""
        now = time.monotonic()
        with self._lock:
            return {
                w: dict(self._meta[w])
                for w, exp in self._leases.items()
                if exp > now
            }

    def role(self, worker_id: str) -> str | None:
        """The lease's ``meta["role"]`` tag (None = untagged)."""
        with self._lock:
            return self._meta.get(worker_id, {}).get("role")

    def meta(self, worker_id: str) -> dict:
        with self._lock:
            return dict(self._meta.get(worker_id, {}))

    def watch(self, callback: Callable[[str, str], None]) -> None:
        """callback(event, worker_id) with event in {'join', 'leave'}."""
        with self._lock:
            self._watchers.append(callback)

    def wait_for_workers(self, n: int, timeout_s: float) -> bool:
        """Bounded startup wait (reference: 5 s then clean shutdown,
        ``src/dispatcher.py:282-295``)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if len(self.alive()) >= n:
                return True
            time.sleep(0.02)
        return len(self.alive()) >= n

    # -- internals ----------------------------------------------------------

    def _expire(
        self, worker_ids: list[str], reason: str, token: int | None = None
    ) -> None:
        fired = []
        with self._lock:
            for w in worker_ids:
                if token is not None and self._tokens.get(w) != token:
                    continue  # superseded holder: the id is not ours to kill
                if w in self._leases:
                    del self._leases[w]
                    self._meta.pop(w, None)
                    self._tokens.pop(w, None)
                    fired.append(w)
            watchers = list(self._watchers)
        for w in fired:
            log.info("worker left (%s): %s", reason, w)
            for cb in watchers:
                try:
                    cb("leave", w)
                except Exception:  # noqa: BLE001
                    log.exception("leave watcher failed")

    def _reap_loop(self) -> None:
        while not self._stop.wait(self._reap_period):
            now = time.monotonic()
            with self._lock:
                dead = [w for w, exp in self._leases.items() if exp <= now]
            if dead:
                self._expire(dead, reason="lease expired")


def weak_watch(watchable, obj, method_name: str) -> None:
    """Subscribe ``obj.<method_name>(event, key)`` to
    ``watchable.watch`` WEAKLY: watcher lists have no unwatch and
    outlive subscribers, so a bound method there would pin a retired
    subscriber (and everything it references — compiled state, KV
    pools, Device handles) forever. The shim no-ops once ``obj`` is
    collected or flips its ``_retired`` flag — the ONE definition of
    the discipline every registry subscriber follows."""
    wr = weakref.ref(obj)

    def _cb(event: str, key: str, _wr=wr) -> None:
        o = _wr()
        if o is not None and not getattr(o, "_retired", False):
            getattr(o, method_name)(event, key)

    watchable.watch(_cb)


class DeviceHealthMonitor:
    """Device health over the SAME membership machinery the worker tier
    uses: every tracked mesh device owns a :class:`WorkerRegistry`
    lease under ``device:<id>``, and a loss is a ``leave`` event — the
    etcd-membership-drives-repartitioning shape of the source paper,
    applied to chips instead of hosts.

    Simulated-kill injectable by construction: :meth:`kill` marks a
    device dead and revokes its lease, firing every registry watcher
    (the ``ContinuousBatcher`` subscribes and re-shards at its next
    tick — or raises ``DeviceLostError`` from subsequent dispatches
    when ``RecoveryConfig.auto_reshard`` is off). On real hardware the
    same ``leave`` edge arrives from lease expiry when a chip's host
    agent stops heartbeating; the serving tier cannot tell the
    difference, which is the point — the recovery path tested against
    :meth:`kill` is the one a real loss exercises.

    Device leases default to a very long TTL (simulated devices have no
    heartbeat loop; the event path is what this monitor models — the
    TTL reaper stays the backstop for registries shared with real
    workers)."""

    #: Lease TTL for tracked devices (no heartbeat loop in-process —
    #: effectively "until killed or deregistered").
    DEVICE_TTL_S = 1e9

    def __init__(self, registry: WorkerRegistry | None = None):
        self.registry = registry if registry is not None else WorkerRegistry()
        self._lock = threading.Lock()
        self._dead: set[int] = set()
        self._devices: dict[int, object] = {}  # device id -> jax Device
        self._retired = False
        # Fold ANY membership leave for a tracked device into the dead
        # set — so a lease EXPIRY (the production loss signal, fired by
        # the registry's TTL reaper) and kill() land identically, and
        # recover()'s dead_ids() view always agrees with the leave
        # event the batcher queued. Weak (see weak_watch): the watcher
        # list outlives monitors.
        weak_watch(self.registry, self, "_fold_leave")

    def close(self) -> None:
        """Retire the monitor: its fold watcher goes quiet (the shared
        registry — and other monitors/batchers watching it — are
        untouched)."""
        self._retired = True

    def _fold_leave(self, event: str, key: str) -> None:
        if event != "leave" or not key.startswith("device:"):
            return
        try:
            did = int(key.split(":", 1)[1])
        except ValueError:
            return
        with self._lock:
            if did in self._devices:
                self._dead.add(did)

    @staticmethod
    def device_key(device) -> str:
        """Membership key for a jax device — the ``/workers/<ip>``
        analog."""
        return f"device:{int(device.id)}"

    def track(self, devices) -> None:
        """Register every device of a mesh (idempotent — re-tracking a
        device renews its lease, etcd keepalive semantics)."""
        for d in devices:
            with self._lock:
                self._devices[int(d.id)] = d
                fresh_dead = int(d.id) in self._dead
            if fresh_dead:
                continue  # a dead device does not rejoin by re-track
            self.registry.register(
                self.device_key(d),
                meta={"platform": getattr(d, "platform", "unknown")},
                ttl_s=self.DEVICE_TTL_S,
            )

    def kill(self, device) -> str:
        """Simulate losing ``device`` (a jax Device or its integer id):
        mark it dead and revoke its membership lease — registry
        watchers fire ``('leave', 'device:<id>')`` synchronously on the
        calling thread. Returns the membership key. Idempotent."""
        did = int(device if isinstance(device, int) else device.id)
        with self._lock:
            already = did in self._dead
            self._dead.add(did)
        key = f"device:{did}"
        if not already:
            self.registry.deregister(key)
        return key

    def is_dead(self, device) -> bool:
        did = int(device if isinstance(device, int) else device.id)
        with self._lock:
            return did in self._dead

    def dead_ids(self) -> set[int]:
        with self._lock:
            return set(self._dead)

    def alive_devices(self, devices) -> list:
        """``devices`` filtered to the ones not marked dead (order
        preserved — mesh rebuilds depend on it)."""
        with self._lock:
            dead = set(self._dead)
        return [d for d in devices if int(d.id) not in dead]

    def watch(self, callback: Callable[[str, str], None]) -> None:
        """Subscribe to membership events (``callback(event, key)``,
        event in {'join', 'leave'}) — delegates to the registry, so a
        monitor sharing a registry with real workers delivers both
        populations through one watch."""
        self.registry.watch(callback)
