"""Stage workers: device-owning executors with heartbeats and kill modes.

The TPU-native analog of the reference's ``Node`` (``/root/reference/src/
node.py``): a worker owns a compute resource (there: the whole machine's TF
runtime; here: one JAX device), accepts stage configurations (there: model
JSON + weights over port 6001 with an ACK, ``src/node.py:65-98``; here: a
jitted stage fn + device_put of its variables), executes data tasks (there:
``model.predict`` per request, ``:177``; here: the XLA stage program), and
posts every result back to the dispatcher hub (Gen-2 star topology,
``src/dispatcher.py:121-151``).

Kill modes for fault injection (SURVEY.md §5 'chaos hook'):
- ``crash``: stop heartbeating AND stop processing -> lease expiry evicts
  the worker from membership.
- ``hang``: keep heartbeating but stop processing -> only the task-deadline
  watchdog can catch it (the harder failure; the reference's watchdog
  exists for exactly this, ``src/dispatcher.py:302-304``).
"""

from __future__ import annotations

import enum
import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import jax

from adapt_tpu.config import FaultConfig
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.tracing import global_flight_recorder, global_tracer

log = get_logger("worker")


class WorkerState(enum.Enum):
    """Reference ``StateEnum`` (``src/node_state.py:163-167``)."""

    IDLE = "idle"
    BUSY = "busy"
    DEAD = "dead"


#: Sentinel stage index for liveness-probe (canary) tasks: the worker
#: answers immediately without touching any stage binding. A hung worker's
#: exec loop swallows the ping exactly like a real task — that is the
#: signal the dispatcher's watchdog turns into a strike.
PING_STAGE = -1


@dataclass
class Task:
    """One stage-execution request (reference: 4-byte stage index + framed
    payload on port 6000, ``src/dispatcher.py:209-213``)."""

    request_id: int
    stage_index: int
    attempt: int
    payload: Any  # host or device array
    #: Chain-mode head submit (comm.remote chain forwarding): the result
    #: returns on a DIFFERENT worker's link, so the receiving proxy must
    #: not count it against its own in-flight depth.
    chained: bool = False
    #: Stamped by StageWorker.submit (perf-counter clock): how long the
    #: task sat in the inbox feeds the ``worker.queue_wait_s`` histogram.
    t_enqueue: float = 0.0


@dataclass
class TaskResult:
    request_id: int
    stage_index: int
    attempt: int
    worker_id: str
    output: Any = None
    error: str | None = None


@dataclass
class _StageBinding:
    fn: Any  # shared jitted (variables, x) -> y
    variables: Any  # device-resident
    device: jax.Device
    spec: Any = field(default=None)
    generation: int = 0  # which configure installed this binding


class StageWorker:
    """In-process worker bound to one JAX device."""

    def __init__(
        self,
        worker_id: str,
        device: jax.Device,
        registry: WorkerRegistry,
        result_queue: "queue.Queue[TaskResult]",
        fault: FaultConfig | None = None,
    ):
        self.worker_id = worker_id
        self.device = device
        self._registry = registry
        self._results = result_queue
        self._fault = fault or FaultConfig()
        self._inbox: queue.Queue[Task | None] = queue.Queue()
        self._bindings: dict[int, _StageBinding] = {}
        self._bind_gen = itertools.count(1)
        self._bind_lock = threading.Lock()
        self._state = WorkerState.IDLE
        self._state_lock = threading.Lock()
        self._crashed = threading.Event()
        self._stopping = threading.Event()  # clean stop() vs crash
        self._hung = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "StageWorker":
        self._registry.register(
            self.worker_id,
            meta={"device": str(self.device)},
            ttl_s=self._fault.lease_ttl_s,
        )
        for name, target in (
            ("exec", self._exec_loop),
            ("heartbeat", self._heartbeat_loop),
        ):
            t = threading.Thread(
                target=target, name=f"{self.worker_id}-{name}", daemon=True
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stopping.set()
        self._crashed.set()
        self._inbox.put(None)
        for t in self._threads:
            t.join(timeout=2.0)
        self._registry.deregister(self.worker_id)

    # -- fault injection ----------------------------------------------------

    def kill(self, mode: str = "crash") -> None:
        global_flight_recorder().record(
            "worker_killed", worker=self.worker_id, mode=mode
        )
        if mode == "crash":
            self._crashed.set()
            self._inbox.put(None)
            log.warning("worker %s crashed (injected)", self.worker_id)
            with self._state_lock:
                self._state = WorkerState.DEAD
        elif mode == "hang":
            # A real hang keeps heartbeating and stays schedulable — the
            # dispatcher must discover it via task deadlines, not state.
            self._hung.set()
            log.warning("worker %s hung (injected)", self.worker_id)
        else:
            raise ValueError(f"unknown kill mode {mode!r}")

    def revive(self) -> None:
        """Chaos hook: clear an injected hang. The exec loop resumes
        draining its inbox — including any queued canary probes, whose
        answers lift the dispatcher's quarantine (self-healing)."""
        self._hung.clear()

    # -- dispatcher-facing API ----------------------------------------------

    @property
    def state(self) -> WorkerState:
        with self._state_lock:
            return self._state

    def is_configured(self, stage_index: int) -> bool:
        with self._bind_lock:
            return stage_index in self._bindings

    def configure(
        self, stage_index: int, fn, host_variables, spec=None, abort=None
    ) -> int:
        """Install a stage on this worker's device; returns when weights are
        resident (the reference's JSON+weights+ACK handshake,
        ``src/dispatcher.py:223-264`` / ``src/node.py:65-98``, collapsed to
        a device_put + blocking ready wait).

        ``abort`` is an optional zero-arg callable checked before the slow
        weight transfer and again immediately before installing the
        binding: a dispatcher that timed out this handshake sets it, so the
        abandoned configure thread cannot install state (and pin HBM) after
        the dispatcher moved on.

        Returns a generation handle for :meth:`unconfigure` — a revoke is
        scoped to the configure that earned it, so undoing an abandoned
        handshake can never drop a newer configure's binding."""
        if self._crashed.is_set():
            raise RuntimeError(f"worker {self.worker_id} is dead")
        if abort is not None and abort():
            raise RuntimeError("configure aborted before weight transfer")
        variables = jax.device_put(host_variables, self.device)
        jax.block_until_ready(variables)  # the ACK
        generation = next(self._bind_gen)
        with self._bind_lock:
            if abort is not None and abort():
                raise RuntimeError("configure aborted (caller timed out)")
            self._bindings[stage_index] = _StageBinding(
                fn=fn,
                variables=variables,
                device=self.device,
                spec=spec,
                generation=generation,
            )
        global_metrics().inc("worker.configured")
        return generation

    def unconfigure(self, stage_index: int, generation: int | None = None) -> None:
        """Drop a stage binding (releases the device weight references).
        With ``generation``, only if that configure's binding is still the
        installed one."""
        with self._bind_lock:
            binding = self._bindings.get(stage_index)
            if binding is None:
                return
            if generation is not None and binding.generation != generation:
                return
            del self._bindings[stage_index]

    def submit(self, task: Task) -> None:
        task.t_enqueue = time.perf_counter()
        self._inbox.put(task)

    @property
    def queue_depth(self) -> int:
        return self._inbox.qsize()

    # -- loops --------------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        # A crashed worker stops renewing; the registry reaper evicts it
        # after lease_ttl (reference: etcd lease expiry on /workers/<ip>).
        while not self._crashed.wait(self._fault.heartbeat_s):
            renewed = self._registry.heartbeat(
                self.worker_id, ttl_s=self._fault.lease_ttl_s
            )
            if not renewed and not self._crashed.is_set():
                # Lease lapsed (e.g. a long compile stalled this thread)
                # but we are alive: re-register rather than serve forever
                # while invisible to the scheduler.
                self._registry.register(
                    self.worker_id,
                    meta={"device": str(self.device)},
                    ttl_s=self._fault.lease_ttl_s,
                )
                if self._crashed.is_set():
                    # Check-then-act race with the exec loop's
                    # crash-eviction deregister: if the kill landed
                    # between our pre-check and the register above, the
                    # eviction may already have run and our register
                    # just resurrected a dead worker's lease. The
                    # post-register re-check closes every interleaving:
                    # whichever side runs last removes the lease.
                    self._registry.deregister(self.worker_id)

    def _exec_loop(self) -> None:
        try:
            self._exec_loop_inner()
        finally:
            if self._crashed.is_set() and not self._stopping.is_set():
                # Event-driven crash eviction: an in-process worker whose
                # exec loop died is gone NOW — deregister instead of
                # letting membership wait out the lease TTL. The
                # reference evicts on socket error, not timeout
                # (src/dispatcher.py:153-161), and the cross-host path
                # here already deregisters when the link closes
                # (comm/remote.py); this is the local equivalent. A hang
                # keeps its lease by design — only the watchdog can call
                # that.
                self._registry.deregister(self.worker_id)
                global_metrics().inc("worker.crash_evicted")
                global_flight_recorder().record(
                    "worker_crash_evicted", worker=self.worker_id
                )
                log.warning(
                    "worker %s evicted on crash (event, not TTL)",
                    self.worker_id,
                )

    def _exec_loop_inner(self) -> None:
        while not self._crashed.is_set():
            task = self._inbox.get()
            if task is None or self._crashed.is_set():
                break
            if self._hung.is_set():
                # Hung worker: swallow the task, never reply. The
                # dispatcher's watchdog must recover it.
                continue
            if task.stage_index < 0:
                # Liveness probe: answer without executing anything. Must
                # flow through this loop (not a side channel) so a blocked
                # exec loop fails the probe the way it fails real tasks.
                self._results.put(
                    TaskResult(
                        request_id=task.request_id,
                        stage_index=task.stage_index,
                        attempt=task.attempt,
                        worker_id=self.worker_id,
                    )
                )
                continue
            if task.t_enqueue:
                # Inbox wait: workers drain serially, so queue depth is
                # latency — the per-worker serving-SLO signal.
                global_metrics().observe(
                    "worker.queue_wait_s",
                    time.perf_counter() - task.t_enqueue,
                )
            with self._state_lock:
                self._state = WorkerState.BUSY
            try:
                with self._bind_lock:
                    binding = self._bindings.get(task.stage_index)
                if binding is None:
                    raise RuntimeError(
                        f"stage {task.stage_index} not configured on "
                        f"{self.worker_id}"
                    )
                with global_tracer().span(
                    "stage_exec",
                    stage=task.stage_index,
                    worker=self.worker_id,
                    request=task.request_id,
                    attempt=task.attempt,
                ):
                    x = jax.device_put(task.payload, self.device)
                    y = binding.fn(binding.variables, x)
                    # Pytree-safe: decode-session stages return (output,
                    # caches) tuples, not a single array.
                    jax.block_until_ready(y)
                self._results.put(
                    TaskResult(
                        request_id=task.request_id,
                        stage_index=task.stage_index,
                        attempt=task.attempt,
                        worker_id=self.worker_id,
                        output=y,
                    )
                )
                global_metrics().inc("worker.tasks_ok")
            except Exception as e:  # noqa: BLE001 — report, don't die
                log.error("worker %s task failed: %s", self.worker_id, e)
                self._results.put(
                    TaskResult(
                        request_id=task.request_id,
                        stage_index=task.stage_index,
                        attempt=task.attempt,
                        worker_id=self.worker_id,
                        error=str(e),
                    )
                )
                global_metrics().inc("worker.tasks_failed")
            finally:
                with self._state_lock:
                    if self._state is not WorkerState.DEAD:
                        self._state = WorkerState.IDLE
