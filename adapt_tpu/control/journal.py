"""Dispatcher write-ahead journal: membership + in-flight work that
OUTLIVES the dispatcher process.

The reference keeps membership in an etcd *server* whose lifetime is
independent of the dispatcher (``/root/reference/src/start_etcd.sh:81-94``;
worker keys ``src/node_state.py:16-20``) — a dispatcher restart rediscovers
the pool from etcd. Managing an etcd server is a declared non-goal
(SURVEY §7.5); what this module rebuilds is the *semantics that matter*:
after a dispatcher crash, a fresh process can (a) re-adopt the worker pool
and (b) replay every request that was accepted but never completed —
exactly once each from the client's view.

Design: an append-only JSONL WAL (`wal.jsonl`) for worker records and
request submit/done marks, with request payloads as individual `.npy`
files written atomically (tmp + rename) BEFORE their submit mark — a
submit mark therefore always has its payload. `record_done` appends on
ANY terminal completion (value or error): replay is for requests that
never completed, not for retrying failures the old dispatcher already
reported. Worker weights are NOT journaled — stage weights re-stream from
the model variables the new dispatcher is constructed with (the
checkpoint layer, ``utils/checkpoint.py``, owns model state; the journal
owns control-plane state).

The WAL is self-compacting: a live mirror of {workers, pending ids}
rides in memory, and every ``compact_every`` appends (and every
:meth:`load`) the file is rewritten to just that state — journal size
and recovery time are bounded by LIVE state, not all-time history.

At-least-once window, stated honestly: a crash BETWEEN a future's
completion and its done mark replays that request once more on recovery
(standard WAL semantics). Within one dispatcher's life, completion is
exactly-once (request ids + attempt tags); across a crash, each pending
request completes exactly once in the recovered dispatcher.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

import numpy as np

from adapt_tpu.utils.logging import get_logger

log = get_logger("journal")


class DispatcherJournal:
    """Append-only crash journal under ``root``. Thread-safe; every
    append is flushed + fsynced (a journal that loses its tail to the
    page cache would silently drop requests on a host crash)."""

    def __init__(self, root: str, compact_every: int = 10_000):
        self.root = root
        self.compact_every = compact_every
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._wal_path = os.path.join(root, "wal.jsonl")
        # Live mirror (rebuilt from the file on open): what a compaction
        # writes, and what keeps compaction O(live state) not O(history).
        self._workers: dict[str, dict] = {}
        self._pending: set[int] = set()
        #: Pending ids' submit metadata (sampling knobs etc. — whatever
        #: JSON dict the submitter attached): what lets a replayed
        #: request be RECONSTRUCTED from the journal, not just re-run
        #: as a bare payload. Dropped with the done mark.
        self._submit_meta: dict[int, dict] = {}
        #: ids whose payload write is in flight (reserved in
        #: record_submit BEFORE the file appears): the compaction sweep
        #: must not reap a payload whose submit mark hasn't landed yet.
        self._writing: set[int] = set()
        #: done-marked ids whose payloads await group-commit reclaim.
        self._reclaimable: list[int] = []
        self._max_id = -1
        self._appends = 0
        self._replay_file_into_mirror()
        self._wal = open(self._wal_path, "a", encoding="utf-8")
        # Persist the WAL's DIRECTORY entry now: appends fsync the file,
        # but a freshly created wal.jsonl only becomes durable once its
        # directory entry reaches disk — until the first compaction's
        # rename-fsync, a host crash could revert the creation and drop
        # every pre-compaction append with it (ADVICE r5).
        self._fsync_root()

    # -- write side ----------------------------------------------------------

    def _apply_to_mirror(self, rec: dict) -> None:
        op = rec.get("op")
        if op == "worker":
            self._workers[rec["id"]] = {
                "host": rec["host"],
                "port": rec["port"],
                "meta": rec.get("meta", {}),
            }
        elif op == "worker_gone":
            self._workers.pop(rec["id"], None)
        elif op == "submit":
            self._pending.add(rec["id"])
            if rec.get("meta") is not None:
                self._submit_meta[rec["id"]] = rec["meta"]
            self._max_id = max(self._max_id, rec["id"])
        elif op == "done":
            self._pending.discard(rec["id"])
            self._submit_meta.pop(rec["id"], None)
            self._max_id = max(self._max_id, rec["id"])
        elif op == "horizon":
            # Compaction's id-watermark record: keeps next_request_id
            # monotone across rewrites without implying any completion.
            self._max_id = max(self._max_id, rec["id"])

    @property
    def next_request_id(self) -> int:
        """One past the highest id this journal has ever seen — the seed
        for any dispatcher serving over this journal (a fresh counter
        would recycle ids and silently clear crashed-but-unreplayed
        requests with its done marks)."""
        with self._lock:
            return self._max_id + 1

    def _replay_file_into_mirror(self) -> None:
        if not os.path.exists(self._wal_path):
            return
        with open(self._wal_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    # A torn final line is the expected crash shape: its
                    # payload (if any) was orphaned pre-mark and is
                    # ignored; everything before it is intact.
                    log.warning("journal: skipping torn WAL line")
                    continue
                self._apply_to_mirror(rec)

    def _fsync_root(self) -> None:
        """Durable-rename half: fsyncing a renamed FILE does not persist
        the rename itself — the DIRECTORY entry must also reach disk, or
        a host crash reverts the rename (losing a payload, or worse,
        reverting a compaction and losing every record after it)."""
        fd = os.open(self.root, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _append(self, record: dict, fsync: bool = True) -> None:
        with self._lock:
            self._wal.write(json.dumps(record) + "\n")
            self._wal.flush()
            if fsync:
                os.fsync(self._wal.fileno())
            self._apply_to_mirror(record)
            self._appends += 1
            if self._appends >= self.compact_every:
                self._compact_locked()

    def _compact_locked(self) -> None:
        """Rewrite the WAL as {current workers} + {pending submit marks}
        — atomic (tmp + rename), then reopen for append."""
        tmp = self._wal_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for wid, info in self._workers.items():
                f.write(
                    json.dumps(
                        {
                            "op": "worker",
                            "id": wid,
                            "host": info["host"],
                            "port": info["port"],
                            "meta": info.get("meta", {}),
                        }
                    )
                    + "\n"
                )
            for rid in sorted(self._pending):
                rec = {"op": "submit", "id": rid}
                meta = self._submit_meta.get(rid)
                if meta is not None:
                    rec["meta"] = meta  # survives compaction with its mark
                f.write(json.dumps(rec) + "\n")
            # Preserve the id horizon across compaction: recycled request
            # ids would break done-mark bookkeeping after recovery. A
            # dedicated record type — a "done" mark here would falsely
            # complete max_id if it is itself still pending.
            f.write(json.dumps({"op": "horizon", "id": self._max_id}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        old = self._wal
        os.replace(tmp, self._wal_path)
        self._fsync_root()
        self._wal = open(self._wal_path, "a", encoding="utf-8")
        try:
            old.close()
        except OSError:
            pass
        self._appends = 0
        # Every done mark is now durable (the compacted file simply has
        # no pending mark for those ids), so the sweep below may reclaim
        # the whole backlog.
        self._reclaimable.clear()
        # Payload GC: sweep files neither the live pending set nor an
        # in-flight submit references (failed-submit leftovers, done
        # payloads, pre-mark crash orphans) — disk stays bounded like
        # the WAL. Payload reclamation for completed requests happens
        # HERE, not in record_done: the mark is un-fsynced there, and an
        # unlink whose directory metadata beats the page-cached mark to
        # disk would turn "one extra replay" into a falsely-LOST request.
        keep = set()
        for rid in self._pending | self._writing:
            keep.add(f"req_{rid}.npy")
            keep.add(f"req_{rid}.npy.tmp")
        for name in os.listdir(self.root):
            if (
                name.startswith("req_")
                and name.endswith((".npy", ".npy.tmp"))
                and name not in keep
            ):
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass

    def compact(self) -> None:
        with self._lock:
            self._compact_locked()

    def record_worker(
        self, worker_id: str, host: str, port: int, meta: dict | None = None
    ) -> None:
        """Durable worker-pool entry (the reference's ``/workers/<ip>``
        etcd key). Latest record per id wins on load."""
        self._append(
            {
                "op": "worker",
                "id": worker_id,
                "host": host,
                "port": port,
                "meta": meta or {},
            }
        )

    def forget_worker(self, worker_id: str) -> None:
        """Remove a worker from the durable pool — called when recovery
        finds its address dead (and available for administrative
        decommission). NOT lease expiry: a transiently-dead worker should
        survive a dispatcher restart; re-attaching re-journals it."""
        self._append({"op": "worker_gone", "id": worker_id})

    def _payload_path(self, request_id: int) -> str:
        return os.path.join(self.root, f"req_{request_id}.npy")

    def record_submit(
        self, request_id: int, payload: Any, meta: dict | None = None
    ) -> None:
        """Payload first (atomic rename), THEN the submit mark: the WAL
        never references bytes that aren't durably there. The id is
        reserved against the compaction sweep for the whole window where
        the payload exists without its mark. ``meta`` (a JSON-able
        dict — sampling knobs, step counts) rides on the submit mark so
        a replayed request can be reconstructed from the journal alone
        (:meth:`submit_meta` / :meth:`read_payload` — the elastic-
        recovery replay path in ``runtime/continuous``)."""
        with self._lock:
            self._writing.add(request_id)
        try:
            path = self._payload_path(request_id)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                np.save(f, np.asarray(payload), allow_pickle=False)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._fsync_root()  # the rename must survive a host crash
            rec = {"op": "submit", "id": request_id}
            if meta is not None:
                rec["meta"] = meta
            self._append(rec)
        finally:
            with self._lock:
                self._writing.discard(request_id)

    def submit_meta(self, request_id: int) -> dict | None:
        """The ``meta`` dict journaled with a still-pending submit mark
        (None once done-marked, or when none was attached)."""
        with self._lock:
            meta = self._submit_meta.get(request_id)
            return dict(meta) if meta is not None else None

    def pending_ids(self) -> set[int]:
        """Ids submitted but never done-marked — what a recovery would
        replay, and the forensics assembler's "still pending" bit
        (``utils.telemetry.assemble_request``)."""
        with self._lock:
            return set(self._pending)

    def read_payload(self, request_id: int) -> np.ndarray:
        """Load one pending request's journaled payload (the replay
        source — raises ``OSError`` if the payload is gone)."""
        return np.load(self._payload_path(request_id), allow_pickle=False)

    #: Group-commit width for payload reclaim: one fsync per this many
    #: completions, then their payloads unlink in a batch.
    RECLAIM_EVERY = 64

    def record_done(self, request_id: int) -> None:
        # No per-mark fsync: a done mark lost to the page cache costs
        # exactly one extra replay (the documented at-least-once
        # window), and the mark rides the hot completion path — fsyncing
        # each would cap throughput at disk latency for zero added
        # guarantee. The payload is NOT unlinked inline (an unlink whose
        # directory metadata beat the page-cached mark to disk would
        # make a completed request look LOST on recovery); instead,
        # every RECLAIM_EVERY completions pay ONE fsync and then unlink
        # that whole batch — their marks are durable first.
        self._append({"op": "done", "id": request_id}, fsync=False)
        batch: list[int] = []
        with self._lock:
            self._reclaimable.append(request_id)
            if len(self._reclaimable) >= self.RECLAIM_EVERY:
                try:
                    os.fsync(self._wal.fileno())
                except (OSError, ValueError):
                    return  # keep the batch; try again next time
                batch, self._reclaimable = self._reclaimable, []
        for rid in batch:
            try:
                os.unlink(self._payload_path(rid))
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            try:
                self._wal.close()
            except OSError:
                pass

    # -- read side (recovery) ------------------------------------------------

    def load(self) -> tuple[dict[str, dict], dict[int, np.ndarray], int]:
        """Recovery snapshot: ``(workers, pending, next_request_id)``
        where ``workers`` maps worker_id -> {host, port, meta} (latest
        record wins), ``pending`` maps request_id -> payload for every
        submit without a done mark, and ``next_request_id`` is one past
        the highest id ever journaled (the recovered dispatcher's counter
        seed). A pending mark whose payload is unreadable is marked done
        (it cannot ever be replayed — rescanning it forever would only
        re-log the same loss) and reported loudly. Compacts the WAL as a
        side effect: recovery is the natural history-truncation point."""
        with self._lock:
            workers = {k: dict(v) for k, v in self._workers.items()}
            pending_ids = sorted(self._pending)
            next_id = self._max_id + 1
        pending: dict[int, np.ndarray] = {}
        for rid in pending_ids:
            path = self._payload_path(rid)
            try:
                pending[rid] = np.load(path, allow_pickle=False)
            except OSError as e:
                log.error(
                    "journal: request %d has a submit mark but no "
                    "readable payload (%s); it is LOST and marked done",
                    rid,
                    e,
                )
                self.record_done(rid)
        self.compact()
        return workers, pending, next_id
