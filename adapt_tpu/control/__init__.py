from adapt_tpu.control.dispatcher import Dispatcher, RequestFailed
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.control.worker import StageWorker, WorkerState

__all__ = [
    "Dispatcher",
    "RequestFailed",
    "WorkerRegistry",
    "StageWorker",
    "WorkerState",
]
