from adapt_tpu.control.dispatcher import Dispatcher, RequestFailed
from adapt_tpu.control.journal import DispatcherJournal
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.control.worker import StageWorker, WorkerState

__all__ = [
    "Dispatcher",
    "DispatcherJournal",
    "RequestFailed",
    "WorkerRegistry",
    "StageWorker",
    "WorkerState",
]
