"""Adaptive dispatcher: the Gen-2 hub-and-spoke control loop, completed.

This is the working re-expression of the reference's *intended* design —
the five lost methods of ``/root/reference/src/dispatcher.py`` rebuilt on a
device mesh (SURVEY.md §0, §2.6-2.7):

- ``_worker_monitor``       -> registry watch callbacks (:276)
- ``_get_available_workers``-> ``WorkerRegistry.alive()`` (:285)
- ``_intermediate_result_server`` -> ``_result_loop`` draining the result
  queue every worker posts to (:298; fragment :121-161)
- ``_task_watchdog``        -> ``_watchdog_loop`` over the in-flight
  registry (:303)
- ``_acquire_and_configure_worker`` -> ``_acquire`` + lazy
  ``StageWorker.configure`` (:178; config handshake :223-264)

Semantics beyond the reference (SURVEY.md §7.4): requests carry ids and
attempt counters, so watchdog re-dispatch plus a late-completing original
worker cannot duplicate or drop a request (the reference could do both).
"""

from __future__ import annotations

import itertools
import os
import queue
import random
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any

import jax

from adapt_tpu.config import ObservabilityConfig, ServeConfig
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.control.worker import (
    PING_STAGE,
    StageWorker,
    Task,
    TaskResult,
    WorkerState,
)
from adapt_tpu.graph.partition import PartitionPlan
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.profiling import (
    aggregate_size_fn,
    global_compile_sentinel,
    global_engine_obs,
)
from adapt_tpu.utils.tracing import global_flight_recorder, global_tracer

log = get_logger("dispatcher")

#: Live dispatchers (weak): per-stage compile watches SUM across them
#: (profiling.aggregate_size_fn) — a second dispatcher must not
#: silently unwatch the first.
_LIVE_DISPATCHERS: "weakref.WeakSet[Dispatcher]" = weakref.WeakSet()


class RequestFailed(RuntimeError):
    """A request exhausted its retries (or no workers remain)."""


class PipelineFuture:
    """Completion handle for one submitted request."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.submit_time = time.monotonic()
        self._event = threading.Event()
        self._value: Any = None
        self._error: str | None = None

    def _complete(self, value: Any = None, error: str | None = None) -> bool:
        if self._event.is_set():
            return False  # exactly-once: late duplicates dropped
        self._value, self._error = value, error
        self._event.set()
        return True

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not done within {timeout}s"
            )
        if self._error is not None:
            raise RequestFailed(self._error)
        return self._value


@dataclass
class _Inflight:
    """Reference in-flight entry: ``{(worker_ip, partition_idx):
    {partition, data, start_time}}`` with the raw payload retained for
    re-send (``src/dispatcher.py:186-194``) — keyed here by request id,
    extended with attempt/retry counters for exactly-once."""

    request_id: int
    stage_index: int
    attempt: int
    payload: Any
    worker_id: str
    start_time: float
    retries: int = 0
    future: PipelineFuture = field(default=None)  # type: ignore[assignment]
    # Workers that already failed/stalled this request: re-dispatch excludes
    # ALL of them, not just the latest (a pool with several hung workers
    # must not bounce one request among them until retries burn out).
    tried: set[str] = field(default_factory=set)
    #: Chain mode (comm.remote direct forwarding): the stage index whose
    #: result completes this request. None = hub routing (the entry's own
    #: stage). A chain entry holds the ORIGINAL stage-0 payload, so any
    #: chain failure re-dispatches end-to-end through the hub path.
    final_stage: int | None = None


class Dispatcher:
    """Hub dispatcher over in-process stage workers."""

    def __init__(
        self,
        plan: PartitionPlan,
        variables,
        registry: WorkerRegistry | None = None,
        config: ServeConfig | None = None,
        journal=None,
    ):
        """``journal`` — optional :class:`~adapt_tpu.control.journal.
        DispatcherJournal`: accepted requests and the dial-out worker
        table survive a dispatcher crash, and :meth:`recover` rebuilds a
        serving dispatcher from them (the reference's
        etcd-outlives-the-dispatcher property, ``src/start_etcd.sh:81-94``,
        re-scoped per SURVEY §7.5). Journaling an accepted request costs
        one host fetch + fsync per submit."""
        self.plan = plan
        self.config = config or ServeConfig()
        self._journal = journal
        # Push the observability knobs onto the process-global tracer /
        # flight recorder. Both are apply-only-when-opinionated: tracing
        # switched on by env (ADAPT_TPU_TRACE) or another component
        # stays on, and a DEFAULT capacity never clobbers a ring another
        # component explicitly sized (a second default-config dispatcher
        # in-process must not truncate the first one's history).
        obs = self.config.obs
        if obs.trace_enabled:
            global_tracer().enabled = True
        _obs_defaults = ObservabilityConfig()
        if obs.trace_capacity != _obs_defaults.trace_capacity:
            global_tracer().set_capacity(obs.trace_capacity)
        if obs.flight_capacity != _obs_defaults.flight_capacity:
            global_flight_recorder().set_capacity(obs.flight_capacity)
        # Engine-tier knobs ride the same apply-only-when-opinionated
        # rules: obs_engine is enable-only, compile_warmup applies only
        # when non-default (utils.profiling).
        if obs.obs_engine:
            global_engine_obs().enabled = True
        if obs.compile_warmup != _obs_defaults.compile_warmup:
            global_compile_sentinel().warmup_samples = obs.compile_warmup
        self.registry = registry or WorkerRegistry(
            default_ttl_s=self.config.fault.lease_ttl_s
        )
        # One shared jitted fn per stage: jit caches executables per device,
        # so configuring the same stage on another same-kind device reuses
        # the compiled program (recovery = weight move, not recompile).
        self._stage_fns = [
            jax.jit(plan.stage_apply(spec)) for spec in plan.stages
        ]
        # Compile-sentinel watch on the stage programs: a failover
        # re-bind is supposed to be a weight move, never a recompile —
        # the sentinel turns a violation into a counted, logged event.
        # Watches sum over the weakly-held live-dispatcher set (two
        # concurrent dispatchers aggregate, neither is silently
        # unwatched; a collected dispatcher's stages drop out).
        _LIVE_DISPATCHERS.add(self)
        for i in range(len(self._stage_fns)):
            global_compile_sentinel().register(
                f"dispatch.stage{i}",
                size_fn=aggregate_size_fn(
                    _LIVE_DISPATCHERS,
                    lambda d, i=i: (
                        d._stage_fns[i]._cache_size()
                        if i < len(d._stage_fns) else None
                    ),
                ),
            )
        self._stage_host_vars = plan.extract_variables(variables)
        # Precompiled re-shard plans (SURVEY.md §7.2.5): example input spec
        # per stage (recorded on first dispatch) + the set of (stage,
        # device) pairs whose executable is already in the jit cache.
        # Prewarming every pair during warmup means a failover re-bind is a
        # weight move, not an XLA recompile — the <2 s recovery budget.
        self._stage_examples: dict[int, jax.ShapeDtypeStruct] = {}
        self._prewarmed: set[tuple[int, Any]] = set()
        self._prewarm_lock = threading.Lock()
        self._prewarm_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="dispatcher-prewarm"
        )
        self._workers: dict[str, StageWorker] = {}
        self._workers_lock = threading.Lock()
        self.result_queue: queue.Queue[TaskResult] = queue.Queue()
        self._inflight: dict[int, _Inflight] = {}
        self._inflight_lock = threading.Lock()
        self._sem = threading.Semaphore(self.config.max_inflight)
        # Over a pre-existing journal, start past every id it has seen:
        # a fresh counter would recycle journaled ids — overwriting a
        # crashed request's payload and clearing its pending mark with
        # the new request's done.
        self._req_ids = itertools.count(
            journal.next_request_id if journal is not None else 0
        )
        self._watchdog_paused = False
        # Strike-based quarantine: a worker that keeps missing task
        # deadlines while heartbeating (a hang) is never evicted by lease
        # expiry; after `quarantine_strikes` deadline misses the scheduler
        # stops acquiring it (the reference's socket-error eviction,
        # src/dispatcher.py:153-161, generalized to hangs).
        #
        # How strikes accrue once rank demotes a struck worker (and real
        # traffic stops reaching it): the watchdog sends canary *probe*
        # tasks (PING_STAGE) to any alive worker that has been silent
        # beyond the probe window; a probe that misses the task deadline is
        # a strike like any other. Probes also self-heal: an answered probe
        # forgives probe-miss strikes (and, under quarantine, slowly decays
        # real-task strikes), so a recovered worker returns to service.
        #
        # _health_lock guards all four maps below — they are touched from
        # the result loop, the watchdog, and the forward pool concurrently.
        self._health_lock = threading.Lock()
        self._strikes: dict[str, int] = {}
        # Of those, the strikes earned by probe misses: an answered probe
        # forgives only these — a ping proves the exec loop drains, not
        # that the worker completes real tasks in time, so real-task
        # deadline strikes persist until a timely real completion.
        self._probe_strikes: dict[str, int] = {}
        self._quarantined: set[str] = set()
        # worker_id -> monotonic time of its last completed task or probe.
        self._last_ok: dict[str, float] = {}
        # worker_id -> (probe request_id, send time) for in-flight probes.
        self._probes: dict[str, tuple[int, float]] = {}
        # worker_id -> most recent probe id ever sent: only the *latest*
        # probe's answer earns forgiveness/decay, so a long-hung worker's
        # backlog of queued pings cannot, on revive, replay as a burst
        # that drains accumulated real-task strikes in one tick.
        self._last_probe_id: dict[str, int] = {}
        self._probe_ids = itertools.count(-2, -1)  # never a request id
        self._boot_time = time.monotonic()
        # Tie-break shuffle runs on forward-pool threads concurrently and
        # random.Random is not thread-safe -> one RNG per thread.
        self._tls = threading.local()
        self._rng_seeds = itertools.count(0x5EED)
        # Forward/re-dispatch pool: _acquire can block on a weight transfer
        # (configure), which must never stall the result loop or the
        # registry reaper (the reference likewise forwards in spawned
        # threads, src/dispatcher.py:137-144).
        self._forward_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="dispatcher-forward"
        )
        self._shutdown = threading.Event()
        self._threads: list[threading.Thread] = []
        self._started = False
        #: Chain forwarding (opt-in, setup_chain): ordered worker ids, one
        #: per stage; data hops worker→worker, only the tail's result (and
        #: any error) returns to the hub. None = hub routing.
        self._chain: list[str] | None = None
        self._chain_lock = threading.Lock()

    # -- worker pool --------------------------------------------------------

    def spawn_workers(self, devices) -> list[StageWorker]:
        """One in-process worker per device (single-host mode: TPU chips as
        the reference's 'machines' — its localhost mode, SURVEY.md §4)."""
        workers = []
        for i, dev in enumerate(devices):
            w = StageWorker(
                worker_id=f"worker-{i}",
                device=dev,
                registry=self.registry,
                result_queue=self.result_queue,
                fault=self.config.fault,
            )
            self.attach_worker(w)
            workers.append(w)
        return workers

    def attach_worker(self, worker: StageWorker) -> None:
        with self._workers_lock:
            self._workers[worker.worker_id] = worker
        # Dial-out remote proxies are re-adoptable after a dispatcher
        # crash (their server keeps listening); journal their address +
        # configure recipe. In-process workers die with this process and
        # gateway joiners redial on their own, so neither is journaled.
        if self._journal is not None:
            addr = getattr(worker, "chain_address", None)
            if addr is not None:
                self._journal.record_worker(
                    worker.worker_id,
                    addr[0],
                    addr[1],
                    meta={
                        "model_config": worker._model_config,
                        "codec": worker._codec_name,
                        "weights_codec": worker._wcodec.name,
                    },
                )

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Dispatcher":
        if self._started:
            return self
        self.registry.start()
        with self._workers_lock:
            workers = list(self._workers.values())
        for w in workers:
            w.start()
        if not self.registry.wait_for_workers(
            1, self.config.fault.startup_wait_s
        ):
            # Reference: clean shutdown when no worker appears in 5 s
            # (src/dispatcher.py:290-295).
            self.shutdown()
            raise RequestFailed(
                f"no workers registered within "
                f"{self.config.fault.startup_wait_s}s"
            )
        self.registry.watch(self._on_membership)
        for name, target in (
            ("results", self._result_loop),
            ("watchdog", self._watchdog_loop),
        ):
            t = threading.Thread(
                target=target, name=f"dispatcher-{name}", daemon=True
            )
            t.start()
            self._threads.append(t)
        self._started = True
        return self

    def hard_stop(self) -> None:
        """Crash simulation (tests / chaos drills): abandon everything
        NOW — no draining, no future completion, no journal marks, no
        deregistration. The process state a SIGKILL leaves behind, minus
        the process exit. Worker processes keep running and listening;
        :meth:`recover` is the other half."""
        # Detach the journal FIRST: in-flight forward/result threads
        # erroring on the closed sockets must not write done marks a real
        # SIGKILL could never write (each would silently shrink the
        # recovery replay set).
        self._journal = None
        self._shutdown.set()
        self.result_queue.put(None)  # type: ignore[arg-type]
        with self._workers_lock:
            workers = list(self._workers.values())
        for w in workers:
            sock = getattr(w, "_sock", None)
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        self._forward_pool.shutdown(wait=False, cancel_futures=True)
        self._prewarm_pool.shutdown(wait=False, cancel_futures=True)
        self.registry.stop()

    @classmethod
    def recover(
        cls,
        plan: PartitionPlan,
        variables,
        journal,
        config: ServeConfig | None = None,
    ) -> tuple["Dispatcher", dict[int, PipelineFuture]]:
        """Rebuild a serving dispatcher from a crashed one's journal.

        Re-adopts every journaled dial-out worker whose server still
        answers (unreachable addresses are skipped with a warning — the
        pool heals, it doesn't block), seeds the request-id counter past
        every journaled id, and REPLAYS each submitted-but-never-completed
        request from its retained payload. Returns ``(dispatcher,
        {request_id: future})`` for the replayed requests — each completes
        exactly once. Replay respects ``max_inflight`` and so may block
        admitting the tail of a large backlog while the head completes.

        Model/weights come from ``plan``/``variables`` (the operator's
        checkpoint, ``utils/checkpoint.py``) — the journal owns
        control-plane state only."""
        from adapt_tpu.comm.remote import RemoteWorkerProxy

        workers, pending, _ = journal.load()
        # cls() seeds the request-id counter from the journal's horizon.
        disp = cls(plan, variables, config=config, journal=journal)
        attached = 0
        proxies = []
        for worker_id, info in workers.items():
            meta = info.get("meta", {})
            proxy = RemoteWorkerProxy(
                worker_id,
                (info["host"], info["port"]),
                disp.registry,
                disp.result_queue,
                model_config=meta.get("model_config", {}),
                codec_name=meta.get("codec", "none"),
                weights_codec=meta.get("weights_codec", "lz"),
                fault=disp.config.fault,
            )
            disp.attach_worker(proxy)
            proxies.append(proxy)
            attached += 1
        if not attached:
            raise RequestFailed(
                "journal holds no re-adoptable workers; nothing to recover"
            )
        # start() dials every attached proxy; a dead address raises from
        # its start() — dial here instead, CONCURRENTLY (a pool with dead
        # addresses must not serialize startup_wait_s stalls), pruning
        # the unreachable from the journal so they never stall another
        # recovery (re-attaching a revived worker re-journals it).
        with disp._workers_lock:
            disp._workers.clear()
        alive = []

        def _dial(proxy):
            try:
                proxy.start()
                return proxy, None
            except Exception as e:  # noqa: BLE001
                return proxy, e

        with ThreadPoolExecutor(
            max_workers=min(16, max(1, len(proxies))),
            thread_name_prefix="recover-dial",
        ) as pool:
            for proxy, err in pool.map(_dial, proxies):
                if err is not None:
                    log.warning(
                        "recovery: worker %s at %s not re-adoptable (%s); "
                        "pruned from the journal",
                        proxy.worker_id,
                        proxy.address,
                        err,
                    )
                    journal.forget_worker(proxy.worker_id)
                    continue
                with disp._workers_lock:
                    disp._workers[proxy.worker_id] = proxy
                alive.append(proxy)
        if not alive:
            raise RequestFailed(
                "no journaled worker answered; cannot recover the pool"
            )
        disp.start()
        futures: dict[int, PipelineFuture] = {}
        for rid, payload in pending.items():
            disp._sem.acquire()
            future = PipelineFuture(rid)
            futures[rid] = future
            try:
                disp._dispatch(rid, 0, payload, future, attempt=0, retries=0)
            except Exception as e:  # noqa: BLE001
                disp._finish(future, error=str(e))
        log.info(
            "recovered: %d workers re-adopted, %d requests replayed",
            len(alive),
            len(futures),
        )
        global_metrics().inc("dispatcher.recovered", 1)
        recorder = global_flight_recorder()
        recorder.record(
            "recovery", workers=len(alive), replayed=len(futures)
        )
        if disp.config.obs.snapshot_on_recovery:
            # Post-mortem artifact: the fault timeline that preceded the
            # crash/recovery, dumped beside the journal so it outlives
            # the ring (and the process).
            try:
                path = os.path.join(
                    journal.root, f"flight-{int(time.time())}.json"
                )
                recorder.snapshot_to(path)
                log.info("flight-recorder snapshot: %s", path)
            except Exception as e:  # noqa: BLE001 — best-effort: a
                # failed post-mortem dump must not abort a recovery
                # whose dispatcher and replayed futures are already live.
                log.warning("flight-recorder snapshot failed: %s", e)
        return disp, futures

    def shutdown(self) -> None:
        self._shutdown.set()
        self.result_queue.put(None)  # type: ignore[arg-type]
        for t in self._threads:
            t.join(timeout=2.0)
        # Fail outstanding futures promptly instead of letting callers
        # sleep out their timeouts.
        self._forward_pool.shutdown(wait=False, cancel_futures=True)
        self._prewarm_pool.shutdown(wait=False, cancel_futures=True)
        with self._inflight_lock:
            abandoned = list(self._inflight.values())
            self._inflight.clear()
        for e in abandoned:
            self._finish(e.future, error="dispatcher shut down")
        with self._workers_lock:
            workers = list(self._workers.values())
        for w in workers:
            w.stop()
        self.registry.stop()
        if self._journal is not None:
            self._journal.close()

    # -- request API --------------------------------------------------------

    def submit(self, x) -> PipelineFuture:
        """Enqueue one request into the pipeline (reference input pump,
        ``src/dispatcher.py:99-107``); bounded by the concurrency
        semaphore (``:151,183``)."""
        if self._shutdown.is_set():
            raise RequestFailed("dispatcher is shut down")
        self._sem.acquire()
        request_id = next(self._req_ids)
        future = PipelineFuture(request_id)
        if self._journal is not None:
            # Write-ahead, strictly before dispatch: with journaling on,
            # an accepted request must be recoverable — a submit the
            # journal can't record is refused, not silently volatile.
            try:
                self._journal.record_submit(request_id, x)
            except Exception as e:
                self._sem.release()
                raise RequestFailed(f"journal write failed: {e}") from e
        try:
            self._dispatch(request_id, 0, x, future, attempt=0, retries=0)
        except Exception as e:  # no worker at all -> fail fast
            self._finish(future, error=str(e))
        return future

    def infer(self, x, timeout: float | None = 60.0) -> Any:
        return self.submit(x).result(timeout)

    def warmup(self, example, timeout: float | None = 300.0) -> None:
        """Run one request end-to-end with the watchdog paused, so
        first-compile time (tens of seconds on TPU) is paid here instead of
        triggering spurious re-dispatches in serving. Then prewarm every
        (stage, device) executable so failover never recompiles."""
        self._watchdog_paused = True
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            self.infer(example, timeout)
            self.prewarm_executables(wait=True, deadline=deadline)
        finally:
            self._watchdog_paused = False

    # -- precompiled re-shard plans -----------------------------------------

    def prewarm_executables(
        self, wait: bool = False, deadline: float | None = None
    ) -> None:
        """Seed the shared jit cache with every (stage, live-worker-device)
        executable, using each stage's recorded example input spec. The jit
        cache keys on avals/shardings, not values, so compilation uses
        device-created zero weights — no weight transfer, no lasting HBM
        cost. With ``wait=True`` blocks until all pairs are compiled (or
        ``deadline``, monotonic seconds, passes — best effort)."""
        if self._shutdown.is_set():
            return
        with self._workers_lock:
            # Remote proxies carry no local device (their server compiles
            # its own stage programs); prewarm only covers in-process
            # workers' devices.
            devices = {
                w.device
                for w in self._workers.values()
                if w.state is not WorkerState.DEAD
                and getattr(w, "device", None) is not None
            }
        with self._prewarm_lock:
            examples = dict(self._stage_examples)
        futures = []
        for stage_index, spec in examples.items():
            for dev in devices:
                with self._prewarm_lock:
                    if (stage_index, dev) in self._prewarmed:
                        continue
                    self._prewarmed.add((stage_index, dev))
                try:
                    futures.append(
                        self._prewarm_pool.submit(
                            self._prewarm_one, stage_index, dev, spec
                        )
                    )
                except RuntimeError:  # pool shut down concurrently
                    with self._prewarm_lock:
                        self._prewarmed.discard((stage_index, dev))
                    return
        if wait:
            for f in futures:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    log.warning(
                        "prewarm deadline passed with compiles outstanding; "
                        "continuing in background"
                    )
                    break
                try:
                    f.result(timeout=remaining)
                except TimeoutError:
                    log.warning(
                        "prewarm deadline passed with compiles outstanding; "
                        "continuing in background"
                    )
                    break

    def _prewarm_one(self, stage_index: int, device, spec) -> None:
        try:
            # Zero-valued weights created directly on the target device:
            # compiles the identical executable (cache keys are avals +
            # shardings) without moving the real weights. The device_put
            # commits the already-on-device arrays — committed and
            # uncommitted args key DIFFERENT cache entries, and serving
            # calls use committed (device_put) arrays.
            with jax.default_device(device):
                variables = jax.tree.map(
                    lambda a: jax.numpy.zeros(a.shape, a.dtype),
                    self._stage_host_vars[stage_index],
                )
                x = jax.numpy.zeros(spec.shape, spec.dtype)
            variables = jax.device_put(variables, device)
            x = jax.device_put(x, device)
            jax.block_until_ready(self._stage_fns[stage_index](variables, x))
            global_metrics().inc("dispatcher.prewarmed")
        except Exception as e:  # noqa: BLE001 — prewarm is best-effort
            with self._prewarm_lock:
                self._prewarmed.discard((stage_index, device))
            log.warning(
                "prewarm of stage %d on %s failed: %s", stage_index, device, e
            )

    def serve_stream(self, inputs, timeout_per_request: float = 120.0):
        """Pump a stream through the pipeline, preserving order (reference
        driver semantics, ``test/test.py:48-50``)."""
        futures = [self.submit(x) for x in inputs]
        return [f.result(timeout_per_request) for f in futures]

    def metrics_snapshot(self) -> dict:
        return global_metrics().snapshot()

    # -- chain forwarding (opt-in data-plane topology) -----------------------

    def setup_chain(self, worker_ids: list[str] | None = None) -> list[str]:
        """Opt-in direct worker→worker forwarding for a static healthy
        pool: stage ``i``'s output hops straight to stage ``i+1``'s worker
        (reference Gen-1 topology, ``/root/reference/src/node.py:163-179``)
        and only the tail's result returns to the hub — halving the DCN
        hops of hub routing (SURVEY §3.2's 2·S critique). The hub keeps
        the whole control plane: probes, deadlines, exactly-once and
        re-dispatch are unchanged, and ANY chain failure (error frame,
        deadline, member death) disables the chain and replays the
        request end-to-end through the proven late-binding hub path —
        the in-flight entry retains the original stage-0 payload.

        ``worker_ids``: one per stage, in stage order. Default: the
        dial-out remote proxies in attach order. Members must be
        ``RemoteWorkerProxy``-shaped (send_route) and every non-head
        member must be dialable by its predecessor (``chain_address``)."""
        with self._workers_lock:
            pool = {
                wid: w
                for wid, w in self._workers.items()
                if w.state is not WorkerState.DEAD
            }
        if worker_ids is None:
            worker_ids = [
                wid
                for wid, w in pool.items()
                if getattr(w, "chain_address", None) is not None
            ][: self.plan.num_stages]
        if len(worker_ids) != self.plan.num_stages:
            raise ValueError(
                f"chain needs exactly {self.plan.num_stages} workers "
                f"(one per stage), got {len(worker_ids)}"
            )
        workers = []
        for i, wid in enumerate(worker_ids):
            w = pool.get(wid)
            if w is None:
                raise ValueError(f"worker {wid!r} is not in the live pool")
            if not hasattr(w, "send_route"):
                raise TypeError(
                    f"worker {wid!r} cannot chain (in-process workers "
                    "share the hub's memory; chaining is a cross-host "
                    "topology)"
                )
            if i > 0 and w.chain_address is None:
                raise ValueError(
                    f"worker {wid!r} has no dialable listen address "
                    "(gateway joiners don't announce one)"
                )
            workers.append(w)
        for i, w in enumerate(workers):
            if not w.is_configured(i):
                self._configure_with_timeout(w, i)
        # Tail-first: no hop ever forwards into a worker missing its route.
        for i in reversed(range(len(workers))):
            if i + 1 < len(workers):
                workers[i].send_route(i, workers[i + 1].chain_address, i + 1)
            else:
                workers[i].send_route(i, None)
        with self._chain_lock:
            self._chain = list(worker_ids)
        log.info("chain forwarding enabled: %s", " -> ".join(worker_ids))
        global_metrics().inc("dispatcher.chain_enabled")
        return list(worker_ids)

    def disable_chain(self, reason: str = "requested") -> None:
        """Back to hub routing. Route clears are best-effort and async —
        correctness doesn't need them: hub traffic uses plain MSG_DATA,
        which ignores any stale route left on an unreachable worker."""
        with self._chain_lock:
            chain, self._chain = self._chain, None
        if chain is None:
            return
        log.warning(
            "chain forwarding disabled (%s); hub routing resumes", reason
        )
        global_metrics().inc("dispatcher.chain_disabled")
        global_flight_recorder().record("chain_disabled", reason=reason)
        with self._workers_lock:
            pool = dict(self._workers)

        def _clear(stage: int, worker) -> None:
            try:
                worker.send_route(stage, None, clear=True)
            except Exception:  # noqa: BLE001 — link may be down/dead
                pass

        for i, wid in enumerate(chain):
            w = pool.get(wid)
            if w is not None and hasattr(w, "send_route"):
                try:
                    self._forward_pool.submit(_clear, i, w)
                except RuntimeError:  # pool shut down
                    break

    # -- scheduling ---------------------------------------------------------

    def _acquire(self, stage_index: int, exclude: set[str]) -> StageWorker:
        """Late binding: pick a live worker for this stage *now* (reference
        ``_acquire_and_configure_worker``, call site
        ``src/dispatcher.py:178``). Preference: already-configured idle >
        idle > shallowest queue; excluded (suspect) workers only as a last
        resort."""
        # Role-tagged leases partition the pool: a worker registered
        # under a dedicated role (the disaggregated serving tier's
        # role="prefill" pool, runtime/disagg) must never be acquired
        # for pipeline stages — its capacity is spoken for. Untagged
        # leases (every pre-role registration) stay fully schedulable.
        # One registry lock hold (alive_untagged), not one per worker.
        alive = set(self.registry.alive_untagged())
        with self._workers_lock:
            pool = [
                w
                for wid, w in self._workers.items()
                if wid in alive and w.state is not WorkerState.DEAD
            ]
        if not pool:
            raise RequestFailed("no live workers")
        with self._health_lock:
            strikes = dict(self._strikes)
            quarantined = set(self._quarantined)
        # Preference cascade: healthy & untried > quarantined & untried
        # (quarantine is a soft signal; a worker this request hasn't tried
        # yet still beats re-picking one that just failed it) > anyone.
        healthy = [w for w in pool if w.worker_id not in quarantined]
        candidates = (
            [w for w in healthy if w.worker_id not in exclude]
            or [w for w in pool if w.worker_id not in exclude]
            or healthy
            or pool
        )

        def rank(w: StageWorker):
            return (
                # Any missed deadline (even below the quarantine threshold)
                # demotes a worker: a hung worker looks perfectly idle and
                # configured — the most attractive rank — so strike
                # feedback must outweigh attractiveness. Workers with NO
                # strikes stay fully schedulable (the watchdog's canary
                # probes, not scheduling starvation, are what detect a
                # silent hang — see _watchdog_loop).
                1 if strikes.get(w.worker_id, 0) else 0,
                0 if w.is_configured(stage_index) else 1,
                0 if w.state is WorkerState.IDLE else 1,
                w.queue_depth,
            )

        # Random tie-break: concurrent re-dispatch waves must scatter over
        # equal-rank candidates, not herd onto one deterministic victim
        # (which would burn one deadline per worker, serially).
        self._shuffle(candidates)
        last_error: Exception | None = None
        for worker in sorted(candidates, key=rank):
            if worker.is_configured(stage_index):
                return worker
            try:
                self._configure_with_timeout(worker, stage_index)
                return worker
            except Exception as e:  # noqa: BLE001 — try the next candidate
                log.warning(
                    "configure of stage %d on %s failed: %s",
                    stage_index,
                    worker.worker_id,
                    e,
                )
                last_error = e
        raise RequestFailed(
            f"no worker could be configured for stage {stage_index}: "
            f"{last_error}"
        )

    def _shuffle(self, seq: list) -> None:
        rng = getattr(self._tls, "rng", None)
        if rng is None:
            rng = self._tls.rng = random.Random(next(self._rng_seeds))
        rng.shuffle(seq)

    def _configure_with_timeout(
        self, worker: StageWorker, stage_index: int
    ) -> None:
        """Bounded config handshake (reference ACK timeout,
        ``src/dispatcher.py:246-260``). On timeout the worker thread is
        abandoned but *cancelled*: the ``abort`` token is checked by the
        worker immediately before installing the binding, so a timed-out
        configure can never install state (or pin weight HBM) after this
        dispatcher has declared it failed and moved on."""
        done = threading.Event()
        abandoned = threading.Event()
        errors: list[Exception] = []

        def _cfg():
            try:
                gen = worker.configure(
                    stage_index,
                    self._stage_fns[stage_index],
                    self._stage_host_vars[stage_index],
                    spec=self.plan.stages[stage_index],
                    abort=abandoned.is_set,
                )
                if abandoned.is_set():
                    # Install won the race with the timeout decision by a
                    # hair: undo it so no binding (or pinned weights)
                    # survives a configure the dispatcher reported failed.
                    # Gen-scoped: a newer configure's binding survives.
                    worker.unconfigure(stage_index, gen)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                done.set()

        t = threading.Thread(target=_cfg, daemon=True)
        t.start()
        if not done.wait(self.config.fault.configure_timeout_s):
            abandoned.set()
            raise RequestFailed(
                f"configure of stage {stage_index} on {worker.worker_id} "
                f"timed out after {self.config.fault.configure_timeout_s}s"
            )
        if errors:
            raise errors[0]

    def _dispatch(
        self,
        request_id: int,
        stage_index: int,
        payload,
        future: PipelineFuture,
        attempt: int,
        retries: int,
        exclude: set[str] | None = None,
    ) -> None:
        if stage_index not in self._stage_examples:
            try:
                spec = jax.ShapeDtypeStruct(
                    jax.numpy.shape(payload), payload.dtype
                )
                with self._prewarm_lock:
                    self._stage_examples[stage_index] = spec
            except Exception:  # noqa: BLE001 — non-array payloads: skip
                pass
        exclude = exclude or set()
        with self._chain_lock:
            chain = self._chain
        if (
            chain is not None
            and stage_index == 0
            and retries == 0
            and not exclude
        ):
            # Chain fast path: one submit to the head; the final result
            # arrives from the tail worker's link. Retries/excludes never
            # take it — a failed chain attempt replays through the hub.
            with self._workers_lock:
                head = self._workers.get(chain[0])
            if head is not None and head.state is not WorkerState.DEAD:
                entry = _Inflight(
                    request_id=request_id,
                    stage_index=0,
                    attempt=attempt,
                    payload=payload,
                    worker_id=head.worker_id,
                    start_time=time.monotonic(),
                    retries=retries,
                    future=future,
                    tried={head.worker_id},
                    final_stage=self.plan.num_stages - 1,
                )
                with self._inflight_lock:
                    self._inflight[request_id] = entry
                try:
                    head.submit(
                        Task(
                            request_id=request_id,
                            stage_index=0,
                            attempt=attempt,
                            payload=payload,
                            chained=True,
                        )
                    )
                except Exception as e:  # noqa: BLE001 — link just died
                    with self._inflight_lock:
                        self._inflight.pop(request_id, None)
                    self.disable_chain(f"chain head submit failed: {e}")
                else:
                    global_metrics().inc("dispatcher.tasks_sent")
                    global_metrics().inc("dispatcher.chain_dispatched")
                    return
            else:
                self.disable_chain("chain head worker gone")
        worker = self._acquire(stage_index, exclude)
        entry = _Inflight(
            request_id=request_id,
            stage_index=stage_index,
            attempt=attempt,
            payload=payload,
            worker_id=worker.worker_id,
            start_time=time.monotonic(),
            retries=retries,
            future=future,
            tried=exclude | {worker.worker_id},
        )
        with self._inflight_lock:
            self._inflight[request_id] = entry
        worker.submit(
            Task(
                request_id=request_id,
                stage_index=stage_index,
                attempt=attempt,
                payload=payload,
            )
        )
        global_metrics().inc("dispatcher.tasks_sent")

    def _forward(self, result: TaskResult, entry: _Inflight, next_stage: int) -> None:
        """Forward a stage output to the next stage (runs on the forward
        pool; _acquire may block on a configure)."""
        try:
            self._dispatch(
                result.request_id,
                next_stage,
                result.output,
                entry.future,
                attempt=0,
                retries=0,
            )
        except Exception as e:  # noqa: BLE001
            self._finish(entry.future, error=str(e))

    def _redispatch(self, entry: _Inflight, reason: str) -> None:
        """Watchdog / failure path: re-send the retained payload to a
        different worker (reference watchdog intent, ``src/dispatcher.py:
        302-304`` + §2.7 'late binding'). A chain entry replays from its
        original stage-0 payload through the hub path — the chain (if
        still up) is disabled first, so the retry cannot re-enter the
        topology that just failed it."""
        if entry.final_stage is not None:
            self.disable_chain(f"chain request replay: {reason}")
        if entry.retries + 1 > self.config.fault.max_retries:
            with self._inflight_lock:
                self._inflight.pop(entry.request_id, None)
            global_flight_recorder().record(
                "request_failed",
                request=entry.request_id,
                stage=entry.stage_index,
                retries=entry.retries,
                reason=reason,
            )
            self._finish(
                entry.future,
                error=(
                    f"request {entry.request_id} stage {entry.stage_index} "
                    f"failed after {entry.retries} retries ({reason})"
                ),
            )
            return
        global_metrics().inc("dispatcher.redispatched")
        global_flight_recorder().record(
            "redispatch",
            request=entry.request_id,
            stage=entry.stage_index,
            attempt=entry.attempt + 1,
            worker=entry.worker_id,
            reason=reason,
        )
        log.warning(
            "re-dispatching request %d stage %d (%s), attempt %d",
            entry.request_id,
            entry.stage_index,
            reason,
            entry.attempt + 1,
        )
        try:
            self._dispatch(
                entry.request_id,
                entry.stage_index,
                entry.payload,
                entry.future,
                attempt=entry.attempt + 1,
                retries=entry.retries + 1,
                exclude=entry.tried,  # includes entry.worker_id by construction
            )
        except Exception as e:
            with self._inflight_lock:
                self._inflight.pop(entry.request_id, None)
            self._finish(entry.future, error=str(e))

    def _finish(self, future: PipelineFuture, value=None, error=None) -> None:
        if future._complete(value, error):
            if self._journal is not None:
                # Terminal either way (value OR reported error): replay
                # is for requests that never completed. A crash before
                # this mark replays the request once — the documented
                # at-least-once window.
                try:
                    self._journal.record_done(future.request_id)
                except Exception:  # noqa: BLE001 — worst case: one replay
                    log.warning(
                        "journal done-mark failed for request %d",
                        future.request_id,
                    )
            self._sem.release()
            global_metrics().inc(
                "dispatcher.completed" if error is None else "dispatcher.failed"
            )
            latency = time.monotonic() - future.submit_time
            if error is None:
                global_metrics().observe("request.latency_s", latency)
            tracer = global_tracer()
            if tracer.enabled:
                end = tracer.now()
                tracer.add_span(
                    "request",
                    start=end - latency,
                    end=end,
                    request=future.request_id,
                    ok=error is None,
                )

    # -- loops --------------------------------------------------------------

    def _result_loop(self) -> None:
        """The intermediate-result server (reference fragment
        ``src/dispatcher.py:121-161``): every stage output returns to the
        hub; forward to the next stage or emit the final result."""
        while not self._shutdown.is_set():
            result = self.result_queue.get()
            if result is None:
                break
            if result.stage_index < 0:
                # Probe (canary) answer: proof the exec loop is draining
                # again — even a stale ping from before a re-probe counts.
                # Forgives probe-miss strikes (a lifted hang) but not
                # real-task deadline strikes, and lifts quarantine only if
                # what remains is below the threshold. Ignored entirely if
                # the worker has left membership since (a rejoin under the
                # same id must start with a clean slate).
                wid = result.worker_id
                if wid not in self.registry.alive():
                    global_metrics().inc("dispatcher.probes_ignored")
                    continue
                with self._health_lock:
                    self._last_ok[wid] = time.monotonic()
                    if result.request_id != self._last_probe_id.get(wid):
                        # Stale ping from a revive-burst: liveness proof
                        # (recorded above) but no forgiveness — only the
                        # newest probe's answer absolves, one per
                        # round-trip actually sent.
                        global_metrics().inc("dispatcher.probes_ok")
                        continue
                    self._probes.pop(wid, None)
                    forgiven = self._probe_strikes.pop(wid, 0)
                    remaining = self._strikes.get(wid, 0) - forgiven
                    remaining = max(remaining, 0)
                    if (
                        wid in self._quarantined
                        and remaining >= self.config.fault.quarantine_strikes
                    ):
                        # Quarantine earned from real-task strikes, whose
                        # late results were dropped as stale and so can
                        # never absolve: each answered probe decays one
                        # real strike, so a transiently-stalled worker
                        # works its way back (to demoted-but-available,
                        # not to full trust) instead of being sidelined
                        # forever. Decay only applies under quarantine —
                        # a merely-demoted slow worker must NOT oscillate
                        # back to full rank on probe answers alone.
                        remaining -= 1
                    if remaining > 0:
                        self._strikes[wid] = remaining
                    else:
                        self._strikes.pop(wid, None)
                    if remaining < self.config.fault.quarantine_strikes:
                        self._quarantined.discard(wid)
                global_metrics().inc("dispatcher.probes_ok")
                continue
            with self._inflight_lock:
                entry = self._inflight.get(result.request_id)
                if entry is not None and entry.final_stage is not None:
                    # Chain entry: SUCCESS must come from the tail stage;
                    # an ERROR matches from ANY hop (a mid-chain worker
                    # reports its failures hub-ward with its own stage
                    # index).
                    matches = entry.attempt == result.attempt and (
                        result.error is not None
                        or result.stage_index == entry.final_stage
                    )
                else:
                    matches = (
                        entry is not None
                        and entry.stage_index == result.stage_index
                        and entry.attempt == result.attempt
                    )
                if not matches:
                    # Stale duplicate (late completion after re-dispatch) —
                    # the duplication bug the reference had (SURVEY §7.4).
                    global_metrics().inc("dispatcher.stale_results")
                    continue
                del self._inflight[result.request_id]
            if result.error is not None:
                if entry.final_stage is not None:
                    # A broken chain never self-heals into the same break:
                    # fall back to hub routing for everything, then replay
                    # this request end-to-end from its retained original
                    # payload.
                    self.disable_chain(
                        f"chain error at stage {result.stage_index}: "
                        f"{result.error}"
                    )
                self._forward_pool.submit(
                    self._redispatch, entry, f"error: {result.error}"
                )
                continue
            # A successful result clears the worker's strike record — a
            # transient stall (queue backlog, first compile) must not
            # sideline a healthy worker forever — and refreshes its
            # liveness evidence (which defers the watchdog's probes).
            with self._health_lock:
                self._last_ok[result.worker_id] = time.monotonic()
                self._probe_strikes.pop(result.worker_id, None)
                if self._strikes.pop(result.worker_id, None) is not None:
                    self._quarantined.discard(result.worker_id)
            next_stage = result.stage_index + 1
            if next_stage < self.plan.num_stages:
                self._forward_pool.submit(
                    self._forward, result, entry, next_stage
                )
            else:
                self._finish(entry.future, value=result.output)
            stage_latency = time.monotonic() - entry.start_time
            global_metrics().observe(
                f"stage{result.stage_index}.latency_s", stage_latency
            )
            tracer = global_tracer()
            if tracer.enabled:
                # Dispatch -> result round-trip, tagged with the SAME
                # request/attempt the framing header carried — remote
                # workers' annex-ingested spans nest under this one in
                # the stitched trace.
                end = tracer.now()
                tracer.add_span(
                    "dispatch.stage_rtt",
                    start=end - stage_latency,
                    end=end,
                    request=result.request_id,
                    attempt=result.attempt,
                    stage=result.stage_index,
                    worker=result.worker_id,
                )

    def _add_strike_locked(
        self, worker_id: str, from_probe: bool = False
    ) -> bool:
        """Record one missed deadline (caller holds ``_health_lock``);
        returns True when this strike crosses the quarantine threshold."""
        strikes = self._strikes.get(worker_id, 0) + 1
        self._strikes[worker_id] = strikes
        if from_probe:
            self._probe_strikes[worker_id] = (
                self._probe_strikes.get(worker_id, 0) + 1
            )
        newly_quarantined = (
            strikes >= self.config.fault.quarantine_strikes
            and worker_id not in self._quarantined
        )
        if newly_quarantined:
            self._quarantined.add(worker_id)
        return newly_quarantined

    def _quarantine_drain(self, worker_id: str, why: str) -> None:
        """A just-quarantined worker's other in-flight tasks are almost
        certainly doomed too — re-dispatch them now instead of one
        deadline at a time."""
        global_metrics().inc("dispatcher.quarantined")
        global_flight_recorder().record(
            "quarantine", worker=worker_id, why=why
        )
        log.warning("worker %s quarantined (%s)", worker_id, why)
        with self._inflight_lock:
            doomed = [
                e for e in self._inflight.values() if e.worker_id == worker_id
            ]
            for e in doomed:
                del self._inflight[e.request_id]
        for e in doomed:
            self._forward_pool.submit(
                self._redispatch, e, "co-resident with quarantine"
            )

    def _add_strike(self, worker_id: str, why: str) -> None:
        with self._health_lock:
            newly_quarantined = self._add_strike_locked(worker_id)
        if newly_quarantined:
            self._quarantine_drain(worker_id, why)

    def _probe_silent_workers(self, now: float, deadline: float) -> None:
        """Canary liveness probes: a hung worker heartbeats (so membership
        keeps it) and, once struck, is rank-demoted (so real traffic stops
        reaching it) — probes are the only way further strikes can accrue
        and quarantine stays reachable. Conversely, a recovered worker's
        answered probe lifts its quarantine (see _result_loop)."""
        silence = self.config.fault.probe_silence_s
        if silence is None:
            silence = self.config.fault.task_deadline_s
        # Expire overdue probes first: each costs one strike. Detection
        # and strike are one atomic critical section, so an answer racing
        # in through the result loop either lands before (probe entry gone,
        # no strike) or after (forgives the probe strike it just earned).
        with self._health_lock:
            missed = [
                wid
                for wid, (_, sent) in self._probes.items()
                if now - sent > deadline
            ]
            quarantine_now = []
            for wid in missed:
                del self._probes[wid]
                if self._add_strike_locked(wid, from_probe=True):
                    quarantine_now.append(wid)
        for wid in missed:
            global_metrics().inc("dispatcher.probes_missed")
            global_flight_recorder().record("probe_miss", worker=wid)
        for wid in quarantine_now:
            self._quarantine_drain(wid, "probe missed")
        alive = set(self.registry.alive())
        with self._workers_lock:
            pool = [
                w
                for wid, w in self._workers.items()
                if wid in alive and w.state is not WorkerState.DEAD
            ]
        for w in pool:
            with self._health_lock:
                if w.worker_id in self._probes:
                    continue
                last = self._last_ok.get(w.worker_id, self._boot_time)
                if now - last <= silence:
                    continue
                if w.worker_id not in self.registry.alive():
                    # Evicted since the pool snapshot above; inserting now
                    # would resurrect health state _on_membership('leave')
                    # just cleared (phantom strikes on rejoin). Safe to
                    # call alive() here: registry watchers fire outside its
                    # lock, so health->registry is the only ordering.
                    continue
                pid = next(self._probe_ids)
                self._probes[w.worker_id] = (pid, now)
                self._last_probe_id[w.worker_id] = pid
            try:
                w.submit(
                    Task(
                        request_id=pid,
                        stage_index=PING_STAGE,
                        attempt=0,
                        payload=None,
                    )
                )
            except Exception as e:  # noqa: BLE001 — e.g. remote socket gone
                # An unsendable probe is not a strike: a dead link stops
                # the proxy's lease renewals, so membership eviction (not
                # the probe path) retires the worker.
                with self._health_lock:
                    self._probes.pop(w.worker_id, None)
                log.warning("probe send to %s failed: %s", w.worker_id, e)
                continue
            global_metrics().inc("dispatcher.probes_sent")

    def _watchdog_loop(self) -> None:
        """Deadline scan over the in-flight registry (the reference's
        ``_task_watchdog``, ``src/dispatcher.py:302-304``, body lost —
        rebuilt here), plus canary probing of silent workers."""
        period = self.config.fault.watchdog_period_s
        deadline = self.config.fault.task_deadline_s
        while not self._shutdown.wait(period):
            if self._watchdog_paused:
                continue
            # The watchdog is the single recovery mechanism for hangs; it
            # must outlive any per-iteration surprise (a worker interface
            # raising, a registry hiccup) — skip the tick, never die.
            try:
                now = time.monotonic()
                overdue: list[_Inflight] = []
                with self._inflight_lock:
                    for rid, entry in list(self._inflight.items()):
                        # A chain entry spans the WHOLE pipeline between
                        # hub touches; its deadline scales with the
                        # stage count.
                        limit = deadline * (
                            self.plan.num_stages
                            if entry.final_stage is not None
                            else 1
                        )
                        if now - entry.start_time > limit:
                            overdue.append(entry)
                            del self._inflight[rid]
                for entry in overdue:
                    if entry.final_stage is None:
                        # Chain entries carry the HEAD's id, but the stall
                        # can be at any hop — striking (and eventually
                        # quarantining) a possibly-healthy head for a hung
                        # tail is wrong. Probes find the actual hung
                        # worker; the replay below goes hub-path anyway.
                        self._add_strike(
                            entry.worker_id, "task deadline exceeded"
                        )
                    self._forward_pool.submit(
                        self._redispatch, entry, "deadline exceeded"
                    )
                self._probe_silent_workers(now, deadline)
            except Exception:  # noqa: BLE001
                log.exception("watchdog iteration failed; continuing")

    def _on_membership(self, event: str, worker_id: str) -> None:
        """Reference ``_worker_monitor`` (:276): on worker death, don't wait
        for task deadlines — immediately re-dispatch its in-flight tasks.
        On join, prewarm the newcomer's executables in the background."""
        if event == "join":
            self.prewarm_executables()
            return
        if event != "leave":
            return
        global_flight_recorder().record("worker_leave", worker=worker_id)
        # A departed worker's record dies with it; a future re-join under
        # the same id starts with a clean slate.
        with self._health_lock:
            self._strikes.pop(worker_id, None)
            self._probe_strikes.pop(worker_id, None)
            self._quarantined.discard(worker_id)
            self._last_ok.pop(worker_id, None)
            self._probes.pop(worker_id, None)
            self._last_probe_id.pop(worker_id, None)
        # A chain member's death breaks the chain for every in-flight
        # chain request, whatever hop each is at — the hub only tracks
        # the head, so orphan them all, now, not at deadline × stages.
        with self._chain_lock:
            in_chain = self._chain is not None and worker_id in self._chain
        if in_chain:
            self.disable_chain(f"chain member {worker_id} left")
        with self._inflight_lock:
            orphaned = [
                e
                for e in self._inflight.values()
                if e.worker_id == worker_id
                or (in_chain and e.final_stage is not None)
            ]
            for e in orphaned:
                del self._inflight[e.request_id]
        for e in orphaned:
            self._forward_pool.submit(
                self._redispatch, e, f"worker {worker_id} left"
            )
