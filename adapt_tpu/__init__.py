"""adapt_tpu — TPU-native adaptive pipeline-parallel inference framework.

A ground-up re-design of ADAPT (reference:
``Karthi-es/Adaptive-Deep-Learning-Architecture-for-Parallel-and-Fault-Tolerant-Inference``)
for TPU hardware:

- models are declared as a DAG of named JAX/flax layers (``adapt_tpu.graph``),
  replacing Keras runtime-graph introspection (reference ``src/dag_util.py``);
- pipeline stages are XLA-compiled functions placed on devices of a
  ``jax.sharding.Mesh`` (``adapt_tpu.core``), replacing per-worker TF slice
  executors (reference ``src/node.py``);
- activations hop between stages over ICI (device-to-device transfers /
  ``ppermute``), with an optional quantizing codec only where a DCN/host
  boundary is crossed (``adapt_tpu.comm``), replacing lz4+zfp over raw TCP
  (reference ``src/node_state.py:39-161``, ``src/dispatcher.py:92-98``);
- a host-side control plane provides TTL-lease membership, late stage->worker
  binding, an in-flight registry with replayable payloads and a deadline
  watchdog (``adapt_tpu.control``), the reconstructed Gen-2 design of the
  reference dispatcher (``src/dispatcher.py:121-317``);
- SPMD parallelism (pipeline, data, tensor, sequence/ring-attention) lives in
  ``adapt_tpu.parallel`` as ``shard_map``/``pjit`` programs over a device mesh.
"""

__version__ = "0.1.0"

from adapt_tpu.graph.ir import INPUT, LayerGraph
from adapt_tpu.graph.partition import PartitionPlan, partition, valid_cut_points

__all__ = [
    "INPUT",
    "LayerGraph",
    "PartitionPlan",
    "partition",
    "valid_cut_points",
    "__version__",
]
