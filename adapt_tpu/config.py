"""Typed configuration.

The reference has no config system — every knob is a source-code constant
(ports ``src/dispatcher.py:14-17``, chunk size ``:24``, worker list / cut
layers / image path hand-edited per README:43-48). Framework-owned upgrade:
one frozen dataclass per subsystem, assembled into ``ServeConfig``.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Timeouts and retry policy (reference analogs cited per field)."""

    # Worker lease TTL; reference: etcd lease on /workers/<ip> (node_state.py:20).
    lease_ttl_s: float = 2.0
    # Heartbeat period (must be < lease_ttl_s).
    heartbeat_s: float = 0.5
    # Per-task deadline before the watchdog re-dispatches; reference:
    # _task_watchdog scanning inflight start_time (dispatcher.py:302-304).
    # Must exceed worst-case first-compile time unless the pipeline is
    # warmed up first (ServingPipeline.warmup) — first XLA compiles on TPU
    # can take tens of seconds.
    task_deadline_s: float = 60.0
    # Watchdog scan period.
    watchdog_period_s: float = 0.25
    # Startup wait for the first worker; reference: 5 s bounded wait then
    # clean shutdown (dispatcher.py:282-295).
    startup_wait_s: float = 5.0
    # Max re-dispatch attempts per task before failing the request.
    max_retries: int = 3
    # Deadline misses before a still-heartbeating worker (a hang) is
    # quarantined — scheduler stops acquiring it except as last resort.
    quarantine_strikes: int = 2
    # Canary probing: a worker that has been silent (no completed task or
    # probe) longer than this window receives a lightweight ping task; a
    # ping that misses the task deadline counts as a strike. This is how a
    # hung-but-heartbeating worker accrues strikes even when the scheduler
    # routes real traffic away from it (rank demotes struck workers), so
    # quarantine stays reachable. None -> task_deadline_s. Set very large
    # to disable probing.
    probe_silence_s: float | None = None
    # Worker-configuration handshake timeout; reference: connect 5 s /
    # ACK 60 s (dispatcher.py:226,250-260).
    configure_timeout_s: float = 60.0
    # Bound on any single cross-host socket send AND on waiting for the
    # send channel lock: a hung peer with a full TCP buffer must never
    # wedge a forward-pool or watchdog thread (the reference's transport
    # is non-blocking with select backpressure for the same reason,
    # node_state.py:39-89). A send that exceeds this marks the connection
    # dead (stream state is unknowable after a partial send).
    send_timeout_s: float = 10.0


@dataclasses.dataclass(frozen=True)
class CodecConfig:
    """Activation/weights codecs at host/DCN boundaries (reference
    compresses every hop with zfp+lz4, dispatcher.py:92-98; on TPU, ICI
    hops need none). Consumed by ``comm.remote.WorkerGateway`` (every
    proxy it spawns for an inbound worker uses these codecs) and by
    ``LocalPipeline.from_config`` hop transforms — in-process device-to-
    device hops ignore it by design."""

    name: str = "none"  # none | bf16 | int8 | int8dev | zfp | lz
    # zfp-style fixed tolerance (absolute) when name == "zfp".
    tolerance: float = 1e-3
    # Codec for stage *weights* on cross-host configure. Lossless by
    # default (the largest payload in the system; reference compresses
    # every weight array, src/dispatcher.py:76-89).
    weights: str = "lz"


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """SPMD pipeline schedule knobs (``parallel.pipeline_spmd``).

    The serial (GPipe) schedule puts every ICI activation hop on the
    critical path; the overlap schedule issues each hop alongside the
    next microbatch's compute so hop latency hides under it (docs/
    SERVING.md "Overlap-scheduled SPMD pipeline"). Consumed by
    ``spmd_pipeline_from_config`` and ``benchmarks/micro/hop_overlap``.
    """

    # "serial" (GPipe; hop on the critical path) or "overlap"
    # (double-buffered; hop issued concurrently with compute).
    schedule: str = "overlap"
    # Microbatches per global batch (more microbatches -> smaller
    # pipeline-fill bubble, smaller per-hop payloads).
    microbatches: int = 8
    # Circular activation-buffer depth for the overlap schedule: a hop
    # gets hop_buffers - 1 ticks to land. 2 = classic double buffering;
    # raise it only when hop latency exceeds one tick's compute.
    hop_buffers: int = 2

    def __post_init__(self):
        if self.schedule not in ("serial", "overlap"):
            raise ValueError(
                f"schedule={self.schedule!r}: expected 'serial' or "
                f"'overlap'"
            )
        if self.microbatches < 1:
            raise ValueError("microbatches must be >= 1")
        if self.hop_buffers < 2:
            raise ValueError("hop_buffers must be >= 2")


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Intra-model tensor parallelism for the serving tier
    (``runtime/continuous`` + ``parallel/sharding.lm_tp_rules``).

    ``tp > 1`` makes the continuous batcher MESH-NATIVE: transformer-LM
    weights place by the megatron-style rules (qkv / mlp-in column-split
    over the ``axis`` mesh axis, attn-out / mlp-out row-split — exactly
    one psum pair per block), and the KV caches (dense slot strips or
    paged pools) shard on their HEAD axis, so per-device KV bytes are
    the logical bytes / tp. Page *tables*, the device-resident sampling
    state and the draft model stay replicated — admission/commit logic
    is sharding-blind. See ``docs/SERVING.md`` "Tensor-parallel
    serving"."""

    #: Mesh size along ``axis``: each block's heads, KV heads, model dim
    #: and MLP hidden must divide by it
    #: (``models.transformer_lm.validate_tp``).
    tp: int = 1
    #: Mesh axis name the splits land on.
    axis: str = "tp"

    def __post_init__(self):
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if not self.axis:
            raise ValueError("axis must be a non-empty mesh axis name")


@dataclasses.dataclass(frozen=True)
class KernelConfig:
    """Decode-kernel dispatch knobs for the serving tier
    (``ops/decode_attention`` + ``ops/paged_attention``;
    ``docs/SERVING.md`` §3).

    ``attn_impl`` picks the attention implementation the batcher's
    decode/verify programs lower against: ``None`` = the measured auto
    rule (``decode_kernel_wins`` / TPU-with-supported-pages), ``"xla"``
    = the einsum oracle, ``"pallas"`` = the streaming kernel (fused
    int8/int4 dequant in VMEM). ``decode_split`` is the flash-decoding
    split along the KV-length axis: each split streams its share of the
    cache blocks (pages, in the paged layout) with its own
    online-softmax state and a single-pass rescale combine reduces the
    partials — long-context slots use the whole VPU/MXU instead of one
    sequential stream. ``None`` auto-derives from the block count
    (``ops.decode_attention.default_decode_split``) on real TPUs and
    stays 1 off-TPU; 1 is the original single-stream kernel, bit-exact.
    Which path actually serves is observable as the
    ``engine.kernel_dispatch.<op>`` gauges
    (``docs/OBSERVABILITY.md``)."""

    attn_impl: str | None = None
    decode_split: int | None = None

    def __post_init__(self):
        if self.attn_impl not in (None, "xla", "pallas"):
            raise ValueError(
                f"attn_impl={self.attn_impl!r}: expected None, 'xla' "
                "or 'pallas'"
            )
        if self.decode_split is not None and self.decode_split < 1:
            raise ValueError(
                f"decode_split must be >= 1, got {self.decode_split}"
            )


@dataclasses.dataclass(frozen=True)
class SpeculativeConfig:
    """Batched speculative decoding knobs (``runtime/continuous``
    speculative mode; ``docs/SERVING.md`` §5).

    Speculation trades DRAFT compute for target-model weight streams:
    every serving tick runs a fixed-shape ``draft_k + 1``-step draft
    scan over all slots plus ONE fused verify pass, and each slot
    commits its longest agreeing prefix plus the target's own
    correction token — between 1 and ``draft_k + 1`` tokens per tick
    per slot. Greedy requests (temperature 0) get exactly the
    target's argmax stream; sampled requests (temperature > 0) go
    through SPECULATIVE SAMPLING — accept/reject each proposal
    against the target distribution with residual resampling — so
    the emitted distribution equals non-speculative sampling
    (lossless in distribution, not bitwise). The batcher
    activates this mode when constructed with a draft model
    (``ContinuousBatcher(..., draft_lm=, draft_variables=,
    speculative=SpeculativeConfig(...))``).
    """

    #: Proposals per round. Tokens-per-target-weight-stream tops out at
    #: ``draft_k + 1`` (perfect acceptance) and degrades toward 1 as the
    #: draft misses; past ~4-8 the marginal proposal is usually rejected
    #: (acceptance compounds per position).
    draft_k: int = 4
    #: Resident dtype of the DRAFT model's weights: ``"native"`` keeps
    #: them as given; ``"int8"`` stores every matrix leaf blockwise
    #: int8-quantized (``ops.quantize.quantize_params``) with dequant
    #: fused inside the draft programs. The draft REPLICATES under
    #: tensor parallelism, so this directly cuts the per-chip HBM cost
    #: of speculation ~4x (f32 weights); the draft's quality only
    #: affects acceptance rate, never the emitted stream (losslessness
    #: is the target's property), so a slightly-perturbed draft is the
    #: cheapest capacity knob speculation has.
    draft_weight_dtype: str = "native"
    #: TREE-DRAFT width: 0 = chain speculation (the default). w >= 1
    #: appends w SIBLING leaf candidates for the position after the
    #: chain — the draft's top-w next tokens at its final scan step,
    #: harvested from logits the scan already computed (no extra draft
    #: forward) — and the verify chunk scores chain + leaves in ONE
    #: pass via the tree mask (``ops.decode_attention.verify_attention
    #: tree_tail``). When the whole chain accepts AND the target's
    #: correction token matches a leaf, that leaf's K/V is already in
    #: cache and the target's prediction AFTER it commits too: up to
    #: ``draft_k + 2`` tokens per verify pass instead of
    #: ``draft_k + 1``, at equal draft FLOPs per committed token. The
    #: draft scan runs one extra step to keep its own cache covering
    #: the leaf position (w > 1 leaves beyond the draft's argmax leave
    #: a draft-side cache entry for the argmax leaf only — an
    #: acceptance-rate nick on the sibling branches, never a
    #: correctness issue: losslessness is the target's property).
    tree_width: int = 0

    def __post_init__(self):
        if self.draft_k < 1:
            raise ValueError(f"draft_k must be >= 1, got {self.draft_k}")
        if self.draft_weight_dtype not in ("native", "int8"):
            raise ValueError(
                f"draft_weight_dtype={self.draft_weight_dtype!r}: "
                "expected 'native' or 'int8'"
            )
        if self.tree_width < 0:
            raise ValueError(
                f"tree_width must be >= 0, got {self.tree_width}"
            )


@dataclasses.dataclass(frozen=True)
class RecoveryConfig:
    """Elastic mesh recovery for the tensor-parallel serving tier
    (``runtime/continuous`` + ``control.registry.DeviceHealthMonitor``;
    ``docs/SERVING.md`` "Elastic recovery").

    When a device of the batcher's mesh is reported dead, the batcher
    rebuilds its mesh from the surviving devices (tp shrinks to the
    largest divisor of the old tp that still fits), re-validates the
    model against the shrunk mesh, re-lowers its program families with
    explicit shardings, and moves live request state across via an
    explicit redistribution plan (``parallel.sharding.KVReshardPlan``)
    — or replays requests from the journal/prefix cache when their
    state cannot migrate. Fault model: COMPUTE loss — the lost shard's
    KV heads are recovered through host staging (the simulated-kill
    stand-in for the host-tier recovery source a real deployment
    plugs in there); requests that opt out of migration replay from
    the journal instead and still emit identical tokens."""

    #: Recover inline at the next ``tick()`` after a loss. False: the
    #: tick raises ``DeviceLostError`` and the operator (or serving
    #: layer) calls :meth:`ContinuousBatcher.recover` explicitly.
    auto_reshard: bool = True
    #: Live-state policy for in-flight requests at recovery time:
    #: ``"migrate"`` moves KV/sampling state to the shrunk mesh
    #: (gather-free for surviving shards, host-staged for the lost
    #: shard's heads) so requests continue bit-identically;
    #: ``"replay"`` re-queues every in-flight request from the journal
    #: (or the in-memory request record) — same final tokens, paid by
    #: re-prefill (cheap again when the paged prefix cache still holds
    #: the prompt pages). Requests mid-chunked-prefill always replay:
    #: they have emitted nothing, so replay costs only the prefill
    #: they had not finished.
    policy: str = "migrate"
    #: Refuse to shrink below this tp (raise ``DeviceLostError``
    #: instead): capacity floor for deployments where a tp=1 remnant
    #: could not hold the model.
    min_tp: int = 1

    def __post_init__(self):
        if self.policy not in ("migrate", "replay"):
            raise ValueError(
                f"policy={self.policy!r}: expected 'migrate' or 'replay'"
            )
        if self.min_tp < 1:
            raise ValueError(f"min_tp must be >= 1, got {self.min_tp}")


@dataclasses.dataclass(frozen=True)
class DisaggConfig:
    """Disaggregated prefill/decode serving (``runtime/disagg``;
    ``docs/SERVING.md`` "Disaggregated prefill/decode").

    Production fleets split compute-bound PREFILL from latency-bound
    DECODE onto separate pools so a long prompt's admission never runs
    inside a decode tick (the decode-stall pathology the load harness
    measures as ``continuous.prefill_stall_s``). The
    ``runtime.disagg.DisaggServer`` placement policy decides PER
    REQUEST between the collocated path (ordinary
    ``ContinuousBatcher.submit`` — prefill runs in the decode tick) and
    the disaggregated path (a ``PrefillWorker`` prefills the prompt's
    full pages against its own pool and streams the KV pages to the
    decode batcher over the comm tier, where they land through the
    paged prefix cache):

    - prompts of at least ``prompt_threshold`` tokens always
      disaggregate (their inline prefill wall is the p99 ITL spike);
    - when the decode tier is BUSY (occupied slots / total slots >=
      ``busy_occupancy``), the threshold drops to
      ``busy_prompt_threshold`` — under load, even mid-length prefills
      steal decode ticks someone is waiting on;
    - everything shorter collocates: the handoff costs one page-stream
      + one suffix pass, which a short prompt's inline prefill
      undercuts.

    The policy also falls back to collocated whenever the prefill
    tier cannot take the request (pool pressure, a dead role-tagged
    lease, a prompt without one full page) — placement is an
    optimization, never a correctness gate."""

    #: Prompts with at least this many tokens always take the
    #: disaggregated path (when one exists). Must exceed the decode
    #: pool's page size — a prompt with no full page has nothing to
    #: hand off.
    prompt_threshold: int = 256
    #: Threshold applied instead when the decode tier is busy.
    busy_prompt_threshold: int = 64
    #: Decode-slot occupancy fraction at/above which the tier counts
    #: as busy.
    busy_occupancy: float = 0.75

    def __post_init__(self):
        if self.prompt_threshold < 1:
            raise ValueError(
                f"prompt_threshold must be >= 1, got "
                f"{self.prompt_threshold}"
            )
        if self.busy_prompt_threshold < 1:
            raise ValueError(
                f"busy_prompt_threshold must be >= 1, got "
                f"{self.busy_prompt_threshold}"
            )
        if self.busy_prompt_threshold > self.prompt_threshold:
            raise ValueError(
                "busy_prompt_threshold must not exceed prompt_threshold "
                f"({self.busy_prompt_threshold} > {self.prompt_threshold})"
            )
        if not 0.0 <= self.busy_occupancy <= 1.0:
            raise ValueError(
                f"busy_occupancy must be in [0, 1], got "
                f"{self.busy_occupancy}"
            )


@dataclasses.dataclass(frozen=True)
class PrefillConfig:
    """Sequence-parallel LONG-CONTEXT prefill
    (``parallel/sp_prefill.SPPrefiller``; ``docs/SERVING.md``
    "Sequence-parallel prefill").

    A prompt of at least ``sp_threshold`` tokens prefills SP-SHARDED:
    the token axis splits over an ``sp`` mesh axis, every chip
    computes its own chunk's projections/MLP sequence-locally, the
    K/V window circulates the ring (``lax.ppermute`` neighbor hops —
    the ring-attention communication pattern), and each chip's
    attention-score block is its chunk's rows only — so the O(S^2)
    prefill wall for one long prompt drops ~linearly with
    ``sp_width`` instead of monopolizing one chip. The resulting
    pages land through the SAME ``KVHandoffPlan`` /
    ``Pager.adopt_cached`` / ``_adopt_pages`` path as a disaggregated
    handoff (head-resharded sender-side, per 2211.05322), so the
    request then admits as an ordinary prefix-cache hit and decode
    stays tp-sharded and untouched; pages are byte-equal to what the
    single-device chunked prefill would have written (pinned).

    Wired at both entry points: ``ContinuousBatcher`` collocated
    admission and the ``runtime/disagg.PrefillWorker`` tier (whose
    ``step()`` dispatches sp-eligible jobs to the sp program instead
    of the chunk loop). Requires ``kv_layout='paged'`` — the landing
    path IS the paged prefix cache."""

    #: Prompts with at least this many tokens prefill sp-sharded
    #: (``None`` disables the sp path entirely). Keep it well above a
    #: page: below a few pages the ring hops cost more than the
    #: score-block split saves (see SERVING.md "when chunked-on-one-
    #: chip wins").
    sp_threshold: int | None = None
    #: Mesh size along ``sp_axis`` — the number of sequence shards
    #: (power of two; 1 turns the sp path off). Composes with tensor
    #: parallelism as an ``(sp, tp)`` mesh: ``sp_width * tp`` devices.
    sp_width: int = 1
    #: Mesh axis name the token-axis split lands on.
    sp_axis: str = "sp"

    def __post_init__(self):
        if self.sp_width < 1 or (self.sp_width & (self.sp_width - 1)):
            raise ValueError(
                f"sp_width must be a power of two >= 1, got "
                f"{self.sp_width}"
            )
        if self.sp_threshold is not None and self.sp_threshold < 1:
            raise ValueError(
                f"sp_threshold must be >= 1, got {self.sp_threshold}"
            )
        if not self.sp_axis:
            raise ValueError("sp_axis must be a non-empty mesh axis name")

    @property
    def enabled(self) -> bool:
        """The sp path is live: a threshold is set and there is a ring
        to split over."""
        return self.sp_threshold is not None and self.sp_width > 1


@dataclasses.dataclass(frozen=True)
class CacheTierConfig:
    """Hierarchical KV cache: a host-DRAM (optionally disk-backed)
    spill tier UNDER the paged prefix cache (``runtime/paged.HostKVTier``
    + ``runtime/continuous``; ``docs/SERVING.md`` §3).

    The HBM prefix LRU caps how many cold prefixes stay warm; without a
    tier, an evicted rc=0 page simply dies and the next same-prefix
    admission recomputes it. With a tier, evicted pages SPILL to host
    buffers (tracked by the same content keys), and the admission
    probe consults the host tier before declaring a prefix miss — a
    host hit re-enters the pool through the existing
    ``Pager.adopt_cached`` / ``_adopt_pages`` landing path (the
    disaggregated-handoff machinery: epoch-carrying, tp-sharded
    placement via ``KVHandoffPlan`` per-shard slices — never a
    gather) and then admits as an ordinary prefix-cache hit.

    Two host sub-tiers, each with its own codec
    (``ops.quantize.encode_page``): WARM pages keep a LOSSLESS codec
    (bit-exact readmits — the default end to end), COLD pages (demoted
    past ``warm_capacity_pages``) may take a LOSSY codec (blockwise
    int8/int4-with-scales or zfp-style mantissa truncation — the
    paper's lz4+zfp transfer-compression DNA). Lossy codecs only ever
    touch SPILLED pages, which are rc=0 by construction — a page
    referenced by a live slot is never spilled, so live decode state
    is never degraded. Spill and readmit work are budgeted PER TICK so
    the decode loop never stalls behind tier traffic."""

    #: Total host-tier capacity in pages (warm + cold, memory-resident).
    host_capacity_pages: int = 1024
    #: Pages held in the WARM sub-tier before demotion to COLD.
    warm_capacity_pages: int = 256
    #: WARM codec — must be lossless ("raw" | "lz"): a warm readmit is
    #: bit-exact by construction.
    warm_codec: str = "lz"
    #: COLD codec — "raw" | "lz" (lossless) or "int8" | "int4" | "zfp"
    #: (lossy; applied to FLOAT page planes only — int value planes of
    #: quantized pools fall back to lossless packing). Default
    #: lossless, so the whole hierarchy is bit-exact unless lossy
    #: compression is opted into.
    cold_codec: str = "lz"
    #: Max pages spilled (D2H fetch + encode) per decode tick — bounds
    #: the tier work any single tick pays. Evictions past the budget
    #: drop their content (``cache_tier.dropped_total``).
    spill_pages_per_tick: int = 8
    #: Max pages readmitted (decode + H2D landing) per decode tick;
    #: prompts whose host hits exceed it recompute the tail instead of
    #: stalling admission.
    readmit_pages_per_tick: int = 8
    #: Proactive spill watermarks, as fractions of the allocatable
    #: pool: when the HBM prefix LRU holds >= ``spill_watermark`` of
    #: the pool, the tier pre-spills the coldest un-backed LRU pages
    #: (budgeted) until the un-backed cold set is down to
    #: ``spill_low_watermark`` — so demand evictions under admission
    #: pressure find their content already host-backed (a free evict)
    #: instead of paying a fetch inside the admission path.
    spill_watermark: float = 0.5
    spill_low_watermark: float = 0.25
    #: Optional disk directory: COLD pages demoted past the host
    #: capacity persist as files there instead of dropping.
    disk_dir: str | None = None
    #: Codec for the disaggregated MSG_KV_PAGES wire
    #: (``runtime/disagg.pack_handoff``): "raw" (today's zero-copy
    #: frames) or any page codec — the crc check runs on the
    #: compressed payload either way. ``DisaggServer`` reads it off
    #: the decode batcher's tier config unless given explicitly.
    wire_codec: str = "raw"

    def __post_init__(self):
        # Direct symbol imports: the ops package re-exports a FUNCTION
        # named ``quantize`` that shadows the module on any
        # ``import ... as`` attribute lookup.
        from adapt_tpu.ops.quantize import (
            LOSSLESS_PAGE_CODECS,
            PAGE_CODECS,
        )

        if self.host_capacity_pages < 1:
            raise ValueError(
                f"host_capacity_pages must be >= 1, got "
                f"{self.host_capacity_pages}"
            )
        if not 0 <= self.warm_capacity_pages <= self.host_capacity_pages:
            raise ValueError(
                f"warm_capacity_pages must be in [0, "
                f"host_capacity_pages], got {self.warm_capacity_pages}"
            )
        if self.warm_codec not in LOSSLESS_PAGE_CODECS:
            raise ValueError(
                f"warm_codec={self.warm_codec!r}: the warm tier must "
                f"be lossless ({LOSSLESS_PAGE_CODECS})"
            )
        for name in ("cold_codec", "wire_codec"):
            v = getattr(self, name)
            if v not in PAGE_CODECS:
                raise ValueError(
                    f"{name}={v!r}: expected one of {PAGE_CODECS}"
                )
        for name in ("spill_pages_per_tick", "readmit_pages_per_tick"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        if not (
            0.0 <= self.spill_low_watermark <= self.spill_watermark <= 1.0
        ):
            raise ValueError(
                "need 0 <= spill_low_watermark <= spill_watermark <= 1, "
                f"got {self.spill_low_watermark} / {self.spill_watermark}"
            )


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Per-tenant traffic-control knobs (``config.SchedulerConfig``;
    ``runtime/scheduler.AdmissionQueue``). ``weight`` is the tenant's
    deficit-round-robin share within its priority class (a weight-2
    tenant drains twice the requests of a weight-1 tenant under
    backlog); ``burst`` caps how many of its requests may sit QUEUED
    at once (admission beyond it rejects synchronously with
    ``QueueFullError`` — the per-tenant flood bound; ``None`` leaves
    only the global ``max_queue_depth`` bound)."""

    weight: float = 1.0
    burst: int | None = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.burst is not None and self.burst < 1:
            raise ValueError(f"burst must be >= 1, got {self.burst}")


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Multi-tenant overload control in front of the continuous
    batcher (``runtime/scheduler``; ``docs/SERVING.md`` "Traffic
    control").

    Three mechanisms, in the order they engage under rising load:

    1. **Admission control** — the submit queue becomes a bounded
       ``AdmissionQueue``: per-tenant FIFO queues drained by
       deficit-round-robin within strict priority classes
       (``SLOSpec.priority``; higher admits first), per-tenant
       ``TenantQuota`` weights + burst caps, and a global
       ``max_queue_depth``. A submit past a bound raises
       ``QueueFullError`` SYNCHRONOUSLY (``request_rejected`` flight
       event) — the client learns immediately and ``result()`` never
       wedges on a request that was never accepted.
    2. **Decode-slot preemption** — when a higher-priority request has
       burned ``preempt_ttft_fraction`` of its TTFT budget waiting and
       no slot is free, the scheduler preempts the lowest-priority
       active decode slot through the elastic-recovery REPLAY path:
       the victim's slot frees (paged: its prompt pages drop into the
       prefix LRU), it re-queues (journal-reconstructed when one is
       configured) and later re-admits as a prefix-cache hit, with
       ``stream_skip`` suppressing re-delivery — exactly-once streams
       and SLO verdicts carry across preemption exactly as they do
       across a chip loss.
    3. **Closed-loop degradation** — a per-tick controller reading the
       engine/workload telemetry (queue depth, slot occupancy, TTFT
       attainment) walks a shed ladder BEFORE preemption has to do the
       work: shrink ``draft_k``, raise the disaggregated
       ``busy_prompt_threshold``, evict cold prefix-cache pages, and
       finally reject best-effort admits (``priority < 0``). Each
       transition is a ``degradation_step`` flight event.
    """

    #: Global bound on queued (not yet admitted) requests across every
    #: tenant — the bound behind ``ContinuousBatcher.submit`` (a full
    #: slot map used to queue unboundedly).
    max_queue_depth: int = 4096
    #: DRR credit granted per service turn, multiplied by the tenant's
    #: weight (request units — one request costs 1).
    quantum: float = 1.0
    #: Weight for tenants without an explicit ``TenantQuota``.
    default_weight: float = 1.0
    #: Per-tenant quotas, keyed by ``SLOSpec.tenant``.
    quotas: dict[str, TenantQuota] = dataclasses.field(
        default_factory=dict
    )
    #: Enable decode-slot preemption (mechanism 2).
    preempt: bool = True
    #: Fraction of a waiting high-priority request's TTFT budget that
    #: may burn before the scheduler preempts for it. Requests with no
    #: TTFT budget never trigger preemption.
    preempt_ttft_fraction: float = 0.5
    #: Enable the closed-loop degradation controller (mechanism 3).
    degrade: bool = True
    #: Escalate when queue depth / max_queue_depth reaches this while
    #: occupancy is at/above ``degrade_occupancy`` (or windowed TTFT
    #: attainment falls below ``degrade_attainment`` with a backlog).
    degrade_queue_high: float = 0.5
    #: De-escalate when queue depth / max_queue_depth falls to this.
    degrade_queue_low: float = 0.05
    #: Slot-occupancy fraction that counts as saturated.
    degrade_occupancy: float = 1.0
    #: Windowed TTFT attainment below this (with a backlog) also
    #: escalates.
    degrade_attainment: float = 0.9
    #: Minimum dwell between ladder transitions (hysteresis).
    degrade_dwell_s: float = 0.25
    #: Cache-aware admission ordering: among same-tenant, same-priority
    #: queued requests, admit the one whose prompt has the
    #: hottest/longest prefix RESIDENT in the pager's radix tree first
    #: (``runtime/paged.Pager.radix_probe``). Arrival order only ever
    #: re-orders within one tenant queue — priority classes, DRR
    #: weights and burst caps are untouched — and only by a STRICT
    #: score win, so a cold cache degrades to exact FIFO. Inert
    #: without the paged KV layout.
    cache_aware: bool = False
    #: How many queue-head candidates the cache-aware pick scans per
    #: pop (bounds both the probe cost per admission and how far a hot
    #: request may jump the line).
    cache_aware_window: int = 16

    def __post_init__(self):
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got "
                f"{self.max_queue_depth}"
            )
        if self.quantum <= 0:
            raise ValueError(f"quantum must be > 0, got {self.quantum}")
        if self.default_weight <= 0:
            raise ValueError(
                f"default_weight must be > 0, got {self.default_weight}"
            )
        if not 0.0 < self.preempt_ttft_fraction <= 1.0:
            raise ValueError(
                f"preempt_ttft_fraction must be in (0, 1], got "
                f"{self.preempt_ttft_fraction}"
            )
        if not 0.0 <= self.degrade_queue_low <= self.degrade_queue_high:
            raise ValueError(
                "degrade_queue_low must be in [0, degrade_queue_high] "
                f"({self.degrade_queue_low} vs {self.degrade_queue_high})"
            )
        if not 0.0 <= self.degrade_occupancy <= 1.0:
            raise ValueError(
                f"degrade_occupancy must be in [0, 1], got "
                f"{self.degrade_occupancy}"
            )
        if self.degrade_dwell_s < 0:
            raise ValueError(
                f"degrade_dwell_s must be >= 0, got "
                f"{self.degrade_dwell_s}"
            )
        if self.cache_aware_window < 1:
            raise ValueError(
                f"cache_aware_window must be >= 1, got "
                f"{self.cache_aware_window}"
            )


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """Per-request latency budget, evaluated by the serving tier's
    existing lifecycle stamps (``runtime/continuous`` request
    timelines; ``docs/OBSERVABILITY.md`` "Workload telemetry").

    ``ContinuousBatcher.submit(..., slo=SLOSpec(...))`` attaches one to
    a request: TTFT is judged once at the first emitted token
    (submit -> first token, queue wait included — the user-visible
    number), ITL at every subsequent commit. A request stays "inside
    budget" until its first violation; tokens committed while inside
    budget count toward ``continuous.goodput_tokens_s``, and the
    request lands in its tenant's ``slo.met_total.<tenant>`` /
    ``slo.missed_total.<tenant>`` counter at finish. Evaluation rides
    the ``obs_timeline`` gate: host-side arithmetic on stamps already
    taken — zero extra device traffic, zero compiled-program impact."""

    #: Submit -> first emitted token budget (None = no TTFT budget).
    ttft_budget_s: float | None = None
    #: Inter-token budget between consecutive commits (None = none).
    itl_budget_s: float | None = None
    #: Accounting label for the per-tenant met/missed counters.
    tenant: str = "default"
    #: Scheduling class (``config.SchedulerConfig`` /
    #: ``runtime/scheduler.AdmissionQueue``): higher admits strictly
    #: first under backlog and may PREEMPT a lower class's decode slot
    #: when its TTFT budget is at risk; ``< 0`` marks the request
    #: best-effort — the degradation ladder's final rung rejects those
    #: admits outright. 0 (the default) is the ordinary class; without
    #: a ``SchedulerConfig`` on the batcher, priority is carried but
    #: inert.
    priority: int = 0

    def __post_init__(self):
        for name in ("ttft_budget_s", "itl_budget_s"):
            v = getattr(self, name)
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be > 0, got {v}")
        if not self.tenant:
            raise ValueError("tenant must be a non-empty label")


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """Tracing + flight-recorder knobs (``utils.tracing``, served by
    ``utils.exporter``). The flight recorder is ALWAYS on (bounded ring,
    per-lifecycle writes only); tracing is opt-in because span recording
    is per-stage-execution. Applying a ``ServeConfig`` (constructing a
    ``Dispatcher``) pushes these onto the process-global tracer/recorder
    — enable-only for ``trace_enabled``, and capacities apply only when
    they differ from the defaults here (a default-config dispatcher must
    never truncate a ring another component explicitly sized). A
    standalone worker process enables tracing with ``ADAPT_TPU_TRACE=1``
    instead."""

    # Record serving-path spans into the global Tracer ring (and ship
    # remote workers' spans back on result frames for stitching). One
    # branch per span site when False.
    trace_enabled: bool = False
    # Span ring size. The ring OVERWRITES oldest spans when full
    # (evictions counted as `tracer.spans_dropped`); size it to cover
    # the window you expect to snapshot via GET /trace.json.
    trace_capacity: int = 65536
    # Flight-recorder ring size: the last N control-plane events
    # (admissions, re-dispatches, quarantines, probe misses,
    # recoveries) retained for GET /debug/events and post-mortem
    # snapshots.
    flight_capacity: int = 2048
    # Dispatcher.recover writes a flight-recorder snapshot JSON beside
    # the journal (flight-<unix_ts>.json) so the fault timeline that led
    # to the crash survives the process.
    snapshot_on_recovery: bool = True
    # Engine-tier per-phase timing (utils.profiling.EngineObs): tick
    # phases (admit/prefill/draft/verify/decode/commit/update) and
    # pipeline stage/hop phases record engine.phase.<name>_s histograms
    # (+ spans when tracing is on). One branch per phase site when
    # False; enabled cost measured by benchmarks/micro/obs_overhead.py
    # against the <5% tick budget. Enable-only, like trace_enabled.
    obs_engine: bool = False
    # Compile-sentinel warmup (utils.profiling.CompileSentinel): jit
    # cache growth within a program's first N sentinel samples after
    # (re-)registration is expected compilation; growth after that is
    # flagged as an unintended recompile (engine.compile_events counter,
    # flight event, WARNING, tracer instant event). Applied only when it
    # differs from this default (same rule as the ring capacities).
    compile_warmup: int = 8
    # Rolling window for the windowed rate/attainment views: the
    # continuous.goodput_tokens_s gauge's sample span and the capacity
    # plane's decode-rate ceiling (runtime/capacity.CapacityModel) read
    # the SAME window, so "goodput" means one thing across gauges and
    # forecasts.
    goodput_window_s: float = 2.0

    def __post_init__(self):
        if self.trace_capacity < 1:
            raise ValueError("trace_capacity must be >= 1")
        if self.flight_capacity < 1:
            raise ValueError("flight_capacity must be >= 1")
        if self.compile_warmup < 0:
            raise ValueError("compile_warmup must be >= 0")
        if self.goodput_window_s <= 0:
            raise ValueError("goodput_window_s must be > 0")


@dataclasses.dataclass(frozen=True)
class CapacityConfig:
    """Replica capacity / placement-signal plane
    (``runtime/capacity.CapacityModel``, docs/OBSERVABILITY.md
    "Capacity & affinity signals").

    Every batcher maintains a self-describing **capacity book**: a
    headroom partition (slots/pages/queue), a self-calibrating TTFT
    forecaster, a bounded prefix-affinity sketch, and a hysteresis
    health score — everything a router needs to place a request
    WITHOUT a per-replica prompt round-trip. All host-side, refreshed
    off the critical path through the ``_obs_flush`` seam."""

    #: Master switch. Off = no model attached: zero extra work per
    #: submit/admit/commit/flush (the obs_overhead capacity arm's
    #: floor).
    enabled: bool = True
    #: Min seconds between book rebuilds (headroom + sketch + health).
    #: Feeds (queue-wait/prefill-wall EWMAs, calibration samples) are
    #: O(1) appends regardless; this bounds the rebuild cadence.
    refresh_s: float = 0.25
    #: Prefix-affinity sketch bound: at most this many radix nodes
    #: (hashed content keys), picked by token-weighted heat.
    sketch_k: int = 32
    #: EWMA learning rate for the forecaster's queue-wait, per-bucket
    #: prefill-wall and bias-corrector estimates.
    ewma_alpha: float = 0.2
    #: Rolling count of (forecast, realized) TTFT pairs the
    #: ``capacity.forecast_calibration`` fraction is computed over.
    calibration_window: int = 256
    #: Health hysteresis: a health IMPROVEMENT must hold this long
    #: before the score follows it (worsening applies immediately —
    #: a router should back off fast and return slowly).
    health_dwell_s: float = 1.0
    #: Min seconds between lease-meta book refreshes
    #: (``WorkerRegistry`` re-register with ``meta["capacity"]``).
    lease_refresh_s: float = 1.0

    def __post_init__(self):
        if self.refresh_s < 0:
            raise ValueError("refresh_s must be >= 0")
        if self.sketch_k < 1:
            raise ValueError("sketch_k must be >= 1")
        if not 0 < self.ewma_alpha <= 1:
            raise ValueError("ewma_alpha must be in (0, 1]")
        if self.calibration_window < 1:
            raise ValueError("calibration_window must be >= 1")
        if self.health_dwell_s < 0:
            raise ValueError("health_dwell_s must be >= 0")
        if self.lease_refresh_s < 0:
            raise ValueError("lease_refresh_s must be >= 0")


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Fleet router / autoscaler (``runtime/router.FleetRouter``,
    docs/SERVING.md "Fleet routing").

    The DECISION half of the capacity plane: the router owns N decode
    replicas and places every submit by scoring each live replica's
    capacity book — prefix affinity folded into the TTFT forecast,
    health and queue pressure as additive penalties — so a resident
    prefix on replica A beats a free slot on replica B until A's queue
    costs more than the prefill the hit would save."""

    #: Placement policy: "affinity" (score books: forecast + affinity
    #: + health + queue), "least_loaded" (headroom only — what
    #: affinity degrades to when every book is cold), or "random"
    #: (the A/B control arm ``benchmarks/load/router_smoke.py``
    #: measures against).
    policy: str = "affinity"
    #: Books older than this are not placement candidates (the
    #: router-side bound; ``FederatedStore.capacity_max_age_s`` is the
    #: federation-side evict — this one must be the tighter of the
    #: two).
    book_max_age_s: float = 5.0
    #: Additive placement penalty (seconds-equivalent) for a replica
    #: publishing health "degraded". "critical" replicas are skipped
    #: outright unless every live replica is critical.
    degraded_penalty_s: float = 0.25
    #: Seconds-equivalent cost per request already queued on the
    #: replica — the least-loaded term, and the tiebreak that lets a
    #: cold-but-idle replica beat a hot-but-swamped one.
    queue_cost_s: float = 0.01
    #: Seconds-equivalent placement bonus for the prompt's rendezvous
    #: HOME replica (highest-random-weight hash of its first prefix
    #: page over live replica names). Closes the sketch-latency
    #: window: repeats of a prefix co-locate deterministically even
    #: before its first prefill has registered any page. Sized a few
    #: ``queue_cost_s`` so it decides ties but real queue pressure and
    #: learned forecasts still override; 0 disables.
    rendezvous_bias_s: float = 0.02
    #: Leave-edge recovery budget: on a replica leave the router must
    #: re-place that replica's unfinished work within this many
    #: seconds (the kill-one-of-3 acceptance bound).
    recovery_budget_s: float = 2.0
    #: TTL on each replica's membership lease (heartbeated every
    #: router tick; expiry = leave edge).
    lease_ttl_s: float = 2.0
    #: Bounded ring of placement decisions ``GET /fleet/placements``
    #: serves (why each request landed where it did).
    placements_capacity: int = 256
    #: Autoscaler floor/ceiling on replica count.
    min_replicas: int = 1
    max_replicas: int = 4
    #: Scale up when fleet queue occupancy (queued / total queue
    #: bound) holds above this for ``autoscale_dwell_s``.
    scale_up_queue_frac: float = 0.5
    #: Scale down when a replica has sat idle (no slots, no queue)
    #: this long and the fleet is above ``min_replicas``.
    scale_down_idle_s: float = 3.0
    #: Pressure must HOLD this long before a scale-up fires (one
    #: burst tick must not spawn a replica).
    autoscale_dwell_s: float = 0.5

    def __post_init__(self):
        if self.policy not in ("affinity", "least_loaded", "random"):
            raise ValueError(
                "policy must be 'affinity', 'least_loaded' or "
                f"'random', got {self.policy!r}"
            )
        if self.book_max_age_s <= 0:
            raise ValueError("book_max_age_s must be > 0")
        if self.degraded_penalty_s < 0:
            raise ValueError("degraded_penalty_s must be >= 0")
        if self.queue_cost_s < 0:
            raise ValueError("queue_cost_s must be >= 0")
        if self.rendezvous_bias_s < 0:
            raise ValueError("rendezvous_bias_s must be >= 0")
        if self.recovery_budget_s <= 0:
            raise ValueError("recovery_budget_s must be > 0")
        if self.lease_ttl_s <= 0:
            raise ValueError("lease_ttl_s must be > 0")
        if self.placements_capacity < 1:
            raise ValueError("placements_capacity must be >= 1")
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not 0 < self.scale_up_queue_frac <= 1:
            raise ValueError("scale_up_queue_frac must be in (0, 1]")
        if self.scale_down_idle_s < 0:
            raise ValueError("scale_down_idle_s must be >= 0")
        if self.autoscale_dwell_s < 0:
            raise ValueError("autoscale_dwell_s must be >= 0")


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """Tick-runtime pipelining (``runtime/continuous.py`` "Pipelined
    async runtime", docs/SERVING.md §3 "Async runtime").

    ``pipeline_depth=1`` (the default) is the synchronous loop: each
    ``tick()`` dispatches the decode/verify programs, blocks on the
    one-fetch D2H, and commits the results before returning —
    byte-for-byte the historical behavior. ``pipeline_depth=2``
    overlaps host and device: while tick *t*'s programs execute on
    device, the host runs tick *t+1*'s scheduler pass and fused
    admission/staging, and tick *t*'s results commit one call LATER
    (the one-tick commit lag — EOS/stop/cancel/SLO bookkeeping and
    ``on_token`` delivery operate on tick *t−1*'s results while *t*
    runs). Greedy streams stay bit-identical between depths; delivery
    timing (TTFT/ITL stamps, cancel consumption) measures commit, not
    device completion. Depths beyond 2 buy nothing on a
    one-program-per-tick engine (the device queue is already full with
    one tick in flight), so they are rejected eagerly rather than
    silently behaving like 2."""

    #: 1 = synchronous tick loop; 2 = one tick in flight (dispatch t
    #: while committing t-1).
    pipeline_depth: int = 1

    def __post_init__(self):
        if self.pipeline_depth not in (1, 2):
            raise ValueError(
                "pipeline_depth must be 1 (synchronous) or 2 "
                f"(one tick in flight), got {self.pipeline_depth}"
            )


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Top-level serving configuration."""

    # Bounded request concurrency; reference: concurrency semaphore
    # (dispatcher.py:151,183) and queue.Queue(10) (test/test.py:40).
    max_inflight: int = 8
    fault: FaultConfig = dataclasses.field(default_factory=FaultConfig)
    codec: CodecConfig = dataclasses.field(default_factory=CodecConfig)
    pipeline: PipelineConfig = dataclasses.field(
        default_factory=PipelineConfig
    )
    obs: ObservabilityConfig = dataclasses.field(
        default_factory=ObservabilityConfig
    )
    spec: SpeculativeConfig = dataclasses.field(
        default_factory=SpeculativeConfig
    )
    kernel: KernelConfig = dataclasses.field(
        default_factory=KernelConfig
    )
    parallel: ParallelConfig = dataclasses.field(
        default_factory=ParallelConfig
    )
    recovery: RecoveryConfig = dataclasses.field(
        default_factory=RecoveryConfig
    )
    disagg: DisaggConfig = dataclasses.field(
        default_factory=DisaggConfig
    )
    scheduler: SchedulerConfig = dataclasses.field(
        default_factory=SchedulerConfig
    )
    prefill: PrefillConfig = dataclasses.field(
        default_factory=PrefillConfig
    )
    runtime: RuntimeConfig = dataclasses.field(
        default_factory=RuntimeConfig
    )
    capacity: CapacityConfig = dataclasses.field(
        default_factory=CapacityConfig
    )
    router: RouterConfig = dataclasses.field(
        default_factory=RouterConfig
    )
    #: Hierarchical KV cache tier (None = off: evicted prefix pages
    #: die, today's behavior). Opt-in, unlike the sibling subsystem
    #: configs — a host tier changes where evicted bytes live.
    cache_tier: CacheTierConfig | None = None
