"""Decoder-only transformer LM with KV-cache incremental decoding.

Beyond reference parity (the reference is CNN-only inference,
SURVEY.md §2.2) but a natural capability for a TPU serving framework:
the causal-attention product path. The full-sequence forward is a
``LayerGraph`` cut by decoder block — the same pipeline-partition
contract as ViT (``models/vit.py``) — while generation runs a
jit-friendly KV-cache loop:

- **Prefill** consumes the prompt in one full causal forward (the flash
  attention dispatch in ``ops/attention`` picks XLA or the streaming
  Pallas kernel by measured score-memory budget) and returns per-block
  K/V caches padded to ``max_len``.
- **Decode** is a ``lax.scan`` over steps: one token's q attends over
  the cache (a single (b, h, 1, max_len) score row — no S x S anything),
  caches update in place via ``dynamic_update_slice``. Static shapes
  throughout, so the whole generate loop is one compiled program.

All modules use ``setup`` (not ``nn.compact``) so ``__call__`` (the
graph/pipeline path), ``prefill`` and ``decode_step`` share one
parameter structure — the cached decode is a different *schedule* over
the same weights, never a different model.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from adapt_tpu.graph.ir import INPUT, LayerGraph
from adapt_tpu.ops.attention import flash_attention
from adapt_tpu.ops.decode_attention import (
    append_kv,
    decode_attention,
    verify_attention,
)
from adapt_tpu.ops.paged_attention import (
    paged_attention,
    paged_chunk_attention,
    paged_verify_attention,
    pool_values,
)
from adapt_tpu.models.moe import MoEDecoderMlp
from adapt_tpu.ops.quantize import quantize_kv_vectors, unpack_int4

_NEG_INF = -1e30


def chosen_logprob(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """THE emitted-token score convention, shared by ``generate`` and
    the continuous batcher (one definition — the parity tests assert
    they agree): log-softmax of the RAW pre-temperature logits at the
    chosen token. logits (n, V), tokens (n,) -> (n,) f32."""
    lp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(
        lp, tokens[:, None].astype(jnp.int32), axis=-1
    )[:, 0]


def apply_rope(x: jax.Array, positions: jax.Array,
               base: float = 10000.0) -> jax.Array:
    """Rotary position embedding over (b, heads, s, head_dim) with
    explicit ``positions`` ((s,) shared or (b, s) per row — per-row
    LOGICAL positions keep ragged rows bitwise-equal to their solo
    runs). Rotate-half convention; head_dim must be even. Computed in
    f32 and cast back (rotation is a unitary mix — doing it in bf16
    would cost precision every cached step)."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos = jnp.asarray(positions, jnp.float32)
    if pos.ndim == 1:
        angles = pos[None, :, None] * freqs  # (1, s, half)
    else:
        angles = pos[:, :, None] * freqs  # (b, s, half)
    cos = jnp.cos(angles)[:, None, :, :]  # (b|1, 1, s, half)
    sin = jnp.sin(angles)[:, None, :, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


class CausalSelfAttention(nn.Module):
    """Causal MHA/GQA sharing weights between the full-sequence path
    (flash dispatch) and the single-token cached path.

    ``kv_heads`` (grouped-query attention): project K/V to fewer heads
    than Q and let each group of ``heads // kv_heads`` query heads share
    one K/V head. The KV cache — the thing decode streams from HBM every
    step and the thing that caps context per chip — shrinks by that same
    factor, composing multiplicatively with the int8 cache option.
    ``kv_heads=1`` is multi-query attention. ``kv_heads=None`` (or ==
    ``heads``) keeps the fused-QKV MHA parameter structure byte-for-byte
    so existing checkpoints and tests are untouched.

    Head-group convention everywhere (full path, decode, verify): query
    head ``i`` uses KV head ``i // group`` — adjacent query heads share.
    The decode/verify paths never materialize repeated K/V: query heads
    fold into extra query ROWS over the (b, kv_heads, L, hd) cache, so
    the HBM traffic is the small cache, not a broadcast copy."""

    dim: int
    heads: int
    dtype: jnp.dtype = jnp.float32
    kv_heads: int | None = None
    #: Sliding-window attention (Mistral-style): each position attends
    #: the previous ``window`` positions only. Decode-side this is just
    #: a dynamic ``valid_from`` (the kernels need no change, and paged
    #: serving can RECYCLE pages behind the window); full-sequence
    #: forwards band the causal mask.
    window: int | None = None
    #: Rotary position embeddings: q/k rotate by their LOGICAL position
    #: (buffer position minus ragged left padding, so padded rows equal
    #: their solo runs bitwise); the cache stores POST-rotation K, so
    #: every cached decode path works unchanged.
    rope: bool = False

    def setup(self):
        if self.dim % self.heads:
            raise ValueError(
                f"model dim {self.dim} not divisible by {self.heads} heads"
            )
        if self.rope and (self.dim // self.heads) % 2:
            raise ValueError(
                f"rope needs an even head_dim, got {self.dim // self.heads}"
            )
        head_dim = self.dim // self.heads
        kvh = self.kv_heads
        if kvh is not None:
            if not 1 <= kvh <= self.heads:
                raise ValueError(
                    f"kv_heads {kvh} outside [1, heads={self.heads}]"
                )
            if self.heads % kvh:
                raise ValueError(
                    f"heads {self.heads} not divisible by kv_heads {kvh}"
                )
        if self._group == 1:
            # MHA: one fused projection (unchanged param structure).
            self.qkv = nn.DenseGeneral(
                (3, self.heads, head_dim), dtype=self.dtype, name="qkv"
            )
        else:
            self.q_proj = nn.DenseGeneral(
                (self.heads, head_dim), dtype=self.dtype, name="q"
            )
            self.kv_proj = nn.DenseGeneral(
                (2, kvh, head_dim), dtype=self.dtype, name="kv"
            )
        self.out = nn.Dense(self.dim, dtype=self.dtype, name="out")

    @property
    def _group(self) -> int:
        """Query heads per KV head (1 = plain MHA)."""
        return self.heads // (self.kv_heads or self.heads)

    @property
    def cache_heads(self) -> int:
        """Head count of K/V cache buffers — kv_heads under GQA, heads
        otherwise. External cache allocators MUST use this (not
        ``heads``) or GQA models get heads-sized buffers and shape
        errors at runtime."""
        return self.kv_heads or self.heads

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads

    def _project(self, x):
        """-> q (b, h, s, hd); k, v (b, kv_h, s, hd) (kv_h == h for
        MHA)."""
        if self._group == 1:
            qkv = self.qkv(x)  # (b, s, 3, h, hd)
            q, k, v = jnp.moveaxis(qkv, 2, 0)
        else:
            q = self.q_proj(x)  # (b, s, h, hd)
            k, v = jnp.moveaxis(self.kv_proj(x), 2, 0)  # (b, s, kv_h, hd)
        # -> (b, heads-axis, s, hd)
        return tuple(jnp.swapaxes(t, 1, 2) for t in (q, k, v))

    def _repeat_kv(self, t):
        """Expand (b, kv_h, s, hd) -> (b, h, s, hd) for the full-sequence
        flash path: repeat is adjacent-block so query head i lines up
        with KV head i // group."""
        g = self._group
        return t if g == 1 else jnp.repeat(t, g, axis=1)

    def _group_q(self, q):
        """Fold query-head groups into query rows: (b, h, s, hd) ->
        (b, kv_h, g*s, hd), row index = group_member * s + position —
        the cached-path attention then runs against the UN-repeated
        (b, kv_h, L, hd) cache with identical einsums."""
        b, h, s, hd = q.shape
        g = self._group
        return q.reshape(b, h // g, g * s, hd)

    def _ungroup_o(self, o, s):
        """Inverse of ``_group_q`` on the attention output."""
        b, kvh, gs, hd = o.shape
        return o.reshape(b, kvh * (gs // s), s, hd)

    def _rope_qk(self, q, k, positions):
        """Rotate q and k by ``positions`` when rope is on (no-op
        otherwise). Runs BEFORE GQA group folding / caching, so the
        cache holds post-rotation K."""
        if not self.rope:
            return q, k
        return apply_rope(q, positions), apply_rope(k, positions)

    def __call__(self, x):
        b, s, d = x.shape
        q, k, v = self._project(x)
        q, k = self._rope_qk(q, k, jnp.arange(s))
        o = flash_attention(
            q, self._repeat_kv(k), self._repeat_kv(v), causal=True,
            window=self.window,
        )
        return self.out(jnp.swapaxes(o, 1, 2).reshape(b, s, d))

    def _window_from(self, index, b, valid_from):
        """Effective ``valid_from`` for cached decode under a sliding
        window: the window's left edge per row, max-composed with any
        ragged left padding. None when windowless and dense."""
        if self.window is None:
            return valid_from
        idx = jnp.broadcast_to(
            jnp.asarray(index, jnp.int32).reshape(-1), (b,)
        )
        w_from = jnp.maximum(idx - self.window + 1, 0)
        if valid_from is not None:
            w_from = jnp.maximum(w_from, valid_from)
        return w_from

    # One scale per cached key/value vector — the shared scheme in
    # ops.quantize (the kernel tests and on-chip smoke quantize with the
    # same function, so the definition cannot fork).
    _quantize_kv = staticmethod(quantize_kv_vectors)

    def _write_kv_pair(self, cache_k, cache_v, k, v, write):
        """Fan one K/V cache write out over the cache's representation:
        quantized ``(values, scales)`` pairs quantize ``k``/``v`` with
        the shared absmax scheme and apply ``write`` to BOTH members;
        native caches write directly. The cache's VALUE width is
        authoritative for the quantized dtype: a ``head_dim // 2`` lane
        member is an int4-PACKED pool (two nibbles per int8 lane —
        ``ops.quantize.quantize_kv_vectors(..., "int4")``), so every
        write packs to match without any extra plumbing.
        ``write(member, new)`` is each
        call site's own primitive (page scatter, chunk scatter,
        ``append_kv``) — this is THE one quantize-then-write-both
        definition, so the decode/prefill/verify paths cannot
        diverge."""
        if isinstance(cache_k, tuple):
            dt = (
                "int4"
                if cache_k[0].shape[-1] * 2 == k.shape[-1]
                else "int8"
            )
            kq, ks = self._quantize_kv(k, dt)
            vq, vs = self._quantize_kv(v, dt)
            return (
                (write(cache_k[0], kq), write(cache_k[1], ks)),
                (write(cache_v[0], vq), write(cache_v[1], vs)),
            )
        return write(cache_k, k), write(cache_v, v)

    def prefill(self, x, max_len: int, valid_from=None, quantize_cache=False):
        """Full causal attention over the prompt, returning output plus
        K/V caches padded to ``max_len`` (zeros beyond the prompt are
        masked by position in ``decode_step``).

        ``valid_from`` (b,) enables ragged batches: row i's keys at
        positions < valid_from[i] are left-padding and masked out. The
        masked variant rides the same measured dispatch as the dense one
        — the Pallas kernel carries the per-row mask as an SMEM scalar,
        so a ragged long-context prefill streams instead of falling back
        to the O(S^2) oracle.

        ``quantize_cache`` stores the cache quantized: ``True`` /
        ``"int8"`` = int8 (one absmax scale per key/value vector),
        ``"int4"`` = the 15-level nibble lattice PACKED two per int8
        lane (values ``head_dim // 2`` wide, same scale plane). This is
        a CONTEXT-CAPACITY feature, not a
        speed feature: cache bytes drop ~1.9x vs bf16 (measured
        603,979,776 -> 320,864,256 at bs8/2k, so ~1.9x more context per
        chip), but the hardware A/B (r04 `lm_decode_long_{native,int8}`)
        measured decode ~12% SLOWER (1,964 vs 2,226 tok/s at 2k context,
        GPT-2-small) — XLA does not fuse the per-step dequant for free,
        so the bandwidth saving does not show up as throughput at this
        size. Caches become ``(int8 values, f32 scales)`` pairs."""
        b, s, d = x.shape
        q, k, v = self._project(x)
        pos = jnp.arange(s)
        if valid_from is not None:
            # LOGICAL positions (0 at each row's first real token) keep
            # a ragged row's rotations bitwise-equal to its solo run.
            pos = pos[None, :] - valid_from[:, None]
        q, k = self._rope_qk(q, k, pos)
        o = flash_attention(
            q, self._repeat_kv(k), self._repeat_kv(v),
            causal=True, valid_from=valid_from, window=self.window,
        )
        pad = ((0, 0), (0, 0), (0, max_len - s), (0, 0))
        out = self.out(jnp.swapaxes(o, 1, 2).reshape(b, s, d))
        if quantize_cache:
            dt = "int4" if quantize_cache == "int4" else "int8"
            kv_, ks = self._quantize_kv(k, dt)
            vv_, vs = self._quantize_kv(v, dt)
            return (
                out,
                (jnp.pad(kv_, pad), jnp.pad(ks, pad)),
                (jnp.pad(vv_, pad), jnp.pad(vs, pad)),
            )
        return out, jnp.pad(k, pad), jnp.pad(v, pad)

    # Write K tokens' K or V at ``index`` (scalar: whole batch at one
    # position, the generate() lockstep; (b,): each ROW at its own
    # position — continuous batching and batched speculation, where
    # every slot is at a different sequence length). One definition in
    # ``ops/decode_attention.append_kv`` shared with the verify paths.
    _cache_write = staticmethod(append_kv)

    def decode_step(
        self, x_t, cache_k, cache_v, index, valid_from=None, quantized=False,
        attn_impl=None, split=None,
    ):
        """One token: write its K/V at ``index``, attend its q over the
        cache. ``index`` is traced — the same compiled step serves every
        position — and may be scalar (whole batch in lockstep) or (b,)
        (each row at its own position; see ``_cache_write``).
        ``valid_from`` (b,) masks a ragged batch's left padding out of
        the cache window. ``quantized`` caches are ``(int8 values, f32
        scales)`` pairs (see ``prefill``). The attention itself is
        :func:`adapt_tpu.ops.decode_attention.decode_attention` —
        ``attn_impl`` (None = measured auto, ``"xla"``, ``"pallas"``)
        picks between the einsum schedule and the streaming Pallas
        kernel that dequantizes int8 caches in VMEM; ``split`` is the
        kernel's flash-decoding KV split (``config.KernelConfig``)."""
        b = x_t.shape[0]
        q, k, v = self._project(x_t)  # q (b, h, 1, hd); k/v (b, kv_h, 1, hd)
        if self.rope:
            idx = jnp.broadcast_to(
                jnp.asarray(index, jnp.int32).reshape(-1), (b,)
            )
            logical = idx - (0 if valid_from is None else valid_from)
            q, k = self._rope_qk(q, k, logical[:, None])
        # GQA: fold query-head groups into query rows so the attention
        # runs unchanged against the small (b, kv_h, L, hd) cache.
        q = self._group_q(q)  # (b, kv_h, g, hd)
        # The cache representation is authoritative (tuple iff
        # quantized — prefill builds it that way); the ``quantized``
        # parameter is the callers' static-arg plumbing, kept for
        # signature stability.
        del quantized
        cache_k, cache_v = self._write_kv_pair(
            cache_k, cache_v, k, v,
            lambda c, t: self._cache_write(c, t, index),
        )
        o = decode_attention(
            q, cache_k, cache_v, index,
            self._window_from(index, b, valid_from), prefer=attn_impl,
            split=split,
        ).astype(x_t.dtype)
        o = self._ungroup_o(o, 1)  # (b, h, 1, hd)
        o = jnp.swapaxes(o, 1, 2).reshape(b, 1, self.dim)
        return self.out(o), cache_k, cache_v


    def decode_step_paged(
        self, x_t, k_pool, v_pool, page_table, index, valid_from=None,
        attn_impl=None, split=None,
    ):
        """One token against a PAGED cache (``ops/paged_attention``):
        write this step's K/V into the slot's physical page at
        ``index``'s (page, offset), then attend over the table-mapped
        window. ``index`` scalar or (b,) as in ``decode_step``; pools
        are (num_pages, kv_h, P, hd) arrays or quantized ``(int8
        values, f32 scales)`` PAIRS of pools (scales (num_pages, kv_h,
        P, 1); this step's K/V quantize via the shared absmax scheme
        before the scatter, and dequant fuses into the attention — see
        ``ops/paged_attention``); ``page_table`` (b, pages_per_slot)
        int32 (idle rows may map everything to the trash page — their
        writes land there, unread)."""
        b = x_t.shape[0]
        page = pool_values(k_pool).shape[2]
        q, k, v = self._project(x_t)  # q (b, h, 1, hd); k/v (b, kv_h, 1, hd)
        idx = jnp.broadcast_to(
            jnp.asarray(index, jnp.int32).reshape(-1), (b,)
        )
        if self.rope:
            logical = idx - (0 if valid_from is None else valid_from)
            q, k = self._rope_qk(q, k, logical[:, None])
        q = self._group_q(q)  # (b, kv_h, g, hd)
        # Negative index = dead row (idle or mid-chunked-prefill slot in
        # a lockstep batch). Its garbage write MUST go to the trash page
        # — the row may own real pages (a prefilling slot does), and
        # table[row, 0] would be prompt page 0. Attention masks every
        # position (cols <= negative is empty), so nothing reads back.
        live_row = idx >= 0
        safe = jnp.maximum(idx, 0)
        phys = jnp.take_along_axis(
            page_table, (safe // page)[:, None], axis=1
        )[:, 0]  # (b,) physical page of each row's write
        phys = jnp.where(live_row, phys, 0)
        off = safe % page

        # Advanced-index scatter: rows (phys[i], :, off[i], :) <- token i.
        def write(pool, t):
            return pool.at[phys, :, off, :].set(
                t[:, :, 0, :].astype(pool.dtype)
            )

        k_pool, v_pool = self._write_kv_pair(k_pool, v_pool, k, v, write)
        o = paged_attention(
            q, k_pool, v_pool, page_table, index,
            self._window_from(index, b, valid_from), prefer=attn_impl,
            split=split,
        ).astype(x_t.dtype)
        o = self._ungroup_o(o, 1)
        o = jnp.swapaxes(o, 1, 2).reshape(b, 1, self.dim)
        return self.out(o), k_pool, v_pool

    def prefill_chunk_paged(
        self, x, k_pool, v_pool, pages, pos0, attn_impl=None,
    ):
        """Incremental prefill of a CHUNK of positions [pos0, pos0 + C)
        directly against a paged window: write the chunk's K/V into its
        own pages (one O(C) scatter), then attend the whole window in
        place via :func:`paged_chunk_attention` — no gathered strip, no
        scatter-back (the chunked-prefill counterpart of
        ``decode_step_paged``). ``pages`` (n,) covers [0, pos0 + C)
        (pow2 trash padding allowed); ``pos0`` is page-aligned and C is
        a whole number of pages. Batch 1 (prefill is per request).
        Quantized ``(values, scales)`` pool pairs quantize the chunk's
        K/V before the page scatter — note the chunk then ATTENDS the
        already-quantized earlier window, so a chunked/suffix prefill
        over int8 pools carries the cache's quantization error into the
        chunk's hidden states (same fine print as chunk fp contraction
        widths, one quantization step coarser)."""
        b, c, d = x.shape
        page = pool_values(k_pool).shape[2]
        q, k, v = self._project(x)  # q (1, h, C, hd); k/v (1, kv_h, C, hd)
        q, k = self._rope_qk(q, k, pos0 + jnp.arange(c))
        q = self._group_q(q)  # (1, kv_h, g*C, hd)
        n_chunk = c // page
        chunk_pages = lax.dynamic_slice(
            jnp.asarray(pages, jnp.int32), (pos0 // page,), (n_chunk,)
        )
        kvh, hd = k.shape[1], k.shape[3]

        def to_pages(t):  # (1, kv_h, C, w) -> (n_chunk, kv_h, page, w)
            return jnp.swapaxes(
                t[0].reshape(kvh, n_chunk, page, t.shape[3]), 0, 1
            )

        def write(pool, t):
            return pool.at[chunk_pages].set(to_pages(t).astype(pool.dtype))

        k_pool, v_pool = self._write_kv_pair(k_pool, v_pool, k, v, write)
        o = paged_chunk_attention(
            q, k_pool, v_pool, pages, pos0, c, prefer=attn_impl,
            window=self.window,
        ).astype(x.dtype)
        o = self._ungroup_o(o, c)  # (1, h, C, hd)
        o = jnp.swapaxes(o, 1, 2).reshape(b, c, self.dim)
        return self.out(o), k_pool, v_pool

    def prefill_sp(self, x, gather, quantize_cache=False, constrain=None):
        """SEQUENCE-PARALLEL prefill body: the whole span's attention
        in one layer-synchronous pass, written so every per-row
        operation mirrors the computation :meth:`prefill_chunk_paged`
        runs for that row, op for op — the sp-sharded prefill program
        (``parallel/sp_prefill``) is byte-equal to the single-device
        chunked prefill at the pinned test shapes, and shares chunked
        prefill's documented ulp fine print beyond them (see the
        sp_prefill module docstring).

        ``x`` is (1, S, d) with the S axis sp-sharded under GSPMD
        (projections, rope, quantization and the MLP are all
        token-local, so they compute shard-locally for free).
        ``gather`` is the caller's window collective — the ring
        collect in ``parallel/sp_prefill.ring_collect`` (K/V blocks
        rotate via ``lax.ppermute`` neighbor hops; each rank
        accumulates the full window) — applied to the POOL
        REPRESENTATION of K/V, exactly what the paged pools would
        hold: ``quantize_cache`` False keeps native dtype,
        ``"int8"``/``"int4"`` quantize with the shared absmax scheme
        (int4 packed two nibbles per lane) BEFORE the window is read,
        so the chunk-attends-the-already-quantized-window fine print
        of chunked prefill is reproduced exactly. The attention math
        mirrors ``paged_chunk_attention_reference`` op for op (f32
        scores, scale columns, -1e30 mask, softmax, scale-weighted
        probabilities) with the mask ``col <= row`` — per-row
        identical to any chunk schedule's mask, with trailing bucket
        padding contributing exact zeros.

        Returns ``(out, cache_k, cache_v)`` where the caches are the
        pool-representation ``(1, kv_h, S, w)`` arrays (or
        ``(values, scales)`` tuples) in sequence order — the caller
        slices them into page-major handoff blocks.

        ``constrain`` (optional) pins the attention intermediates'
        row axis to the caller's sp sharding
        (``with_sharding_constraint`` on ``(1, kv_h, rows, X)``
        arrays): without it GSPMD's propagation may replicate the
        score block — every rank computing every row — which is
        numerically identical but forfeits exactly the O(S^2/P)
        compute split this path exists for. Resharding never changes
        values, so the byte-equality contract is constraint-blind."""
        b, s, d = x.shape
        q, k, v = self._project(x)
        q, k = self._rope_qk(q, k, jnp.arange(s))
        if quantize_cache:
            dt = "int4" if quantize_cache == "int4" else "int8"
            ck = self._quantize_kv(k, dt)
            cv = self._quantize_kv(v, dt)
        else:
            ck, cv = k, v
        kg = gather(ck)
        vg = gather(cv)
        pin = constrain if constrain is not None else (lambda t: t)
        q = pin(self._group_q(q))  # (1, kv_h, g*S, hd), rows sp-sharded
        sm = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
        if quantize_cache:
            kv_, ksc = kg
            vv_, vsc = vg
            if kv_.shape[-1] * 2 == q.shape[-1]:  # packed int4 nibbles
                kv_, vv_ = unpack_int4(kv_), unpack_int4(vv_)
            s_ = jnp.einsum(
                "bhqd,bhkd->bhqk",
                q.astype(jnp.float32),
                kv_.astype(jnp.float32),
            ) * jnp.swapaxes(ksc, 2, 3) * sm
        else:
            kv_, vv_ = kg, vg
            s_ = jnp.einsum(
                "bhqd,bhkd->bhqk",
                q.astype(jnp.float32),
                kv_.astype(jnp.float32),
            ) * sm
        rows = jnp.arange(q.shape[2]) % s  # folded row -> position
        cols = jnp.arange(s)
        live = cols[None, :] <= rows[:, None]
        if self.window is not None:
            live = live & (cols[None, :] > rows[:, None] - self.window)
        s_ = pin(jnp.where(live[None, None], s_, -1e30))
        p = jax.nn.softmax(s_, axis=-1)
        if quantize_cache:
            p = p * jnp.swapaxes(vsc, 2, 3)
        o = pin(jnp.einsum(
            "bhqk,bhkd->bhqd", p, vv_.astype(jnp.float32)
        ).astype(q.dtype))
        o = self._ungroup_o(o, s)  # (1, h, S, hd)
        o = jnp.swapaxes(o, 1, 2).reshape(b, s, d)
        return self.out(o), ck, cv

    def verify_chunk(self, x, cache_k, cache_v, index, tree_tail=0):
        """Append a CHUNK of ``K`` tokens at positions
        ``index..index+K-1`` in ONE cached pass — the speculative-decode
        verify primitive: each chunk row's query attends the cache up to
        its own position (``p <= index + row``), so the K logits equal
        exactly what K sequential ``decode_step`` calls would produce,
        for one forward instead of K. ``index`` is scalar (the
        single-request speculative loop) or (b,) (BATCHED speculation:
        every slot verifies its own chunk at its own position — rows
        desynchronize, the compiled program does not; a negative row
        index marks a dead slot whose writes and reads are trash-masked).
        The chunk K/V write is one ``append_kv`` scatter; rejected
        suffixes need no rollback — the position mask simply never
        admits them (the same trash-slot discipline the continuous
        batcher uses). Quantized ``(int8 values, f32 scales)`` cache
        pairs quantize the chunk's K/V with the shared absmax scheme
        before the append — the same values K sequential quantized
        ``decode_step`` calls would write, so quantized verify logits
        equal the sequential quantized decode's.

        ``tree_tail`` = w > 0 marks the chunk's last w rows as TREE
        LEAVES — grouped draft candidates for ONE logical position,
        ``index + chain + 1`` (chain = K - 1 - w): they embed/rotate at
        that shared logical position, write at their own DISTINCT
        physical cache slots (``index + row``, inside the speculative
        slack), and attend the chain plus only themselves
        (``ops.decode_attention.verify_attention``'s tree mask) — one
        verify pass scores every leaf of a draft token tree."""
        b, kc, d = x.shape
        q, k, v = self._project(x)  # q (b, h, K, hd); k/v (b, kv_h, K, hd)
        offs = jnp.arange(kc)
        if tree_tail:
            # Leaves share the logical position after the chain.
            offs = jnp.minimum(offs, kc - tree_tail)
        if jnp.ndim(index):
            pos = index[:, None] + offs[None, :]  # (b, K)
        else:
            pos = index + offs
        q, k = self._rope_qk(q, k, pos)
        q = self._group_q(q)  # (b, kv_h, g*K, hd), row = member*K + pos
        cache_k, cache_v = self._write_kv_pair(
            cache_k, cache_v, k, v, lambda c, t: append_kv(c, t, index)
        )
        o = verify_attention(
            q, cache_k, cache_v, index, kc, window=self.window,
            tree_tail=tree_tail,
        ).astype(x.dtype)
        o = self._ungroup_o(o, kc)  # (b, h, K, hd)
        o = jnp.swapaxes(o, 1, 2).reshape(b, kc, self.dim)
        return self.out(o), cache_k, cache_v

    def verify_chunk_paged(
        self, x, k_pool, v_pool, page_table, index, attn_impl=None,
        tree_tail=0, split=None,
    ):
        """Batched verify over a PAGED cache: scatter each slot's K
        chunk tokens into its own pages at ``index[b]..index[b]+K-1``
        (table-mapped, one advanced-index scatter), then attend each
        row's paged window up to its own diagonal
        (:func:`paged_verify_attention`) — ``verify_chunk``'s exact
        semantics over ``decode_step_paged``'s layout. ``index`` (b,);
        a negative row is dead (idle or mid-chunked-prefill slot): its
        writes route to the trash page and its positions all mask.
        Quantized ``(values, scales)`` pool pairs scatter the chunk's
        quantized K/V into both members (the scale plane rides the
        same page table). ``tree_tail``/``split`` as in
        ``verify_chunk`` / ``decode_step_paged``."""
        b, kc, _ = x.shape
        page = pool_values(k_pool).shape[2]
        q, k, v = self._project(x)  # q (b, h, K, hd); k/v (b, kv_h, K, hd)
        idx = jnp.broadcast_to(
            jnp.asarray(index, jnp.int32).reshape(-1), (b,)
        )
        offs = jnp.arange(kc)
        if tree_tail:
            offs = jnp.minimum(offs, kc - tree_tail)
        if self.rope:
            q, k = self._rope_qk(q, k, idx[:, None] + offs[None, :])
        q = self._group_q(q)  # (b, kv_h, g*K, hd)
        live_row = idx >= 0
        pos = jnp.maximum(idx, 0)[:, None] + jnp.arange(kc)[None, :]
        phys = jnp.take_along_axis(page_table, pos // page, axis=1)
        phys = jnp.where(live_row[:, None], phys, 0)  # dead -> trash page
        off = pos % page
        # Advanced-index scatter: (phys[b,t], :, off[b,t], :) <- token t
        # of slot b. Dead rows' K writes pile unordered onto the trash
        # page — never read (their masks are empty).
        def write(pool, t):
            return pool.at[phys, :, off, :].set(
                jnp.swapaxes(t, 1, 2).astype(pool.dtype)
            )

        k_pool, v_pool = self._write_kv_pair(k_pool, v_pool, k, v, write)
        o = paged_verify_attention(
            q, k_pool, v_pool, page_table, idx, kc, prefer=attn_impl,
            window=self.window, tree_tail=tree_tail, split=split,
        ).astype(x.dtype)
        o = self._ungroup_o(o, kc)
        o = jnp.swapaxes(o, 1, 2).reshape(b, kc, self.dim)
        return self.out(o), k_pool, v_pool


class DecoderBlock(nn.Module):
    """Pre-LN decoder block; residuals stay inside the node so block
    boundaries are clean pipeline cuts (same contract as ViT's
    ``EncoderBlock``).

    ``moe_experts`` swaps the dense MLP for a dropless per-token MoE
    (:class:`adapt_tpu.models.moe.MoEDecoderMlp`) — the Mixtral-shaped
    decoder. ``_mlp`` is the ONE touch point every schedule shares
    (full forward, prefill, decode_step, verify_chunk, paged chunk
    prefill), so the MoE block serves through every decode path —
    generate, continuous batching, speculative, pipelined — with the
    exact cache-parity contract of the dense block, and its
    expert-stacked params EP-shard via ``parallel.expert`` unchanged."""

    dim: int
    heads: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.float32
    kv_heads: int | None = None
    moe_experts: int | None = None
    moe_top_k: int = 1
    window: int | None = None
    rope: bool = False

    @property
    def cache_heads(self) -> int:
        """Cache-buffer head count (see ``CausalSelfAttention.cache_heads``)."""
        return self.kv_heads or self.heads

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads

    def setup(self):
        self.ln1 = nn.LayerNorm(dtype=self.dtype)
        self.attn = CausalSelfAttention(
            self.dim, self.heads, dtype=self.dtype, kv_heads=self.kv_heads,
            window=self.window, rope=self.rope,
        )
        self.ln2 = nn.LayerNorm(dtype=self.dtype)
        if self.moe_experts is not None:
            self.moe = MoEDecoderMlp(
                num_experts=self.moe_experts,
                hidden_dim=self.mlp_dim,
                top_k=self.moe_top_k,
                dtype=self.dtype,
            )
        else:
            self.mlp_in = nn.Dense(self.mlp_dim, dtype=self.dtype)
            self.mlp_out = nn.Dense(self.dim, dtype=self.dtype)

    def _mlp(self, x):
        if self.moe_experts is not None:
            return self.moe(x)
        return self.mlp_out(nn.gelu(self.mlp_in(x)))

    def __call__(self, x):
        x = x + self.attn(self.ln1(x))
        return x + self._mlp(self.ln2(x))

    def prefill(self, x, max_len: int, valid_from=None, quantize_cache=False):
        a, ck, cv = self.attn.prefill(
            self.ln1(x), max_len, valid_from, quantize_cache
        )
        x = x + a
        return x + self._mlp(self.ln2(x)), ck, cv

    def decode_step(
        self, x_t, cache_k, cache_v, index, valid_from=None, quantized=False,
        attn_impl=None, split=None,
    ):
        a, ck, cv = self.attn.decode_step(
            self.ln1(x_t), cache_k, cache_v, index, valid_from, quantized,
            attn_impl, split,
        )
        x_t = x_t + a
        return x_t + self._mlp(self.ln2(x_t)), ck, cv

    def decode_step_paged(
        self, x_t, k_pool, v_pool, page_table, index, valid_from=None,
        attn_impl=None, split=None,
    ):
        a, kp, vp = self.attn.decode_step_paged(
            self.ln1(x_t), k_pool, v_pool, page_table, index, valid_from,
            attn_impl, split,
        )
        x_t = x_t + a
        return x_t + self._mlp(self.ln2(x_t)), kp, vp

    def prefill_chunk_paged(
        self, x, k_pool, v_pool, pages, pos0, attn_impl=None,
    ):
        a, kp, vp = self.attn.prefill_chunk_paged(
            self.ln1(x), k_pool, v_pool, pages, pos0, attn_impl
        )
        x = x + a
        return x + self._mlp(self.ln2(x)), kp, vp

    def prefill_sp(self, x, gather, quantize_cache=False, constrain=None):
        a, ck, cv = self.attn.prefill_sp(
            self.ln1(x), gather, quantize_cache, constrain
        )
        x = x + a
        return x + self._mlp(self.ln2(x)), ck, cv

    def verify_chunk(self, x, cache_k, cache_v, index, tree_tail=0):
        a, ck, cv = self.attn.verify_chunk(
            self.ln1(x), cache_k, cache_v, index, tree_tail
        )
        x = x + a
        return x + self._mlp(self.ln2(x)), ck, cv

    def verify_chunk_paged(
        self, x, k_pool, v_pool, page_table, index, attn_impl=None,
        tree_tail=0, split=None,
    ):
        a, kp, vp = self.attn.verify_chunk_paged(
            self.ln1(x), k_pool, v_pool, page_table, index, attn_impl,
            tree_tail, split,
        )
        x = x + a
        return x + self._mlp(self.ln2(x)), kp, vp


class TokenEmbed(nn.Module):
    """Token + (optionally) learned positional embeddings.

    ``use_pos=False`` drops the position table entirely — the rope
    decoder's position signal lives in the attention rotations, not in
    the residual stream; the three embed entry points keep their
    signatures so every schedule calls them identically."""

    vocab: int
    dim: int
    max_len: int
    dtype: jnp.dtype = jnp.float32
    use_pos: bool = True

    def setup(self):
        self.tok = nn.Embed(self.vocab, self.dim, dtype=self.dtype)
        if self.use_pos:
            self.pos = self.param(
                "pos_embed",
                nn.initializers.normal(0.02),
                (self.max_len, self.dim),
                jnp.float32,
            )

    def __call__(self, ids):
        s = ids.shape[1]
        out = self.tok(ids)
        if self.use_pos:
            out = out + self.pos[:s].astype(self.dtype)
        return out

    def embed_at(self, ids_t, index):
        """Embed a single token column at traced position ``index``."""
        out = self.tok(ids_t)
        if self.use_pos:
            p = lax.dynamic_slice(self.pos, (index, 0), (1, self.dim))
            out = out + p.astype(self.dtype)
        return out

    def embed_positions(self, ids, pos_ids):
        """Embed with explicit per-row position ids (ragged batches:
        a left-padded row's logical positions start at 0 at its first
        real token, not at buffer column 0)."""
        out = self.tok(ids)
        if self.use_pos:
            out = out + self.pos[jnp.clip(pos_ids, 0)].astype(self.dtype)
        return out


class LMHead(nn.Module):
    """Final LN + vocab projection (logits in f32 for stable sampling)."""

    vocab: int
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.ln = nn.LayerNorm(dtype=self.dtype)
        self.logits = nn.Dense(self.vocab, dtype=jnp.float32)

    def __call__(self, x):
        return self.logits(self.ln(x).astype(jnp.float32))


@dataclasses.dataclass(frozen=True)
class TransformerLM:
    """A built LM: the pipeline-partitionable graph plus the decode
    metadata ``generate`` needs."""

    graph: LayerGraph
    depth: int
    max_len: int

    @property
    def vocab(self) -> int:
        """Read from the head module — one source of truth, no field that
        could drift from the actual logits dimension."""
        return self.graph.node("head").module.vocab

    @property
    def block_names(self) -> list[str]:
        return [f"decoder_block_{i}" for i in range(self.depth)]


def transformer_lm(
    vocab: int,
    dim: int,
    depth: int,
    heads: int,
    mlp_dim: int,
    max_len: int = 1024,
    dtype: jnp.dtype = jnp.float32,
    name: str = "transformer_lm",
    kv_heads: int | None = None,
    moe_experts: int | None = None,
    moe_top_k: int = 1,
    window: int | None = None,
    pos: str = "learned",
) -> TransformerLM:
    """``kv_heads < heads`` builds a grouped-query (GQA) decoder: KV
    caches shrink by ``heads // kv_heads`` (``kv_heads=1`` = MQA), the
    serving-era cache-capacity knob — see ``CausalSelfAttention``.

    ``moe_experts`` builds a Mixtral-shaped MoE decoder: every block's
    MLP becomes a dropless per-token mixture of that many experts
    (``moe_top_k`` active per token, ``mlp_dim`` = per-expert hidden).
    Served by every decode path with exact cache parity, and
    EP-shardable via ``parallel.expert.place_experts`` — see
    :class:`DecoderBlock` / :class:`adapt_tpu.models.moe.MoEDecoderMlp`.

    ``pos="rope"`` swaps learned positional embeddings for rotary ones
    (q/k rotate by logical position in every schedule; the cache holds
    post-rotation K, so all decode paths serve it unchanged).

    ``window`` builds a sliding-window (Mistral-style) decoder: each
    position attends only the previous ``window`` positions. Cached
    decode masks the window as a dynamic ``valid_from`` (no kernel
    changes; blocks behind the window skip compute), and the paged
    batcher RECYCLES pages that fall wholly behind it mid-request —
    pool usage bounds by the window, not the sequence.
    """
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if pos not in ("learned", "rope"):
        raise ValueError(f"pos={pos!r}: expected 'learned' or 'rope'")
    rope = pos == "rope"
    g = LayerGraph(name)
    prev = g.add(
        "embed",
        TokenEmbed(vocab, dim, max_len, dtype=dtype, use_pos=not rope),
        INPUT,
    )
    for i in range(depth):
        prev = g.add(
            f"decoder_block_{i}",
            DecoderBlock(dim, heads, mlp_dim, dtype=dtype,
                         kv_heads=kv_heads, moe_experts=moe_experts,
                         moe_top_k=moe_top_k, window=window, rope=rope),
            prev,
        )
    g.add("head", LMHead(vocab, dtype=dtype), prev)
    return TransformerLM(graph=g, depth=depth, max_len=max_len)


def lm_tiny(vocab: int = 256, max_len: int = 64) -> TransformerLM:
    """Small LM for tests."""
    return transformer_lm(vocab, 64, 4, 4, 128, max_len, name="lm_tiny")


def validate_tp(lm: TransformerLM, tp: int) -> None:
    """Eager divisibility checks for megatron-style tensor parallelism
    (``parallel.sharding.lm_tp_rules`` placement + head-sharded KV
    caches): every decoder block's query heads, KV/cache heads, model
    dim and MLP hidden must divide by ``tp``, or the column/row splits
    (and the cache's head-axis sharding) cannot land evenly. Raises a
    named ValueError instead of an opaque device_put/GSPMD error. The
    ``cache_heads`` check is the GQA-aware one: KV heads shard over tp,
    so kv_heads % tp == 0 keeps each shard's query-head groups aligned
    with its own resident KV heads (collective-free attention)."""
    if tp <= 1:
        return
    for name in lm.block_names:
        block = lm.graph.node(name).module
        for what, n in (
            ("heads", block.heads),
            ("cache (KV) heads", block.cache_heads),
            ("model dim", block.dim),
            ("mlp hidden dim", block.mlp_dim),
        ):
            if n % tp:
                raise ValueError(
                    f"{name}: {what} {n} not divisible by tp={tp} — "
                    "megatron TP splits heads/KV-heads column-wise and "
                    "dim/mlp row-wise, all must divide evenly"
                )


def nucleus_filter(lg: jax.Array, top_p: jax.Array) -> jax.Array:
    """Top-p (nucleus) truncation with a TRACED p: keep the smallest
    descending-probability prefix whose mass reaches ``top_p`` (the
    crossing token inclusive; the top-1 token always survives, so the
    filter can never empty a row). ``top_p`` is scalar or per-row (n,).
    Costs one (n, V) sort — callers on hot paths gate it behind a
    static use-flag like the top-k sort."""
    sorted_desc = jnp.sort(lg, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    p = jnp.asarray(top_p)
    if p.ndim:
        p = p[:, None]
    keep = (cum - probs) < p  # mass BEFORE this token still under p
    # p == 1.0 must be an EXACT identity (no filtering): with peaked
    # logits the f32 cumsum saturates at 1.0 before the tail, so
    # (cum - probs) < 1.0 alone would drop tokens whose probability
    # rounds below the cumsum's ulp — and a mixed batch sharing one
    # compiled filter (continuous batching) would then diverge from the
    # filter-free solo path.
    keep = keep | (p >= 1.0)
    kth = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(lg >= kth, lg, -jnp.inf)


def sample_next_tokens(
    logits: jax.Array,
    key: jax.Array,
    temperature: jax.Array,
    *,
    do_sample: bool,
    top_k: int | None,
    top_p: jax.Array | float | None = None,
    row_offset: jax.Array | int = 0,
) -> jax.Array:
    """logits (n, V) -> (n,) token ids: greedy argmax, or sample from
    ``softmax(logits / temperature)`` optionally truncated to ``top_k``
    and/or the ``top_p`` nucleus (k first, then p — the usual serving
    composition).

    Sampling keys are PER ROW — the step key folded with the row's
    *global* batch index (``row_offset + i``) — so any contiguous slice
    of a batch draws exactly what the full batch draws for those rows.
    That slice-invariance is what lets pipelined decode
    (:mod:`adapt_tpu.parallel.pipeline_decode`), which samples one
    microbatch at a time on the last pipeline rank, match single-program
    :func:`generate` token-for-token even at temperature > 0."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    lg = logits / temperature
    if top_k is not None:
        # lax.top_k, not a full vocab sort: this runs once per decoded
        # token on the serving hot path.
        kth = lax.top_k(lg, top_k)[0][:, -1:]
        lg = jnp.where(lg >= kth, lg, -jnp.inf)
    if top_p is not None:
        lg = nucleus_filter(lg, top_p)
    rows = row_offset + jnp.arange(lg.shape[0])
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(key, rows)
    return jax.vmap(jax.random.categorical)(keys, lg)


def _left_align(prompt: jax.Array, lengths: jax.Array):
    """Right-padded ragged rows -> (left-aligned buffer, per-row logical
    position ids, per-row left-pad counts). Row i shifts right by
    ``s0 - lengths[i]`` so every row's last real token sits at buffer
    column s0-1 and decode shares one scalar cache index across the
    batch; logical positions are 0 at each row's first real token
    (negatives mark padding)."""
    _, s0 = prompt.shape
    pad = (s0 - lengths)[:, None]  # (b, 1)
    cols = jnp.arange(s0)[None, :]
    src = jnp.clip(cols - pad, 0)
    aligned = jnp.take_along_axis(prompt, src, axis=1)
    pos_ids = cols - pad
    return aligned, pos_ids, pad[:, 0]


def validate_generate_args(
    lm: TransformerLM,
    prompt: jax.Array,
    steps: int,
    temperature: float,
    top_k: int | None,
    rng: jax.Array | None,
    prompt_lengths: jax.Array | None,
    kv_cache_dtype: str,
    top_p: float | None = None,
) -> tuple[jax.Array, jax.Array, bool]:
    """Shared request validation for :func:`generate` and the pipelined
    decoder: returns ``(lengths, rng, do_sample)`` with every constraint
    checked eagerly (clear ValueErrors instead of opaque trace errors)."""
    b, s0 = prompt.shape
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if s0 + steps > lm.max_len:
        raise ValueError(
            f"prompt {s0} + steps {steps} exceeds max_len {lm.max_len}"
        )
    do_sample = bool(temperature > 0.0)
    if do_sample and rng is None:
        raise ValueError("temperature > 0 requires an rng key")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_k is not None and top_k > lm.vocab:
        # lax.top_k with k > axis size fails at trace time with an opaque
        # XLA error; name the real constraint instead.
        raise ValueError(f"top_k {top_k} exceeds vocab size {lm.vocab}")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if kv_cache_dtype not in ("native", "int8", "int4"):
        raise ValueError(
            f"kv_cache_dtype={kv_cache_dtype!r}: expected 'native', "
            "'int8' or 'int4'"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused by the greedy path
    if prompt_lengths is None:
        lengths = jnp.full((b,), s0, jnp.int32)
    else:
        lengths = jnp.asarray(prompt_lengths, jnp.int32)
        if lengths.shape != (b,):
            raise ValueError(
                f"prompt_lengths shape {lengths.shape} != ({b},)"
            )
        # Out-of-range lengths would silently gather a corrupted prompt
        # (clip hides it). Validate eagerly when values are concrete;
        # traced callers (generate under an outer jit) must pre-validate.
        try:
            import numpy as _np

            lv = _np.asarray(lengths)
        except jax.errors.TracerArrayConversionError:
            pass
        else:
            if (lv < 1).any() or (lv > s0).any():
                raise ValueError(
                    f"prompt_lengths must be in [1, {s0}], got {lv}"
                )
    return lengths, rng, do_sample


def generate(
    lm: TransformerLM,
    variables,
    prompt: jax.Array,
    steps: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_id: int | None = None,
    rng: jax.Array | None = None,
    prompt_lengths: jax.Array | None = None,
    kv_cache_dtype: str = "native",
    decode_attn: str | None = None,
    return_logprobs: bool = False,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Generation as one compiled program: prefill over the prompt + a
    ``lax.scan`` of single-token cached decode steps.

    prompt: (b, s0) int32 token ids, s0 >= 1; returns (b, steps) ids.

    Ragged batches: pass right-padded prompts plus ``prompt_lengths``
    (b,) — rows are left-aligned internally (so every row's next token
    lands at one shared cache index), position embeddings are row
    logical (0 at each row's first real token), and the left padding is
    masked out of every attention window. Each row's output then starts
    at ITS OWN continuation, exactly as if it had been generated alone.

    ``kv_cache_dtype="int8"`` stores the KV cache quantized (absmax
    int8 per key/value vector): ~1.9x fewer cache bytes than bf16, so
    ~1.9x more context fits per chip, at a small logits perturbation
    (tested against the native-cache path). Use it for CAPACITY, not
    speed — the hardware A/B measured decode ~12% slower than the
    native cache at 2k context (see ``prefill``'s docstring and
    ``benchmarks/results/r04/lm_decode_long_*.json``).
    ``"int4"`` halves the value bytes again (two nibbles packed per
    int8 lane, same per-vector f32 scale plane) at a larger
    perturbation — the serving tier gates its top-1 agreement against
    int8 rather than claiming losslessness.

    Sampling: ``temperature=0`` (default) is greedy argmax and needs no
    ``rng``; ``temperature > 0`` samples from ``softmax(logits / T)``,
    optionally truncated to the ``top_k`` highest-probability tokens
    and/or the ``top_p`` nucleus (smallest probability mass >= p; k
    then p when both are set — the standard serving knobs). ``eos_id``
    makes a finished row emit
    ``eos_id`` forever after — scan length is static, so "stop" means
    "pad with EOS", the jit-friendly convention.

    Compilation: only the *shape* of the request is static (steps,
    top_k, and the sample/top_p/eos on-off booleans); temperature,
    top_p, and eos_id are traced operands, so a server sweeping them
    per request reuses one compiled program.

    ``decode_attn`` picks the per-step attention implementation (None =
    measured auto, ``"xla"``, ``"pallas"`` — see
    :mod:`adapt_tpu.ops.decode_attention`).

    ``return_logprobs=True`` returns ``(tokens, logprobs)`` where
    ``logprobs[b, t]`` is the MODEL's log-probability (log-softmax of
    the raw, pre-temperature logits) of the emitted token — the serving
    convention: sampling knobs shape which token gets picked, the
    reported score is always the model's own.
    """
    lengths, rng, do_sample = validate_generate_args(
        lm, prompt, steps, temperature, top_k, rng, prompt_lengths,
        kv_cache_dtype, top_p=top_p,
    )
    if decode_attn not in (None, "xla", "pallas"):
        raise ValueError(
            f"decode_attn={decode_attn!r}: expected None, 'xla' or 'pallas'"
        )
    return _generate_impl(
        lm,
        variables,
        prompt,
        lengths,
        jnp.asarray(temperature, jnp.float32),
        # top_p rides as a traced operand (servers sweep it per request
        # without recompiling); use_top_p is the static on/off.
        jnp.asarray(1.0 if top_p is None else top_p, jnp.float32),
        jnp.asarray(-1 if eos_id is None else eos_id, prompt.dtype),
        rng,
        steps=steps,
        do_sample=do_sample,
        top_k=top_k,
        use_top_p=top_p is not None,
        use_eos=eos_id is not None,
        ragged=prompt_lengths is not None,
        # Static: False, "int8" or "int4" — prefill's quantize_cache
        # builds the matching (values, scales) representation and the
        # decode path follows the cache's own width from there.
        kv_quant=(
            kv_cache_dtype if kv_cache_dtype != "native" else False
        ),
        decode_attn=decode_attn,
        return_logprobs=return_logprobs,
    )


@partial(
    jax.jit,
    static_argnames=(
        "lm", "steps", "do_sample", "top_k", "use_top_p", "use_eos",
        "ragged", "kv_quant", "decode_attn", "return_logprobs",
    ),
)
def _generate_impl(
    lm: TransformerLM,
    variables,
    prompt: jax.Array,
    lengths: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    eos_id: jax.Array,
    rng: jax.Array,
    *,
    steps: int,
    do_sample: bool,
    top_k: int | None,
    use_top_p: bool,
    use_eos: bool,
    ragged: bool,
    kv_quant: bool,
    decode_attn: str | None = None,
    return_logprobs: bool = False,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    g = lm.graph
    b, s0 = prompt.shape
    embed = g.node("embed").module
    head = g.node("head").module
    blocks = [g.node(n).module for n in lm.block_names]

    if ragged:
        prompt, pos_ids, valid_from = _left_align(prompt, lengths)
    else:
        pos_ids = None
        valid_from = None

    def pick(lg, key):
        """logits (b, V) -> token ids (b,); per-row keys (see
        sample_next_tokens)."""
        return sample_next_tokens(
            lg, key, temperature, do_sample=do_sample, top_k=top_k,
            top_p=top_p if use_top_p else None,
        )

    # ---- prefill ---------------------------------------------------------
    if ragged:
        h = embed.apply(
            variables["embed"], prompt, pos_ids, method="embed_positions"
        )
    else:
        h = embed.apply(variables["embed"], prompt)
    caches = []
    for name, block in zip(lm.block_names, blocks):
        h, ck, cv = block.apply(
            variables[name],
            h,
            lm.max_len,
            valid_from,
            kv_quant,
            method="prefill",
        )
        caches.append((ck, cv))
    logits = head.apply(variables["head"], h[:, -1:, :])  # (b, 1, V)
    rng, key0 = jax.random.split(rng)
    first = pick(logits[:, 0], key0).astype(prompt.dtype)  # (b,)
    done0 = (first == eos_id) if use_eos else jnp.zeros((b,), bool)

    first_lp = (
        chosen_logprob(logits[:, 0], first) if return_logprobs else None
    )

    # ---- decode ----------------------------------------------------------
    # Each iteration consumes the carried token and emits its successor,
    # so steps-1 iterations (plus the prefill's `first`) produce exactly
    # `steps` tokens with no dead final forward.
    def step(carry, key):
        tok, index, done, caches = carry
        if ragged:
            # Logical position differs per row (index - left padding).
            x_t = embed.apply(
                variables["embed"],
                tok[:, None],
                (index - valid_from)[:, None],
                method="embed_positions",
            )
        else:
            x_t = embed.apply(
                variables["embed"], tok[:, None], index, method="embed_at"
            )  # (b, 1, d)
        new_caches = []
        for name, block, (ck, cv) in zip(lm.block_names, blocks, caches):
            x_t, ck, cv = block.apply(
                variables[name],
                x_t,
                ck,
                cv,
                index,
                valid_from,
                kv_quant,
                decode_attn,
                method="decode_step",
            )
            new_caches.append((ck, cv))
        lg = head.apply(variables["head"], x_t)[:, 0]  # (b, V)
        nxt = pick(lg, key).astype(tok.dtype)
        if use_eos:
            nxt = jnp.where(done, eos_id.astype(tok.dtype), nxt)
            done = done | (nxt == eos_id)
        out = (
            (nxt, chosen_logprob(lg, nxt)) if return_logprobs else nxt
        )
        return (nxt, index + 1, done, tuple(new_caches)), out

    (_, _, _, _), rest = lax.scan(
        step,
        (first, jnp.asarray(s0, jnp.int32), done0, tuple(caches)),
        jax.random.split(rng, steps - 1) if steps > 1 else jnp.zeros(
            (0, 2), jnp.uint32
        ),
    )
    if return_logprobs:
        rest_tok, rest_lp = rest
        tokens = jnp.concatenate(
            [first[:, None], jnp.swapaxes(rest_tok, 0, 1)], axis=1
        )
        lps = jnp.concatenate(
            [first_lp[:, None], jnp.swapaxes(rest_lp, 0, 1)], axis=1
        )
        return tokens, lps  # (b, steps) each
    return jnp.concatenate(
        [first[:, None], jnp.swapaxes(rest, 0, 1)], axis=1
    )  # (b, steps)


def logits_full(lm: TransformerLM, variables, ids: jax.Array) -> jax.Array:
    """Full-sequence causal logits — the oracle the cached decode must
    match position-for-position (and the pipeline-partition path)."""
    return lm.graph.apply(variables, ids)
