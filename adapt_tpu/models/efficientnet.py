"""EfficientNet-B0/B4 as LayerGraphs (multi-branch DAG partition workload).

BASELINE.json config 4: "EfficientNet-B4 (dag_util multi-branch DAG
partition)" — the workload that exercises the reference partitioner's
DAG-join handling (``/root/reference/src/dag_util.py:28-43``). Blocks with
identity residuals become branch+add node pairs (real joins); stride or
channel-changing blocks are single chain nodes. Keras-style block names
(``block{stage}{letter}``) keep cut lists portable.
"""

from __future__ import annotations

import math
import string

import jax.numpy as jnp

from adapt_tpu.graph.ir import INPUT, LayerGraph
from adapt_tpu.graph.spec import registered_lambda
from adapt_tpu.models.layers import (
    ClassifierHead,
    ConvBN,
    MBConvBranch,
)
import jax

# B0 base architecture: (repeats, in_filters, out_filters, kernel, stride,
# expand_ratio) per stage — EfficientNet paper Table 1.
_B0_STAGES = (
    (1, 32, 16, 3, 1, 1),
    (2, 16, 24, 3, 2, 6),
    (2, 24, 40, 5, 2, 6),
    (3, 40, 80, 3, 2, 6),
    (3, 80, 112, 5, 1, 6),
    (4, 112, 192, 5, 2, 6),
    (1, 192, 320, 3, 1, 6),
)


def _round_filters(filters: int, width_mult: float, divisor: int = 8) -> int:
    filters *= width_mult
    new = max(divisor, int(filters + divisor / 2) // divisor * divisor)
    if new < 0.9 * filters:
        new += divisor
    return int(new)


def _round_repeats(repeats: int, depth_mult: float) -> int:
    return int(math.ceil(depth_mult * repeats))


def efficientnet(
    width_mult: float,
    depth_mult: float,
    num_classes: int = 1000,
    dtype: jnp.dtype = jnp.float32,
    name: str = "efficientnet",
) -> LayerGraph:
    g = LayerGraph(name)
    stem_filters = _round_filters(32, width_mult)
    g.add(
        "stem",
        ConvBN(stem_filters, (3, 3), strides=2, act=jax.nn.silu, dtype=dtype),
        INPUT,
    )
    prev = "stem"
    in_f = stem_filters
    for stage_idx, (repeats, _, out_f0, kernel, stride, expand) in enumerate(
        _B0_STAGES, start=1
    ):
        out_f = _round_filters(out_f0, width_mult)
        for r in range(_round_repeats(repeats, depth_mult)):
            blk = f"block{stage_idx}{string.ascii_lowercase[r]}"
            s = stride if r == 0 else 1
            branch_mod = MBConvBranch(
                in_filters=in_f,
                out_filters=out_f,
                kernel=kernel,
                strides=s,
                expand_ratio=expand,
                dtype=dtype,
            )
            if s == 1 and in_f == out_f:
                # Identity residual: a real DAG join.
                b = g.add(f"{blk}_branch", branch_mod, prev)
                prev = g.add(
                    f"{blk}_add", registered_lambda("add"), (prev, b)
                )
            else:
                prev = g.add(blk, branch_mod, prev)
            in_f = out_f
    top_filters = _round_filters(1280, width_mult)
    g.add(
        "top_conv",
        ConvBN(top_filters, (1, 1), act=jax.nn.silu, dtype=dtype),
        prev,
    )
    g.add("head", ClassifierHead(num_classes, dtype=dtype), "top_conv")
    return g


def efficientnet_b0(
    num_classes: int = 1000, dtype: jnp.dtype = jnp.float32
) -> LayerGraph:
    return efficientnet(1.0, 1.0, num_classes, dtype, name="efficientnet_b0")


def efficientnet_b4(
    num_classes: int = 1000, dtype: jnp.dtype = jnp.float32
) -> LayerGraph:
    """B4: width x1.4, depth x1.8 (canonical input 380x380)."""
    return efficientnet(1.4, 1.8, num_classes, dtype, name="efficientnet_b4")
