"""Mixture-of-Experts MLP with Switch/GShard-style static routing.

Beyond reference parity (SURVEY.md §2.2: no MoE constructs anywhere) but
first-class here as the expert-parallel workload. The design is
TPU-idiomatic end to end: routing is expressed as dense one-hot einsums
with a STATIC per-expert capacity, so the whole layer is fixed-shape — no
gather/scatter, no data-dependent shapes, everything tiles onto the MXU
and shards cleanly.

Routing (top-k, k in {1, 2}): softmax gate over experts; each token's
chosen expert slot is its prefix-count position in that expert's queue
(cumsum over the one-hot); tokens past ``capacity = ceil(cf * N * k / E)``
are dropped (their combine weight is zero, output falls back to the
residual path of the surrounding block). Aux load-balance loss is the
standard mean(fraction_tokens * fraction_probs) * E.

Expert parallelism: expert-stacked params carry a leading ``E`` dim;
:func:`adapt_tpu.parallel.expert.expert_shardings` shards that dim over
the ``ep`` mesh axis and GSPMD turns the dispatch/combine einsums into
all-to-alls over ICI (the scaling-book recipe — annotate, don't hand-roll
collectives).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp


def _expert_params(mod: nn.Module, d: int, e: int, hidden: int):
    """The expert-stacked parameter block shared by the train-side
    (:class:`MoEMlp`) and serve-side (:class:`MoEDecoderMlp`) layers —
    one declaration, so their weights stay structurally interchangeable
    (same names, shapes, initializers; ``parallel.expert`` shards both
    identically)."""
    wg = mod.param("gate", nn.initializers.lecun_normal(), (d, e),
                   jnp.float32)
    w1 = mod.param("w1", nn.initializers.lecun_normal(), (e, d, hidden),
                   jnp.float32)
    b1 = mod.param("b1", nn.initializers.zeros, (e, hidden))
    w2 = mod.param("w2", nn.initializers.lecun_normal(), (e, hidden, d),
                   jnp.float32)
    b2 = mod.param("b2", nn.initializers.zeros, (e, d))
    return wg, w1, b1, w2, b2


def _topk_combine(gates: jax.Array, top_k: int):
    """Per-token top-k gate weights [N, E] (chosen entries carry their
    gate probability, the rest zero) plus the FIRST-choice one-hot —
    the argmax-and-mask loop shared by both routing flavors."""
    combine = jnp.zeros_like(gates)
    first_onehot = None
    remaining = gates
    for choice in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)
        onehot = jax.nn.one_hot(idx, gates.shape[-1], dtype=gates.dtype)
        if choice == 0:
            first_onehot = onehot
        combine = combine + onehot * gates
        remaining = remaining * (1.0 - onehot)
    return combine, first_onehot


def _switch_aux_loss(gates: jax.Array, first_onehot: jax.Array):
    """THE load-balance aux convention (Switch-style, first choice
    only, minimum 1.0 at perfect balance) — one definition so the two
    MoE layers' sown ``aux_loss`` stay on one scale."""
    e = gates.shape[-1]
    importance = jnp.sum(first_onehot, axis=0)
    frac_tokens = importance / jnp.maximum(jnp.sum(importance), 1.0)
    return jnp.sum(frac_tokens * jnp.mean(gates, axis=0)) * e


def _one_hot_routing(gates: jax.Array, capacity: int, top_k: int):
    """Build (dispatch [N,E,C], combine [N,E,C], aux_loss) from gate
    probabilities [N, E]."""
    n, e = gates.shape
    dispatch_slots = []
    combine_weights = []
    remaining = gates
    # Track how full each expert queue already is from earlier choices.
    base_count = jnp.zeros((e,), jnp.int32)
    first_onehot = None
    for choice in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)  # [N]
        onehot = jax.nn.one_hot(idx, e, dtype=gates.dtype)  # [N, E]
        if choice == 0:
            first_onehot = onehot
        prob = jnp.sum(gates * onehot, axis=-1)  # [N]
        pos = (
            jnp.cumsum(onehot, axis=0) - 1.0 + base_count[None, :]
        ) * onehot  # [N, E]
        slot = jnp.sum(pos, axis=-1).astype(jnp.int32)  # [N]
        keep = slot < capacity
        dispatch = (
            onehot[:, :, None]
            * jax.nn.one_hot(slot, capacity, dtype=gates.dtype)[:, None, :]
            * keep[:, None, None]
        )  # [N, E, C]
        dispatch_slots.append(dispatch)
        combine_weights.append(dispatch * prob[:, None, None])
        base_count = base_count + jnp.sum(
            onehot * keep[:, None], axis=0
        ).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)  # mask chosen expert
    dispatch = sum(dispatch_slots)
    combine = sum(combine_weights)
    return dispatch, combine, _switch_aux_loss(gates, first_onehot)


class MoEDecoderMlp(nn.Module):
    """Dropless per-token MoE for the DECODE/serving paths: each token's
    output is ``sum_{e in its top-k} gate_e * MLP_e(token)`` — no
    capacity, no slots, no cross-token coupling. That independence is
    the point: a token's output is a pure function of its own hidden
    state, so KV-cached decode, verify_chunk, chunked prefill and the
    full-sequence forward all agree EXACTLY (the repo's decode-parity
    contract), where :class:`MoEMlp`'s capacity routing would drop
    different tokens under different batch shapes.

    Computed in the masked-dense form (every expert evaluates every
    token via expert-stacked einsums; combine weights zero the rest) —
    fully static shapes, no gather/scatter. With the expert dim sharded
    over ``ep`` (:func:`adapt_tpu.parallel.expert.expert_shardings`
    applies unchanged — same leading-``E`` params), GSPMD gives each
    device its ``E/ep`` experts over replicated tokens and psums the
    combine: per-device cost ~ ``(E/ep) x`` a dense MLP, the classic
    dense-EP inference schedule. The capacity-routed :class:`MoEMlp`
    remains the train-side layer (its dispatch einsums all-to-all
    instead of replicating token compute)."""

    num_experts: int = 8
    hidden_dim: int = 128
    top_k: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2, got {self.top_k}")
        if self.top_k > self.num_experts:
            # A third pick over a fully-masked gate row would re-select
            # expert 0 and silently double its weight.
            raise ValueError(
                f"top_k {self.top_k} exceeds num_experts "
                f"{self.num_experts}"
            )
        b, s, d = x.shape
        tokens = x.reshape(b * s, d)
        wg, w1, b1, w2, b2 = _expert_params(
            self, d, self.num_experts, self.hidden_dim
        )
        gates = jax.nn.softmax(
            tokens.astype(jnp.float32) @ wg, axis=-1
        )  # [N, E]
        combine, first_onehot = _topk_combine(gates, self.top_k)
        self.sow(
            "intermediates", "aux_loss",
            _switch_aux_loss(gates, first_onehot),
        )

        xt = tokens.astype(self.dtype)
        h = jax.nn.gelu(
            jnp.einsum("nd,edh->neh", xt, w1.astype(self.dtype))
            + b1[None, :, :].astype(self.dtype)
        )
        out_e = (
            jnp.einsum("neh,ehd->ned", h, w2.astype(self.dtype))
            + b2[None, :, :].astype(self.dtype)
        )
        out = jnp.einsum(
            "ned,ne->nd", out_e, combine.astype(self.dtype)
        )
        return out.reshape(b, s, d).astype(x.dtype)


class MoEMlp(nn.Module):
    """Token-routed expert MLP: [B, S, D] -> [B, S, D]."""

    num_experts: int = 8
    hidden_dim: int = 128
    top_k: int = 1
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        assert self.top_k in (1, 2), "top_k must be 1 or 2"
        assert self.top_k <= self.num_experts, (
            f"top_k={self.top_k} needs >= that many experts "
            f"(got {self.num_experts}); a second choice would re-route to "
            "the same expert and double the output"
        )
        b, s, d = x.shape
        n = b * s
        e = self.num_experts
        capacity = max(
            1, math.ceil(self.capacity_factor * n * self.top_k / e)
        )
        tokens = x.reshape(n, d)
        wg, w1, b1, w2, b2 = _expert_params(
            self, d, e, self.hidden_dim
        )

        gates = jax.nn.softmax(
            (tokens.astype(jnp.float32)) @ wg, axis=-1
        ).astype(self.dtype)
        dispatch, combine, aux = _one_hot_routing(
            gates, capacity, self.top_k
        )
        self.sow("intermediates", "aux_loss", aux)

        xt = tokens.astype(self.dtype)
        # Dispatch: [N,E,C] x [N,D] -> [E,C,D]; with w1/w2 sharded on E,
        # GSPMD lowers this to an all-to-all over the ep axis.
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, xt)
        h = jax.nn.gelu(
            jnp.einsum("ecd,edh->ech", expert_in, w1.astype(self.dtype))
            + b1[:, None, :].astype(self.dtype)
        )
        expert_out = (
            jnp.einsum("ech,ehd->ecd", h, w2.astype(self.dtype))
            + b2[:, None, :].astype(self.dtype)
        )
        out = jnp.einsum("nec,ecd->nd", combine, expert_out)
        return out.reshape(b, s, d).astype(x.dtype)
