"""ViT-B/16 as a LayerGraph cut by transformer block.

BASELINE.json config 5: "ViT-B/16 encoder split by transformer block,
kill-one-stage fault-injection". Each encoder block (pre-LN MHA + MLP with
internal residuals) is one node named ``encoder_block_{i}``, so every block
boundary is a valid cut point — the transformer analog of the reference's
layer-name cuts. The homogeneous block structure also admits the stacked
SPMD pipeline path in ``adapt_tpu.parallel`` (scan-over-blocks +
``ppermute``), which this per-node graph form complements.
"""

from __future__ import annotations

from collections.abc import Callable

import flax.linen as nn
import jax.numpy as jnp

from adapt_tpu.graph.ir import INPUT, LayerGraph
from adapt_tpu.ops.attention import flash_attention


class MultiHeadSelfAttention(nn.Module):
    """Self-attention on the fused Pallas flash kernel (``ops/attention``).

    The product-path consumer of the kernel: qkv/out projections are flax
    DenseGenerals (MXU matmuls), the softmax(QK^T)V core is
    ``flash_attention`` — blockwise online-softmax in VMEM, O(S*D) memory
    (and the jnp oracle for parity testing via ``attn_fn``)."""

    heads: int
    dtype: jnp.dtype = jnp.float32
    #: None -> ``flash_attention`` with ``prefer=attn_prefer``; a custom
    #: callable receives plain (q, k, v) and owns its own dispatch.
    attn_fn: Callable | None = None
    attn_prefer: str | None = None

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        if d % self.heads:
            raise ValueError(
                f"model dim {d} not divisible by {self.heads} heads"
            )
        head_dim = d // self.heads
        qkv = nn.DenseGeneral(
            (3, self.heads, head_dim), dtype=self.dtype, name="qkv"
        )(x)  # (b, s, 3, h, hd)
        # -> three (b, h, s, hd) tensors for the kernel's layout.
        q, k, v = jnp.moveaxis(qkv, 2, 0)
        q, k, v = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        if self.attn_fn is None:
            o = flash_attention(q, k, v, prefer=self.attn_prefer)
        else:
            o = self.attn_fn(q, k, v)
        o = jnp.swapaxes(o, 1, 2).reshape(b, s, d)
        return nn.Dense(d, dtype=self.dtype, name="out")(o)


class PatchEmbed(nn.Module):
    """Patchify conv + [CLS] token + learned position embeddings."""

    patch: int
    dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.dim,
            (self.patch, self.patch),
            strides=self.patch,
            padding="VALID",
            dtype=self.dtype,
        )(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        cls = self.param(
            "cls", nn.initializers.zeros, (1, 1, self.dim), jnp.float32
        ).astype(self.dtype)
        x = jnp.concatenate([jnp.tile(cls, (b, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, h * w + 1, self.dim),
            jnp.float32,
        ).astype(self.dtype)
        return x + pos


class EncoderBlock(nn.Module):
    """Pre-LN transformer encoder block (residuals kept inside the node, so
    inter-block edges are clean pipeline boundaries)."""

    dim: int
    heads: int
    mlp_dim: int
    dtype: jnp.dtype = jnp.float32
    attn_prefer: str | None = None

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MultiHeadSelfAttention(
            heads=self.heads,
            dtype=self.dtype,
            attn_prefer=self.attn_prefer,
            name="attn",
        )(y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim, dtype=self.dtype)(y)
        return x + y


class ViTHead(nn.Module):
    """Final LN + CLS-token classifier."""

    num_classes: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            x[:, 0].astype(jnp.float32)
        )


def vit(
    patch: int,
    dim: int,
    depth: int,
    heads: int,
    mlp_dim: int,
    num_classes: int = 1000,
    dtype: jnp.dtype = jnp.float32,
    name: str = "vit",
    attn_prefer: str | None = None,
) -> LayerGraph:
    g = LayerGraph(name)
    prev = g.add("patch_embed", PatchEmbed(patch, dim, dtype=dtype), INPUT)
    for i in range(depth):
        prev = g.add(
            f"encoder_block_{i}",
            EncoderBlock(
                dim, heads, mlp_dim, dtype=dtype, attn_prefer=attn_prefer
            ),
            prev,
        )
    g.add("head", ViTHead(num_classes, dtype=dtype), prev)
    return g


def vit_b16(
    num_classes: int = 1000,
    dtype: jnp.dtype = jnp.float32,
    attn_prefer: str | None = None,
) -> LayerGraph:
    """``attn_prefer`` forces the attention path ("pallas"/"xla"); default
    None follows the measured dispatch in ``ops.attention`` (the A/B knob
    behind ``benchmarks/tpu_models.py --attn``)."""
    return vit(
        16, 768, 12, 12, 3072, num_classes, dtype,
        name="vit_b16", attn_prefer=attn_prefer,
    )


def vit_tiny(num_classes: int = 10, dtype: jnp.dtype = jnp.float32) -> LayerGraph:
    """Small ViT for tests (32x32/4 patches, 4 blocks)."""
    return vit(4, 64, 4, 4, 128, num_classes, dtype, name="vit_tiny")


def vit_block_cuts(depth: int, num_stages: int) -> list[str]:
    """Evenly split ``depth`` encoder blocks into ``num_stages`` stages."""
    if num_stages < 2:
        return []
    if num_stages > depth:
        raise ValueError(
            f"cannot split {depth} encoder blocks into {num_stages} stages"
        )
    bounds = []
    for k in range(1, num_stages):
        b = max(1, round(k * depth / num_stages))
        if bounds and b <= bounds[-1]:  # guard banker's-rounding collisions
            b = bounds[-1] + 1
        bounds.append(b)
    return [f"encoder_block_{b - 1}" for b in bounds]
