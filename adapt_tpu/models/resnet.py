"""ResNet-50/101/152 as LayerGraphs with Keras-compatible node names.

The reference's headline workload (``/root/reference/test/test.py:13``
loads Keras ResNet-50 and cuts it at named layers, ``:18``). Here each
residual block is three DAG nodes — branch, (projection) shortcut, merge —
so the graph has real joins and the partitioner's dominator validation is
exercised exactly as on the Keras graph. Merge nodes are named
``conv{S}_block{B}_out`` matching Keras's post-add activation layer names,
so reference cut lists transfer verbatim.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from adapt_tpu.graph.ir import INPUT, LayerGraph, Lambda
from adapt_tpu.models.layers import (
    BottleneckBranch,
    ClassifierHead,
    Projection,
    ResNetStem,
    SpaceToDepthStem,
)

#: blocks per stage (conv2..conv5), Keras ResNetXX layouts.
_DEPTHS = {
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}
_FILTERS = (64, 128, 256, 512)


def _add_relu():
    # Registry-built so the graph ships by value (spec.py verifies merge
    # ops by function identity, not name).
    from adapt_tpu.graph.spec import registered_lambda

    return registered_lambda("add_relu")


def resnet(
    depth: int,
    num_classes: int = 1000,
    dtype: jnp.dtype = jnp.float32,
    stem: str = "conv7",
) -> LayerGraph:
    """``stem='s2d'`` swaps the 7x7/s2 stem conv for the space-to-depth
    + 4x4/s1 form (``layers.SpaceToDepthStem``) — same downsampling and
    receptive-field class, far better MXU tiling for the first conv. Cut
    names are unchanged (the stem is one node either way)."""
    if depth not in _DEPTHS:
        raise ValueError(f"unsupported ResNet depth {depth}; have {list(_DEPTHS)}")
    stems = {"conv7": ResNetStem, "s2d": SpaceToDepthStem}
    if stem not in stems:
        raise ValueError(f"unknown stem {stem!r}; have {sorted(stems)}")
    g = LayerGraph(f"resnet{depth}")
    g.add("stem", stems[stem](dtype=dtype), INPUT)
    prev = "stem"
    for stage_idx, (blocks, filters) in enumerate(
        zip(_DEPTHS[depth], _FILTERS), start=2
    ):
        for b in range(1, blocks + 1):
            name = f"conv{stage_idx}_block{b}"
            strides = 2 if (b == 1 and stage_idx > 2) else 1
            branch = g.add(
                f"{name}_branch",
                BottleneckBranch(filters, strides=strides, dtype=dtype),
                prev,
            )
            if b == 1:
                shortcut = g.add(
                    f"{name}_short",
                    Projection(4 * filters, strides=strides, dtype=dtype),
                    prev,
                )
            else:
                shortcut = prev
            prev = g.add(f"{name}_out", _add_relu(), (shortcut, branch))
    g.add("head", ClassifierHead(num_classes, dtype=dtype), prev)
    return g


def resnet50(
    num_classes: int = 1000,
    dtype: jnp.dtype = jnp.float32,
    stem: str = "conv7",
) -> LayerGraph:
    return resnet(50, num_classes, dtype, stem=stem)


def resnet101(
    num_classes: int = 1000,
    dtype: jnp.dtype = jnp.float32,
    stem: str = "conv7",
) -> LayerGraph:
    return resnet(101, num_classes, dtype, stem=stem)


def resnet152(
    num_classes: int = 1000,
    dtype: jnp.dtype = jnp.float32,
    stem: str = "conv7",
) -> LayerGraph:
    return resnet(152, num_classes, dtype, stem=stem)


#: BASELINE.json config 2: "ResNet-50 split at conv3_block1/conv4_block1
#: into 3 pjit stages" — boundaries at the outputs of the blocks *before*
#: conv3_block1 and conv4_block1 (a cut at layer L means L's output is the
#: boundary, SURVEY.md §2.4).
RESNET50_3STAGE_CUTS = ("conv2_block3_out", "conv3_block4_out")

#: BASELINE.json config 3: ResNet-152 into 8 stages.
RESNET152_8STAGE_CUTS = (
    "conv2_block3_out",
    "conv3_block4_out",
    "conv3_block8_out",
    "conv4_block9_out",
    "conv4_block18_out",
    "conv4_block27_out",
    "conv4_block36_out",
)
