"""Shared flax building blocks for the model zoo.

TPU notes: compute dtype is configurable (bf16 keeps matmuls/convs on the
MXU at full rate; params stay f32). BatchNorm always runs in inference mode
(`use_running_average=True`) — parity scope is inference-only
(SURVEY.md §2.8: the reference has no training path).
"""

from __future__ import annotations

from collections.abc import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


class ConvBN(nn.Module):
    """Conv + BatchNorm(+ optional activation) — the Keras `X_conv`/`X_bn`
    pair the reference's models are made of."""

    features: int
    kernel: tuple[int, int] = (3, 3)
    strides: int = 1
    groups: int = 1
    act: Callable | None = jax.nn.relu
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=self.strides,
            padding="SAME",
            use_bias=False,
            feature_group_count=self.groups,
            dtype=self.dtype,
        )(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        if self.act is not None:
            x = self.act(x)
        return x


class ResNetStem(nn.Module):
    """7x7/2 conv + 3x3/2 maxpool (Keras `conv1_*` + `pool1_pool`)."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = ConvBN(64, (7, 7), strides=2, dtype=self.dtype)(x)
        return nn.max_pool(
            x, window_shape=(3, 3), strides=(2, 2), padding="SAME"
        )


class SpaceToDepthStem(nn.Module):
    """MXU-friendly stem: 2x2 space-to-depth of the image, then a 4x4/s1
    conv (same receptive field class and output shape as the 7x7/s2 conv,
    but stride-1 with 12 input channels instead of a strided conv over 3 —
    the standard TPU ResNet stem transform). Same maxpool after."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        b, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(f"space-to-depth stem needs even H/W, got {h}x{w}")
        # (b, h, w, c) -> (b, h/2, w/2, 4c): each output pixel carries its
        # 2x2 input neighborhood, so stride-2 convs become stride-1.
        x = x.reshape(b, h // 2, 2, w // 2, 2, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
        x = ConvBN(64, (4, 4), strides=1, dtype=self.dtype)(x)
        return nn.max_pool(
            x, window_shape=(3, 3), strides=(2, 2), padding="SAME"
        )


class BottleneckBranch(nn.Module):
    """The residual branch of a ResNet bottleneck block: 1x1 -> 3x3 -> 1x1
    (x4 filters), no activation after the last BN (the add supplies it)."""

    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = ConvBN(self.filters, (1, 1), strides=self.strides, dtype=self.dtype)(x)
        x = ConvBN(self.filters, (3, 3), dtype=self.dtype)(x)
        return ConvBN(4 * self.filters, (1, 1), act=None, dtype=self.dtype)(x)


class Projection(nn.Module):
    """1x1 projection shortcut (Keras `_0_conv`/`_0_bn`)."""

    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return ConvBN(
            self.features, (1, 1), strides=self.strides, act=None, dtype=self.dtype
        )(x)


class ClassifierHead(nn.Module):
    """Global average pool + Dense (Keras `avg_pool` + `predictions`)."""

    num_classes: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = jnp.mean(x, axis=(1, 2))
        # Logits in f32 for stable softmax downstream.
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )


class SqueezeExcite(nn.Module):
    """SE block (EfficientNet): global pool -> reduce -> swish -> expand ->
    sigmoid gate."""

    reduced: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = nn.Conv(self.reduced, (1, 1), dtype=self.dtype)(s)
        s = jax.nn.silu(s)
        s = nn.Conv(x.shape[-1], (1, 1), dtype=self.dtype)(s)
        return x * jax.nn.sigmoid(s)


class MBConvBranch(nn.Module):
    """EfficientNet MBConv body: expand 1x1 -> depthwise kxk -> SE ->
    project 1x1 (no activation after project)."""

    in_filters: int
    out_filters: int
    kernel: int = 3
    strides: int = 1
    expand_ratio: int = 6
    se_ratio: float = 0.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        expanded = self.in_filters * self.expand_ratio
        if self.expand_ratio != 1:
            x = ConvBN(expanded, (1, 1), act=jax.nn.silu, dtype=self.dtype)(x)
        x = ConvBN(
            expanded,
            (self.kernel, self.kernel),
            strides=self.strides,
            groups=expanded,
            act=jax.nn.silu,
            dtype=self.dtype,
        )(x)
        if self.se_ratio > 0:
            x = SqueezeExcite(
                max(1, int(self.in_filters * self.se_ratio)), dtype=self.dtype
            )(x)
        return ConvBN(self.out_filters, (1, 1), act=None, dtype=self.dtype)(x)


class Cast(nn.Module):
    """Dtype cast node (e.g. f32 input -> bf16 compute at the stem)."""

    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        return x.astype(self.dtype)
