"""Model zoo: the reference's benchmark workloads, declared as LayerGraphs.

Reference workloads (BASELINE.json configs): ResNet-50 (``/root/reference/
test/test.py:13``, ``test/local_infer.py:8``), plus ResNet-152,
EfficientNet-B4 and ViT-B/16 from the build targets. Node names follow the
Keras layer naming the reference cuts on (e.g. ``conv3_block1_out``,
``test/test.py:18``) so cut lists transfer directly.
"""

from adapt_tpu.models.efficientnet import efficientnet_b0, efficientnet_b4
from adapt_tpu.models.resnet import resnet50, resnet101, resnet152
from adapt_tpu.models.speculative import speculative_generate
from adapt_tpu.models.transformer_lm import generate, lm_tiny, transformer_lm
from adapt_tpu.models.vit import vit_b16, vit_tiny

#: name -> (graph factory, canonical input shape HWC). Image models only —
#: the decoder LM (``transformer_lm``) takes token ids and has its own
#: generate() loop, so it is exported but not registered here.
MODEL_REGISTRY = {
    "resnet50": (resnet50, (224, 224, 3)),
    "resnet101": (resnet101, (224, 224, 3)),
    "resnet152": (resnet152, (224, 224, 3)),
    "efficientnet_b0": (efficientnet_b0, (224, 224, 3)),
    "efficientnet_b4": (efficientnet_b4, (380, 380, 3)),
    "vit_b16": (vit_b16, (224, 224, 3)),
    "vit_tiny": (vit_tiny, (32, 32, 3)),
}

__all__ = [
    "MODEL_REGISTRY",
    "resnet50",
    "resnet101",
    "resnet152",
    "efficientnet_b0",
    "efficientnet_b4",
    "vit_b16",
    "vit_tiny",
    "transformer_lm",
    "lm_tiny",
    "generate",
    "speculative_generate",
]
