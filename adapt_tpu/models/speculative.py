"""Speculative decoding: draft cheap, verify in one cached pass.

Autoregressive decode is bandwidth-bound — every emitted token streams
all weights once (``benchmarks/lm_decode.py``'s MBU framing). Speculative
decoding buys tokens-per-weight-stream: a cheap DRAFT model proposes
``draft_k`` tokens, the big model scores all of them in ONE cached
forward (``verify_chunk`` — K causal logits against the KV cache for one
weight stream instead of K), and the longest agreeing prefix is accepted
plus one correction token from the big model's own logits. Greedy
speculative decoding is LOSSLESS: the emitted stream is exactly the big
model's greedy stream whatever the draft proposes (the draft only
changes HOW FAST it is produced) — which is the tested contract here:
token-for-token equality with ``generate()``, from a perfect draft
(acceptance 1.0) down to an adversarially wrong one (acceptance 0, one
token per round, still correct).

TPU shape discipline: the per-round programs are two fixed-shape jits —
a ``draft_k + 1``-step draft scan and a ``draft_k + 1``-token verify
chunk — so rounds never recompile regardless of acceptance. Rejected
speculation needs NO rollback on either cache: cache entries past the
accepted position are simply never admitted by the position masks and
get overwritten by later rounds (the same discipline the continuous
batcher's trash slot and the SPMD ring's bubble ticks use). Caches are
allocated with ``draft_k + 1`` slack positions so overshoot writes land
in masked space.

v1 scope: greedy (temperature 0 — where losslessness is exact equality),
batch size 1 (per-row acceptance desynchronizes rows; batch speculation
composes with the continuous batcher later), native-dtype caches. No
reference analog (CNN-only); this is the serving-latency frontier for
the repo's flagship LM workload.

Numerics fine print: "exact equality" assumes the chunked verify and the
sequential decode produce bitwise-equal logits. They run the same ops in
the same dtypes, but XLA may reorder reductions between the (K, L) and
(1, L) shapes; under bf16 a near-tie argmax could then flip a token. The
f32 test suite pins exactness; the hardware benchmark reports a
mismatch count rather than assuming it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from adapt_tpu.models.transformer_lm import TransformerLM


def _modules(lm: TransformerLM):
    g = lm.graph
    return (
        g.node("embed").module,
        [g.node(n).module for n in lm.block_names],
        g.node("head").module,
    )


@partial(jax.jit, static_argnames=("lm", "cache_len"))
def _prefill(lm: TransformerLM, variables, prompt, *, cache_len: int):
    """Full prompt forward building caches padded to ``cache_len``;
    returns (greedy first token (b,), caches)."""
    embed, blocks, head = _modules(lm)
    h = embed.apply(variables["embed"], prompt)
    caches = []
    for name, block in zip(lm.block_names, blocks):
        h, ck, cv = block.apply(
            variables[name], h, cache_len, method="prefill"
        )
        caches.append((ck, cv))
    logits = head.apply(variables["head"], h[:, -1:, :])[:, 0]
    return jnp.argmax(logits, axis=-1).astype(prompt.dtype), caches


@partial(jax.jit, static_argnames=("lm", "n"))
def _draft_chunk(lm: TransformerLM, variables, tok, index, caches, *, n):
    """``n`` greedy decode steps of the draft model: consumes ``tok`` at
    ``index``, returns its next-token chain (n, b) and updated caches."""
    embed, blocks, head = _modules(lm)

    def step(carry, _):
        tok, index, caches = carry
        x = embed.apply(
            variables["embed"], tok[:, None], index, method="embed_at"
        )
        new_caches = []
        for name, block, (ck, cv) in zip(lm.block_names, blocks, caches):
            x, ck, cv = block.apply(
                variables[name], x, ck, cv, index, method="decode_step"
            )
            new_caches.append((ck, cv))
        logits = head.apply(variables["head"], x)[:, 0]
        nxt = jnp.argmax(logits, axis=-1).astype(tok.dtype)
        return (nxt, index + 1, tuple(new_caches)), nxt

    (_, _, caches), toks = lax.scan(
        step, (tok, index, tuple(caches)), None, length=n
    )
    return toks, list(caches)


@partial(jax.jit, static_argnames=("lm",))
def _verify_chunk(lm: TransformerLM, variables, tokens, index, caches):
    """One cached forward over a (b, K) token chunk starting at
    ``index``; returns the big model's greedy prediction AFTER each
    chunk position ((b, K)) and updated caches."""
    embed, blocks, head = _modules(lm)
    kc = tokens.shape[1]
    pos = index + jnp.arange(kc)[None, :]
    x = embed.apply(
        variables["embed"], tokens, pos, method="embed_positions"
    )
    new_caches = []
    for name, block, (ck, cv) in zip(lm.block_names, blocks, caches):
        x, ck, cv = block.apply(
            variables[name], x, ck, cv, index, method="verify_chunk"
        )
        new_caches.append((ck, cv))
    logits = head.apply(variables["head"], x)  # (b, K, V)
    return jnp.argmax(logits, axis=-1).astype(tokens.dtype), new_caches


def speculative_generate(
    lm: TransformerLM,
    variables,
    prompt: jax.Array,
    steps: int,
    draft_lm: TransformerLM,
    draft_variables,
    draft_k: int = 4,
    eos_id: int | None = None,
    return_stats: bool = False,
):
    """Greedy generation accelerated by a draft model; output is
    token-for-token identical to ``generate(lm, variables, prompt,
    steps)`` (and EOS-padded identically when ``eos_id`` is set).

    prompt: (1, s0) int32 ids. ``draft_lm``/``draft_variables`` must
    share the vocab; its quality only affects speed (the per-round
    acceptance), never the output. With ``return_stats`` the emitted
    array comes with {"rounds", "drafted", "accepted", "acceptance"}.
    """
    prompt = jnp.asarray(prompt)
    b, s0 = prompt.shape
    if b != 1:
        raise ValueError(
            f"speculative_generate is single-request (b=1), got b={b}; "
            "batch speculation desynchronizes rows per-round"
        )
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if draft_k < 1:
        raise ValueError(f"draft_k must be >= 1, got {draft_k}")
    if s0 + steps > lm.max_len:
        raise ValueError(
            f"prompt {s0} + steps {steps} exceeds max_len {lm.max_len}"
        )
    if s0 + steps > draft_lm.max_len:
        raise ValueError(
            f"prompt {s0} + steps {steps} exceeds draft max_len "
            f"{draft_lm.max_len}"
        )
    if draft_lm.vocab != lm.vocab:
        raise ValueError(
            f"draft vocab {draft_lm.vocab} != target vocab {lm.vocab}"
        )
    # draft_k + 1 slack: a round's chunk writes up to index + draft_k
    # positions, of which only the accepted prefix ever becomes live.
    cache_len = lm.max_len + draft_k + 1
    draft_cache_len = draft_lm.max_len + draft_k + 1
    d = draft_k

    first, caches = _prefill(lm, variables, prompt, cache_len=cache_len)
    _, dcaches = _prefill(
        draft_lm, draft_variables, prompt, cache_len=draft_cache_len
    )

    emitted = [int(first[0])]
    index = s0  # both models: position where the NEXT consumed token lands
    rounds = drafted = accepted = 0
    while len(emitted) < steps:
        t0 = jnp.asarray([emitted[-1]], prompt.dtype)
        # Draft d proposals (plus one throwaway step so the draft's own
        # cache covers every token the next round may start after).
        dtoks, dcaches = _draft_chunk(
            draft_lm, draft_variables, t0, jnp.asarray(index, jnp.int32),
            dcaches, n=d + 1,
        )
        props = np.asarray(dtoks)[:d, 0]  # d proposals
        chunk = jnp.concatenate(
            [t0[:, None], jnp.asarray(props, prompt.dtype)[None, :]], axis=1
        )  # (1, d+1): [t0, d1..dd]
        preds, caches = _verify_chunk(
            lm, variables, chunk, jnp.asarray(index, jnp.int32), caches
        )
        preds = np.asarray(preds)[0]  # preds[i] = greedy after chunk[i]
        # Longest agreeing prefix: preds[i-1] == d_i.
        a = 0
        while a < d and preds[a] == props[a]:
            a += 1
        new = [int(t) for t in props[:a]] + [int(preds[a])]
        rounds += 1
        drafted += d
        accepted += a
        emitted.extend(new)
        index += a + 1
        if eos_id is not None and eos_id in new:
            break  # finished; the tail below pads with EOS
    emitted = emitted[:steps]
    while len(emitted) < steps:
        emitted.append(eos_id)
    out = np.asarray(emitted, np.int32)[None, :]
    if eos_id is not None:
        # generate()'s convention: a finished row pads with EOS forever.
        hits = np.nonzero(out[0] == eos_id)[0]
        if hits.size:
            out[0, hits[0]:] = eos_id
    if return_stats:
        return out, {
            "rounds": rounds,
            "drafted": drafted,
            "accepted": accepted,
            "acceptance": accepted / drafted if drafted else 0.0,
        }
    return out
