"""Speculative decoding: draft cheap, verify in one cached pass.

Autoregressive decode is bandwidth-bound — every emitted token streams
all weights once (``benchmarks/lm_decode.py``'s MBU framing). Speculative
decoding buys tokens-per-weight-stream: a cheap DRAFT model proposes
``draft_k`` tokens, the big model scores all of them in ONE cached
forward (``verify_chunk`` — K causal logits against the KV cache for one
weight stream instead of K), and the longest agreeing prefix is accepted
plus one correction token from the big model's own logits. Greedy
speculative decoding is LOSSLESS: the emitted stream is exactly the big
model's greedy stream whatever the draft proposes (the draft only
changes HOW FAST it is produced) — which is the tested contract here:
token-for-token equality with ``generate()``, from a perfect draft
(acceptance 1.0) down to an adversarially wrong one (acceptance 0, one
token per round, still correct).

TPU shape discipline: the per-round programs are two fixed-shape jits —
a ``draft_k + 1``-step draft scan (:func:`draft_chunk`) and a
``draft_k + 1``-token verify-and-accept chunk — so rounds never
recompile regardless of acceptance. Both are BATCH-SHAPED: ``index``
may be a (b,) vector, each row drafting/verifying at its own position,
which is what lets the continuous batcher
(``runtime/continuous.ContinuousBatcher`` speculative mode) run them
over desynchronized slots as the same two programs. Rejected
speculation needs NO rollback on either cache: cache entries past the
accepted position are simply never admitted by the position masks and
get overwritten by later rounds (the same discipline the continuous
batcher's trash slot and the SPMD ring's bubble ticks use). Caches are
allocated with ``draft_k + 1`` slack positions so overshoot writes land
in masked space.

Host-transfer discipline (the serving-control-path cost): acceptance is
computed ON DEVICE — the round's longest-agreeing-prefix reduction and
the emitted tokens come back as ONE packed ``(draft_k + 2,)`` fetch per
round (``stats()["host_fetches"]`` counts them; the test suite pins
``rounds + 1``), and the loop re-uploads NOTHING (the next round's
carry token and position stay device-resident). The old loop fetched
the proposals, re-uploaded them into the verify chunk, then fetched the
predictions — three transfers and two syncs per round.

Scope of THIS module's loop: greedy (temperature 0 — where
losslessness is exact equality), native-dtype caches, single-request.
The batched composition lives in the continuous batcher's speculative
mode — which also serves int8 KV caches (``verify_chunk`` /
``verify_chunk_paged`` quantize their appends), int8 draft WEIGHTS
(``SpeculativeConfig.draft_weight_dtype``; :func:`draft_chunk`
dequantizes them in-program), and temperature > 0 requests via
SPECULATIVE SAMPLING: the batcher's verify pass accepts each proposal
with probability ``p_target(x) / p_draft(x)`` (here the draft proposes
its argmax, so a proposal is accepted with the target's own
probability of that token) and resamples rejections from the residual
distribution — lossless in DISTRIBUTION rather than bitwise, the
standard speculative-sampling guarantee. This module's loop stays
greedy; the sampling correction lives in
``runtime/continuous.ContinuousBatcher._spec_verify``.

Numerics fine print: "exact equality" assumes the chunked verify and the
sequential decode produce bitwise-equal logits. They run the same ops in
the same dtypes, but XLA may reorder reductions between the (K, L) and
(1, L) shapes; under bf16 a near-tie argmax could then flip a token. The
f32 test suite pins exactness; the hardware benchmark reports a
mismatch count rather than assuming it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from adapt_tpu.models.transformer_lm import TransformerLM
from adapt_tpu.ops.quantize import dequantize_params


def _modules(lm: TransformerLM):
    g = lm.graph
    return (
        g.node("embed").module,
        [g.node(n).module for n in lm.block_names],
        g.node("head").module,
    )


@partial(jax.jit, static_argnames=("lm", "cache_len"))
def _prefill(lm: TransformerLM, variables, prompt, *, cache_len: int):
    """Full prompt forward building caches padded to ``cache_len``;
    returns (greedy first token (b,), caches)."""
    embed, blocks, head = _modules(lm)
    h = embed.apply(variables["embed"], prompt)
    caches = []
    for name, block in zip(lm.block_names, blocks):
        h, ck, cv = block.apply(
            variables[name], h, cache_len, method="prefill"
        )
        caches.append((ck, cv))
    logits = head.apply(variables["head"], h[:, -1:, :])[:, 0]
    return jnp.argmax(logits, axis=-1).astype(prompt.dtype), caches


@partial(jax.jit, static_argnames=("lm", "n", "tail_w"), donate_argnums=(4,))
def draft_chunk(lm: TransformerLM, variables, tok, index, caches, *, n,
                tail_w=0):
    """``n`` greedy decode steps of the draft model: consumes ``tok``
    ((b,)) at ``index``, returns its next-token chain (n, b) and updated
    caches (donated — the round loop owns them).

    ``index`` is scalar (single-request, every row at one position) or
    (b,) (batched speculation: each slot drafts from its OWN position —
    negative rows are dead slots whose writes clamp into their own
    row's masked space). One compiled program either way; the
    continuous batcher's speculative tick calls this exact jit.

    ``tail_w`` > 0 (tree drafts, ``SpeculativeConfig.tree_width``) also
    harvests each step's TOP-``tail_w`` token ids — grouped sibling
    proposals the verify pass scores as tree leaves. The extra ids come
    from logits the scan already computed (one ``lax.top_k`` per step),
    so widening the tree costs no extra draft forward passes; the
    return becomes ``(toks, (n, b, tail_w) top ids, caches)``.

    ``variables`` may carry int8-quantized matrix leaves
    (``SpeculativeConfig.draft_weight_dtype="int8"``,
    ``ops.quantize.quantize_params``): they dequantize HERE, inside the
    compiled program, so the persistent HBM residency stays int8 and
    the f32 weights exist only for the scan's lifetime."""
    variables = dequantize_params(variables)
    embed, blocks, head = _modules(lm)
    per_row = bool(jnp.ndim(index))

    def step(carry, _):
        tok, index, caches = carry
        if per_row:
            x = embed.apply(
                variables["embed"], tok[:, None], index[:, None],
                method="embed_positions",
            )
        else:
            x = embed.apply(
                variables["embed"], tok[:, None], index, method="embed_at"
            )
        new_caches = []
        for name, block, (ck, cv) in zip(lm.block_names, blocks, caches):
            x, ck, cv = block.apply(
                variables[name], x, ck, cv, index, method="decode_step"
            )
            new_caches.append((ck, cv))
        logits = head.apply(variables["head"], x)[:, 0]
        nxt = jnp.argmax(logits, axis=-1).astype(tok.dtype)
        if tail_w:
            top = lax.top_k(logits, tail_w)[1].astype(tok.dtype)  # (b, w)
            return (nxt, index + 1, tuple(new_caches)), (nxt, top)
        return (nxt, index + 1, tuple(new_caches)), nxt

    (_, _, caches), ys = lax.scan(
        step, (tok, index, tuple(caches)), None, length=n
    )
    if tail_w:
        toks, tops = ys
        return toks, tops, list(caches)
    return ys, list(caches)


def accept_speculation(props, preds):
    """Per-row longest-agreeing-prefix acceptance, on device: ``props``
    (b, d) draft proposals, ``preds`` (b, d+1) target greedy
    predictions after each chunk position. Returns (b,) accepted
    counts ``a`` — the round commits ``preds[:, :a+1]`` (the agreeing
    prefix IS the target's own predictions, plus its correction token),
    which is why greedy speculation is lossless."""
    d = props.shape[1]
    agree = jnp.cumprod(
        (preds[:, :d] == props).astype(jnp.int32), axis=1
    )
    return jnp.sum(agree, axis=1)


@partial(jax.jit, static_argnames=("lm", "d"), donate_argnums=(5,))
def _verify_accept(lm: TransformerLM, variables, t0, dtoks, index, caches,
                   *, d):
    """One verify-and-accept round for the single-request loop: build
    the (1, d+1) chunk ``[t0, proposals]`` ON DEVICE from the draft
    scan's output (no host round-trip), run ``verify_chunk``, reduce
    the agreeing prefix, and return ONE packed (d+2,) int32 vector
    ``[a, preds_0..preds_d]`` (the round's single D2H) plus the next
    round's device-resident carry (next token, next index) and
    caches."""
    embed, blocks, head = _modules(lm)
    props = jnp.swapaxes(dtoks[:d], 0, 1)  # (1, d)
    chunk = jnp.concatenate(
        [t0[:, None], props.astype(t0.dtype)], axis=1
    )  # (1, d+1)
    kc = d + 1
    pos = index + jnp.arange(kc)[None, :]
    x = embed.apply(
        variables["embed"], chunk, pos, method="embed_positions"
    )
    new_caches = []
    for name, block, (ck, cv) in zip(lm.block_names, blocks, caches):
        x, ck, cv = block.apply(
            variables[name], x, ck, cv, index, method="verify_chunk"
        )
        new_caches.append((ck, cv))
    logits = head.apply(variables["head"], x)  # (1, d+1, V)
    preds = jnp.argmax(logits, axis=-1).astype(t0.dtype)  # (1, d+1)
    a = accept_speculation(props, preds)  # (1,)
    packed = jnp.concatenate(
        [a.astype(jnp.int32), preds[0].astype(jnp.int32)]
    )  # (d+2,)
    nxt = jnp.take_along_axis(preds, a[:, None], axis=1)[:, 0]  # (1,)
    return packed, nxt, index + a[0] + 1, new_caches


def speculative_generate(
    lm: TransformerLM,
    variables,
    prompt: jax.Array,
    steps: int,
    draft_lm: TransformerLM,
    draft_variables,
    draft_k: int = 4,
    eos_id: int | None = None,
    return_stats: bool = False,
):
    """Greedy generation accelerated by a draft model; output is
    token-for-token identical to ``generate(lm, variables, prompt,
    steps)`` (and EOS-padded identically when ``eos_id`` is set).

    prompt: (1, s0) int32 ids. ``draft_lm``/``draft_variables`` must
    share the vocab; its quality only affects speed (the per-round
    acceptance), never the output. With ``return_stats`` the emitted
    array comes with {"rounds", "drafted", "accepted", "acceptance",
    "host_fetches"} — ``host_fetches`` counts every device->host
    transfer the loop performed (one packed vector per round plus the
    prefill token; the tests pin it at ``rounds + 1``).
    """
    prompt = jnp.asarray(prompt)
    b, s0 = prompt.shape
    if b != 1:
        raise ValueError(
            f"speculative_generate is single-request (b=1), got b={b}; "
            "batched speculation lives in the continuous batcher "
            "(ContinuousBatcher(draft_lm=...))"
        )
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if draft_k < 1:
        raise ValueError(f"draft_k must be >= 1, got {draft_k}")
    if s0 + steps > lm.max_len:
        raise ValueError(
            f"prompt {s0} + steps {steps} exceeds max_len {lm.max_len}"
        )
    if s0 + steps > draft_lm.max_len:
        raise ValueError(
            f"prompt {s0} + steps {steps} exceeds draft max_len "
            f"{draft_lm.max_len}"
        )
    if draft_lm.vocab != lm.vocab:
        raise ValueError(
            f"draft vocab {draft_lm.vocab} != target vocab {lm.vocab}"
        )
    # draft_k + 1 slack: a round's chunk writes up to index + draft_k
    # positions, of which only the accepted prefix ever becomes live.
    cache_len = lm.max_len + draft_k + 1
    draft_cache_len = draft_lm.max_len + draft_k + 1
    d = draft_k

    first, caches = _prefill(lm, variables, prompt, cache_len=cache_len)
    _, dcaches = _prefill(
        draft_lm, draft_variables, prompt, cache_len=draft_cache_len
    )

    fetches = 1  # the prefill token below
    emitted = [int(first[0])]
    # Device-resident round carry: the last emitted token and the
    # position where the next consumed token lands, for BOTH models —
    # the loop stages nothing back to the device between rounds.
    tok_dev = first  # (1,)
    # One position cursor serves both models: their caches cover the
    # same committed stream.
    index_dev = jnp.asarray(s0, jnp.int32)
    rounds = drafted = accepted = 0
    while len(emitted) < steps:
        # Draft d proposals (plus one throwaway step so the draft's own
        # cache covers every token the next round may start after).
        dtoks, dcaches = draft_chunk(
            draft_lm, draft_variables, tok_dev, index_dev, dcaches,
            n=d + 1,
        )
        packed, tok_dev, index_dev, caches = _verify_accept(
            lm, variables, tok_dev, dtoks, index_dev, caches, d=d
        )
        packed = np.asarray(packed)  # THE round's one device->host sync
        fetches += 1
        a = int(packed[0])
        new = [int(t) for t in packed[1: a + 2]]  # preds[:a+1]
        rounds += 1
        drafted += d
        accepted += a
        emitted.extend(new)
        if eos_id is not None and eos_id in new:
            break  # finished; the tail below pads with EOS
    emitted = emitted[:steps]
    while len(emitted) < steps:
        emitted.append(eos_id)
    out = np.asarray(emitted, np.int32)[None, :]
    if eos_id is not None:
        # generate()'s convention: a finished row pads with EOS forever.
        hits = np.nonzero(out[0] == eos_id)[0]
        if hits.size:
            out[0, hits[0]:] = eos_id
    if return_stats:
        return out, {
            "rounds": rounds,
            "drafted": drafted,
            "accepted": accepted,
            "acceptance": accepted / drafted if drafted else 0.0,
            "host_fetches": fetches,
        }
    return out
