"""Fleet router: the DECISION half of the capacity plane.

PR 19 made every replica self-describing (``runtime/capacity``: a
headroom partition, a self-calibrating TTFT forecaster, a bounded
prefix-affinity sketch, a hysteresis health score — one book per
replica, shipped over telemetry reports and registry leases). This
module spends those signals: a :class:`FleetRouter` owns N decode
replicas and places every submit by scoring each live replica's book —
``affinity_score(sketch, prompt)`` folded into the TTFT forecast,
health and queue pressure as additive penalties — so a resident prefix
on replica A beats a free slot on replica B until A's queue costs more
than the prefill the hit would save.

The scoring formula (docs/SERVING.md "Fleet routing")::

    cost(r) = ttft_forecast_r(len, affinity_tokens_r)   # 0 when cold
            + queue_cost_s * queue_depth_r
            + queue_cost_s * [no free slot]
            + degraded_penalty_s * [health == degraded]
            - rendezvous_bias_s * [r is HOME and no sketch speaks]
            - 1e-6 * affinity_tokens_r                  # pure tiebreak

    place on argmin cost; "critical" replicas are skipped outright
    unless EVERY live replica is critical.

A learned forecaster makes affinity quantitative: the hit tokens
shorten the forecast's prefill suffix, so the router is literally
comparing "prefill what's missing here" against "prefill everything
there".  A cold fleet (no forecast yet) degrades to least-loaded with
affinity as the tiebreak — exactly what an unmeasured replica deserves.

The rendezvous term closes the SKETCH LATENCY window: a prompt's first
full page rendezvous-hashes (highest-random-weight over live replica
names) to one deterministic HOME replica, so a prefix's repeats
co-locate from the very first occurrence — before any page of it has
registered in a sketch — and keep co-locating across membership
changes (HRW moves only the prefixes whose home left). The bias fires
ONLY while every candidate's sketch is silent on the prompt (the cold
window it exists for): once any replica reports real affinity, the
sketch is ground truth and rendezvous must not fight it — a popular
prefix whose first prefill landed off-home (queue pressure, a
membership change) stays where its pages actually are instead of
oscillating. Sized a few ``queue_cost_s``, it decides cold-window ties;
real queue pressure still overrides it, so a hot home sheds load
instead of melting.

Overload sheds synchronously through the PR-10 admission books: the
router runs the chosen replica's ``admission_check`` before anything
else touches the request, walks to the next-best replica on a
rejection, and re-raises ``QueueFullError`` only when EVERY live
replica's book says no (``router.shed_total``).

Cross-replica prefill rides the existing disagg wire: a dedicated
:class:`~adapt_tpu.runtime.disagg.PrefillWorker` tier streams each
finished prefill to the *chosen* decode replica as ``MSG_KV_PAGES``
frames — packed with ``head_ranges`` destination tiles
(``parallel.sharding.head_tiles``) so a tp=2 prefill pool feeds a tp=4
decode replica with the wire already cut into the aligned-union slices
the destination's ``KVHandoffPlan`` places, never a global gather
(2211.05322) — and lands through ``adopt_cached`` as an ordinary
prefix hit.

Elastic membership is the paper's etcd plane promoted to whole
replicas: every replica holds a ``WorkerRegistry`` TTL lease
(``decode:<name>``, book in ``meta["capacity"]``); an external
deregister or TTL expiry is a LEAVE EDGE — the router cancels the dead
replica's in-flight work and re-places it on survivors within
``RouterConfig.recovery_budget_s``, with the per-request
delivered-token watermark suppressing replayed prefixes so greedy
streams stay bit-identical and delivery stays exactly-once. A
:class:`FleetAutoscaler` closes the loop: sustained fleet queue
pressure spawns a replica (``scale_up``), a drained idle replica
retires (``scale_down``), both decided on the same books.

Single-threaded by design, like :class:`DisaggServer`: one
:meth:`FleetRouter.tick` = leave-edge processing -> lease heartbeats ->
prefill step + landings -> autoscale -> one tick per live replica.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import time
from typing import Callable

import numpy as np

from adapt_tpu.comm.framing import frame_parts
from adapt_tpu.config import DisaggConfig, RouterConfig, SLOSpec
from adapt_tpu.control.registry import weak_watch
from adapt_tpu.parallel.sharding import head_tiles
from adapt_tpu.runtime.capacity import (
    affinity_score,
    forecast_from_snapshot,
    prefill_tier_book,
)
from adapt_tpu.runtime.disagg import (
    HandoffError,
    KVHandoff,
    PrefillWorker,
    loopback,
    pack_handoff,
    unpack_handoff,
)
from adapt_tpu.runtime.scheduler import QueueFullError
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.tracing import global_flight_recorder

log = get_logger("router")

#: /fleet/placements payload version.
PLACEMENTS_V = 1

#: Placement-memory LRU bound (first-page prefix key -> replica last
#: placed on). Keys are one page of int32 tokens, so the worst case is
#: a few MB — sized well past any sketch so memory never forgets a
#: prefix the sketches still remember.
_PREFIX_MEMO_CAP = 4096


@dataclasses.dataclass
class _Replica:
    """Router-side view of one decode replica."""

    name: str
    engine: object  # ContinuousBatcher (or duck-typed equivalent)
    lease_key: str
    lease_token: object | None = None
    alive: bool = True
    #: Router sids currently owned by this replica.
    sids: set = dataclasses.field(default_factory=set)
    #: Wall (monotonic) since the replica last had work — the
    #: autoscaler's scale-down clock.
    idle_since: float | None = None
    #: Last lease-meta capacity refresh (monotonic).
    cap_last: float = 0.0


@dataclasses.dataclass
class _Tracked:
    """Router-side request state: where the request lives and how many
    tokens its caller has ALREADY seen (the exactly-once watermark a
    re-placement replays against)."""

    sid: int
    tier: str  # "prefill" | "decode" | "done"
    replica: str | None = None
    rid: int | None = None  # engine-side id once decode-submitted
    prompt: np.ndarray | None = None
    kwargs: dict | None = None
    user_cb: Callable | None = None
    t_submit: float = 0.0
    delivered: int = 0
    replaced: int = 0


class FleetRouter:
    """A serving front-end over N decode replicas (see module
    docstring). Mirrors the batcher's synchronous driver surface
    (``submit`` / ``tick`` / ``cancel`` / ``run`` / ``result`` /
    ``stats`` / ``drain``), so the load harness drives a fleet exactly
    like one replica.

    ``replicas`` maps name -> decode engine (a paged
    ``ContinuousBatcher`` when a ``prefill`` tier is attached — the
    handoff lands through the prefix cache). ``registry`` (a
    ``control.WorkerRegistry``) turns membership on: each replica gets
    a ``decode:<name>`` TTL lease carrying its capacity book, and a
    leave edge on any of those leases triggers re-placement."""

    def __init__(
        self,
        replicas: dict[str, object],
        *,
        prefill: PrefillWorker | None = None,
        config: RouterConfig | None = None,
        disagg: DisaggConfig | None = None,
        registry=None,
        wire_codec: str = "raw",
        seed: int = 0,
        name: str = "router0",
    ):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.cfg = config or RouterConfig()
        self.disagg_cfg = disagg or DisaggConfig()
        self.prefill = prefill
        self.wire_codec = wire_codec
        self.name = name
        self._registry = registry
        self._rng = np.random.RandomState(seed)
        self._replicas: dict[str, _Replica] = {}
        self._tracked: dict[int, _Tracked] = {}
        self._done: dict[int, np.ndarray] = {}
        self._next_sid = 0
        self._closed = False
        #: Leave edges observed by the registry watcher (fires on the
        #: deregistering thread) — drained at the top of every tick.
        self._pending_leaves: list[str] = []
        #: Lease keys WE are deregistering right now (graceful detach
        #: must not read as a crash in our own watcher).
        self._our_deregs: set = set()
        #: Placement-decision ring — what ``GET /fleet/placements``
        #: serves (via :meth:`placements` as the exporter provider).
        self._decisions: collections.deque = collections.deque(
            maxlen=self.cfg.placements_capacity
        )
        self._autoscaler = None
        #: Placement memory: first-page prefix key -> replica this
        #: router LAST placed it on. Ground truth for the sketch
        #: latency window — for a prefix the router itself routed
        #: moments ago, where it SENT the prefill beats any hash.
        #: Bounded LRU; entries pointing at a left replica are purged
        #: on the leave edge so those prefixes re-home.
        self._placed_prefix: collections.OrderedDict = (
            collections.OrderedDict()
        )
        # Books: placed/shed/replaced live in stats() AND as router.*
        # counters; affinity_hit_ratio is cumulative placements that
        # found a resident prefix on the replica they landed on.
        self.placed = 0
        self.shed = 0
        self.replaced = 0
        self.failed = 0
        self._affinity_hits = 0
        for rname, engine in replicas.items():
            self.add_replica(rname, engine, _join_event=False)
        if self._registry is not None:
            # WEAK subscription: watcher lists have no unwatch and
            # outlive subscribers — a closed router must not be pinned
            # by the registry (control.registry.weak_watch's contract).
            weak_watch(self._registry, self, "_on_watch")

    # -- membership --------------------------------------------------------

    def _check_compat(self, name: str, engine) -> None:
        if self.prefill is None:
            return
        if not getattr(engine, "_paged", False):
            raise ValueError(
                f"replica {name!r} is not paged — a prefill-tier "
                "router lands handoffs through the prefix cache"
            )
        if self.prefill.page_size != engine._page:
            raise ValueError(
                f"prefill page size {self.prefill.page_size} != "
                f"replica {name!r} page size {engine._page}"
            )
        if self.prefill.kv_cache_dtype != engine._kv_dtype:
            raise ValueError(
                f"prefill/replica kv_cache_dtype mismatch on {name!r}"
            )
        if self.prefill.lm.vocab != engine.lm.vocab:
            raise ValueError(f"prefill/replica vocab mismatch on {name!r}")

    def add_replica(self, name: str, engine, _join_event: bool = True):
        """Join edge: validate, lease, place-eligible from the next
        submit. The autoscaler's scale-up path lands here too."""
        if name in self._replicas and self._replicas[name].alive:
            raise ValueError(f"replica {name!r} already attached")
        self._check_compat(name, engine)
        rep = _Replica(
            name=name, engine=engine, lease_key=f"decode:{name}"
        )
        if self._registry is not None:
            rep.lease_token = self._registry.register(
                rep.lease_key,
                meta=self._lease_meta(rep),
                ttl_s=self.cfg.lease_ttl_s,
            )
        self._replicas[name] = rep
        if _join_event:
            global_flight_recorder().record(
                "replica_join", replica=name, fleet=len(self._live())
            )
        return rep

    def _lease_meta(self, rep: _Replica) -> dict:
        meta = {"role": "decode", "router": self.name}
        book = None
        cap_book = getattr(rep.engine, "capacity_book", None)
        if callable(cap_book):
            book = cap_book()
        if book is not None:
            meta["capacity"] = book
        return meta

    def _on_watch(self, event: str, worker_id) -> None:
        if event != "leave":
            return
        wid = str(worker_id)
        if not wid.startswith("decode:") or wid in self._our_deregs:
            return
        name = wid.split(":", 1)[1]
        rep = self._replicas.get(name)
        if rep is not None and rep.alive:
            self._pending_leaves.append(name)

    def _live(self) -> list[_Replica]:
        return [r for r in self._replicas.values() if r.alive]

    def detach(self, name: str) -> None:
        """Graceful leave (the autoscaler's scale-down path): release
        the lease, stop placing. The replica must be idle — a graceful
        detach never strands work (use :meth:`mark_failed` to model a
        crash)."""
        rep = self._replicas.get(name)
        if rep is None or not rep.alive:
            return
        st = rep.engine.stats()
        if st.get("active") or st.get("queued") or rep.sids:
            raise ValueError(
                f"replica {name!r} still holds work — detach is for "
                "drained replicas"
            )
        rep.alive = False
        self._drop_lease(rep)
        global_flight_recorder().record(
            "replica_leave", replica=name, reason="drain", moved=0,
            fleet=len(self._live()),
        )

    def mark_failed(self, name: str) -> None:
        """Crash-model leave edge: mark dead NOW and re-place its
        unfinished work on survivors (same path a lease-expiry watch
        event takes at the next tick)."""
        self._leave_edge(name)

    def _drop_lease(self, rep: _Replica) -> None:
        if self._registry is None or rep.lease_token is None:
            return
        self._our_deregs.add(rep.lease_key)
        try:
            self._registry.deregister(rep.lease_key, rep.lease_token)
        finally:
            self._our_deregs.discard(rep.lease_key)
            rep.lease_token = None

    # -- placement scoring -------------------------------------------------

    def _book(self, rep: _Replica) -> dict | None:
        cap_book = getattr(rep.engine, "capacity_book", None)
        book = cap_book() if callable(cap_book) else None
        if book is None:
            return None
        age = time.time() - float(book.get("wall") or 0.0)
        if age > self.cfg.book_max_age_s:
            return None  # stale book = no capacity signal at all
        return book

    def _prefix_key(self, prompt, cands: list[_Replica]) -> bytes | None:
        """The prompt's first full page as bytes — the identity
        co-location is remembered and rendezvous-hashed under. None
        when the prompt has no full page (nothing recurring to
        co-locate) or the engines aren't paged."""
        page = getattr(cands[0].engine, "_page", 0) if cands else 0
        if not page or int(prompt.shape[0]) < page:
            return None
        return np.asarray(prompt[:page], np.int32).tobytes()

    def _home(self, key: bytes, cands: list[_Replica]) -> str | None:
        """The prefix's HOME among ``cands``: the replica this router
        LAST PLACED it on if still a candidate — the router's own
        recent routing is ground truth for the window before that
        prefill registers in any sketch — else the rendezvous
        (highest-random-weight) hash of (key, replica name).
        Rendezvous is deterministic, sketch-independent, and minimally
        disruptive under membership churn (a replica joining or
        leaving re-homes only the prefixes that hashed to it), so
        repeats of a never-seen prefix co-locate from the very first
        occurrence even across router restarts. The bias is applied in
        :meth:`_rank`, and only while every candidate's sketch is
        silent on this prompt — sketches are ground truth; home only
        covers the window before the first prefill registers."""
        placed = self._placed_prefix.get(key)
        if placed is not None and any(r.name == placed for r in cands):
            return placed
        return max(
            cands,
            key=lambda r: hashlib.blake2b(
                key + r.name.encode(), digest_size=8
            ).digest(),
        ).name

    def _remember_placement(self, prompt, name: str) -> None:
        key = self._prefix_key(prompt, self._live())
        if key is None:
            return
        self._placed_prefix[key] = name
        self._placed_prefix.move_to_end(key)
        while len(self._placed_prefix) > _PREFIX_MEMO_CAP:
            self._placed_prefix.popitem(last=False)

    def _cost(self, rep: _Replica, prompt, s0: int) -> dict:
        """One replica's placement cost and its WHY (the
        ``/fleet/placements`` record)."""
        cfg = self.cfg
        book = self._book(rep)
        if book is None:
            # No (or stale) book: least-loaded on live stats — an
            # in-process engine always answers, a remote one with a
            # dead book simply scores as pure pressure.
            st = rep.engine.stats()
            queued = int(st.get("queued", 0)) + int(st.get("active", 0))
            return {
                "health": "unknown",
                "affinity_tokens": 0,
                "forecast_s": 0.0,
                "queue_depth": queued,
                "home": False,
                "cost": cfg.queue_cost_s * queued,
            }
        hr = book.get("headroom") or {}
        health = str(book.get("health", "ok"))
        aff = 0.0
        if cfg.policy == "affinity":
            aff = affinity_score(book.get("sketch") or {}, prompt)
        hit_tokens = int(aff)
        queued = int(hr.get("queue_depth", 0))
        slots_free = int(hr.get("slots_free", 0))
        fc = 0.0
        if cfg.policy != "random":
            snap = book.get("forecast") or {}
            if queued == 0 and slots_free > 0 and snap.get(
                "queue_wait_s"
            ):
                # Internal-consistency clamp: a book whose headroom
                # shows an IDLE engine (empty queue, free slots)
                # cannot also claim a queue wait — that is a stale
                # EWMA from traffic it is no longer getting. Without
                # this, a replica that once looked slow never gets
                # the traffic that would prove otherwise (the
                # starvation death spiral: its queue-wait memory only
                # decays through admissions it is never offered).
                snap = dict(snap, queue_wait_s=0.0)
            fc = forecast_from_snapshot(snap, s0, hit_tokens)
        cost = fc
        cost += cfg.queue_cost_s * queued
        if slots_free <= 0:
            cost += cfg.queue_cost_s
        if health == "degraded":
            cost += cfg.degraded_penalty_s
        cost -= 1e-6 * hit_tokens
        return {
            "health": health,
            "affinity_tokens": hit_tokens,
            "forecast_s": round(fc, 6),
            "queue_depth": queued,
            "home": False,
            "cost": cost,
        }

    def _rank(self, prompt, s0: int, exclude: set | None = None):
        """Live replicas in placement order (best first) with their
        scoring records. Critical replicas sort behind every
        non-critical one; the random policy shuffles instead (its
        scores are still computed — the decision ring shows what
        affinity WOULD have said)."""
        cands = [
            r for r in self._live()
            if not exclude or r.name not in exclude
        ]
        scored = [(r, self._cost(r, prompt, s0)) for r in cands]
        if (
            self.cfg.policy == "affinity"
            and self.cfg.rendezvous_bias_s > 0
            and len(scored) > 1
            and all(w["affinity_tokens"] == 0 for _, w in scored)
        ):
            # Cold window: no sketch has seen this prefix yet (its
            # first prefill may literally be in flight). Pull the
            # placement toward the HOME — placement memory first,
            # rendezvous hash for the never-seen — so back-to-back
            # repeats co-locate instead of load-balancing apart.
            key = self._prefix_key(prompt, cands)
            home = self._home(key, cands) if key is not None else None
            for r, w in scored:
                if r.name == home:
                    w["home"] = True
                    w["cost"] -= self.cfg.rendezvous_bias_s
        if self.cfg.policy == "random":
            order = self._rng.permutation(len(scored))
            return [scored[i] for i in order]
        scored.sort(
            key=lambda t: (t[1]["health"] == "critical", t[1]["cost"])
        )
        return scored

    def _record_decision(
        self, kind: str, sid: int, chosen: str, why: dict, ranked
    ) -> None:
        self._decisions.append(
            {
                "kind": kind,
                "sid": sid,
                "replica": chosen,
                "policy": self.cfg.policy,
                "why": why,
                "alternatives": {
                    r.name: round(w["cost"], 6)
                    for r, w in ranked
                    if r.name != chosen
                },
                "wall": time.time(),
            }
        )

    def placements(self) -> dict:
        """The ``GET /fleet/placements`` payload (pass this method to
        ``serve_metrics(placements_provider=...)``): the bounded
        decision ring plus the fleet roster — why every recent request
        landed where it did."""
        return {
            "v": PLACEMENTS_V,
            "router": self.name,
            "policy": self.cfg.policy,
            "replicas": {
                r.name: {"alive": r.alive, "requests": len(r.sids)}
                for r in self._replicas.values()
            },
            "decisions": list(self._decisions),
        }

    # -- request lifecycle -------------------------------------------------

    def submit(
        self,
        prompt,
        steps: int,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
        rng=None,
        stop: list | None = None,
        on_token: Callable[[int, int, int], None] | None = None,
        slo: SLOSpec | None = None,
    ) -> int:
        """Place one request; returns the ROUTER-side id (use it with
        :meth:`cancel` / :meth:`result`; callbacks see it too). Raises
        ``QueueFullError`` only when every live replica's admission
        book rejects — the synchronous shed path."""
        t0 = time.perf_counter()
        live = self._live()
        if not live:
            raise RuntimeError("no live replicas")
        # THE decode-side validation body, once, against any replica
        # (the fleet serves one model): a bad request fails here
        # synchronously, never after routing.
        prompt, _ = live[0].engine.validate_request(
            prompt, steps, temperature=temperature, top_k=top_k,
            top_p=top_p, rng=rng, stop=stop, slo=slo,
        )
        s0 = int(prompt.shape[0])
        sid = self._next_sid
        self._next_sid += 1
        t = _Tracked(
            sid=sid, tier="decode", prompt=prompt, user_cb=on_token,
            t_submit=time.perf_counter(),
        )
        t.kwargs = dict(
            steps=steps, temperature=temperature, top_k=top_k,
            top_p=top_p, eos_id=eos_id, rng=rng, stop=stop, slo=slo,
        )
        ranked = self._rank(prompt, s0)
        chosen, why, rejection = None, None, None
        for rep, score in ranked:
            try:
                rep.engine.admission_check(slo, request=sid)
            except QueueFullError as e:
                rejection = e
                continue
            chosen, why = rep, score
            break
        if chosen is None:
            # Every live replica's admission book said no: shed
            # synchronously (each engine recorded its own rejection).
            self.shed += 1
            global_metrics().inc("router.shed_total")
            self._record_decision("shed", sid, "", {"cost": 0.0}, ranked)
            raise rejection if rejection is not None else QueueFullError(
                "all replicas rejected"
            )
        self._tracked[sid] = t
        t.replica = chosen.name
        chosen.sids.add(sid)
        chosen.idle_since = None
        if self.cfg.policy == "affinity":
            self._remember_placement(prompt, chosen.name)
        if self.prefill is not None and self._disaggregate(chosen, s0, slo):
            t.tier = "prefill"
            self.prefill.submit(sid, prompt)
        else:
            self._decode_submit(t, chosen)
        self.placed += 1
        if why.get("affinity_tokens", 0) > 0:
            self._affinity_hits += 1
        reg = global_metrics()
        reg.inc("router.placed_total")
        reg.set_gauge(
            "router.affinity_hit_ratio",
            self._affinity_hits / self.placed,
        )
        reg.observe("router.placement_s", time.perf_counter() - t0)
        self._record_decision("placed", sid, chosen.name, why, ranked)
        return sid

    def _disaggregate(
        self, rep: _Replica, s0: int, slo: SLOSpec | None
    ) -> bool:
        """DisaggServer's placement policy, per chosen replica: full
        pages to hand off, prompt over the (busy-sensitive) threshold,
        and a prefill pool that can actually cover it."""
        page = rep.engine._page
        m = (s0 - 1) // page
        if m < 1:
            return False
        slots = rep.engine.slots
        occupancy = sum(
            1 for s in slots if s.req is not None
        ) / len(slots)
        busy = occupancy >= self.disagg_cfg.busy_occupancy or (
            slo is not None and slo.priority > 0
        )
        threshold = (
            self.disagg_cfg.busy_prompt_threshold
            if busy
            else self.disagg_cfg.prompt_threshold
        )
        if s0 < threshold:
            return False
        if m > self.prefill._pager.num_allocatable and not (
            self.prefill.sp_eligible(s0)
        ):
            return False
        return True

    def _make_cb(self, t: _Tracked):
        """Exactly-once delivery across re-placements: the engine
        invokes this with its OWN rid and in-order token indices; the
        caller sees the router sid, and any index below the delivered
        watermark is a replayed prefix from a re-placed (greedy,
        deterministic) request — suppressed, never delivered twice."""

        def cb(rid, tok, idx, _t=t):
            if idx < _t.delivered:
                return
            _t.delivered = idx + 1
            if _t.user_cb is not None:
                _t.user_cb(_t.sid, tok, idx)

        return cb

    def _decode_submit(self, t: _Tracked, rep: _Replica) -> None:
        kwargs = dict(t.kwargs)
        kwargs["on_token"] = self._make_cb(t)
        t.rid = rep.engine.submit(
            t.prompt, t_submit=t.t_submit, **kwargs
        )
        t.tier = "decode"
        t.replica = rep.name
        rep.sids.add(t.sid)

    def cancel(self, sid: int) -> bool:
        t = self._tracked.get(sid)
        if t is None or t.tier == "done":
            return False
        if t.tier == "decode":
            rep = self._replicas.get(t.replica)
            if rep is None:
                return False
            if rep.engine.cancel(t.rid):
                rep.sids.discard(sid)
                return True
            return False
        if self.prefill is not None and self.prefill.cancel(sid):
            self._finish_empty(t, "cancelled")
            global_flight_recorder().record(
                "cancel", request=sid, state="prefill"
            )
            global_flight_recorder().record(
                "finish", request=sid, reason="cancelled", tokens=0
            )
            return True
        return False

    def _finish_empty(self, t: _Tracked, reason: str) -> None:
        self._done[t.sid] = np.zeros((0,), np.int32)
        rep = self._replicas.get(t.replica or "")
        if rep is not None:
            rep.sids.discard(t.sid)
        t.tier = "done"
        t.kwargs = t.prompt = None

    def _fail(self, sid: int, err: Exception) -> None:
        """A request that can no longer be served fails CLEANLY: empty
        result, loud flight events, the fleet keeps serving."""
        t = self._tracked.get(sid)
        self.failed += 1
        if t is not None:
            self._finish_empty(t, "failed")
        else:
            self._done[sid] = np.zeros((0,), np.int32)
        global_flight_recorder().record(
            "request_failed", request=sid, reason=str(err)[:200]
        )
        global_flight_recorder().record(
            "finish", request=sid, reason="failed", tokens=0
        )
        log.error("router failed request %d: %s", sid, err)

    # -- cross-replica handoff landing -------------------------------------

    def _head_ranges(self, rep: _Replica, handoff: KVHandoff):
        """Destination head tiles for sender-side resharding: the
        chosen replica's tp cuts the wire. None = unsharded
        destination (or heads that don't tile) — whole-leaf frames,
        today's wire."""
        mesh = getattr(rep.engine, "_mesh", None)
        if mesh is None:
            return None
        tp = int(dict(mesh.shape).get("tp", 1))
        if tp <= 1 or not handoff.blocks:
            return None
        k0 = handoff.blocks[0][0]
        kv_heads = int(
            (k0[0] if isinstance(k0, tuple) else k0).shape[1]
        )
        if kv_heads % tp:
            return None
        return head_tiles(kv_heads, tp)

    def _land(self, handoff: KVHandoff) -> None:
        """Stream one finished prefill to its CHOSEN replica: frame
        (sender-side resharded) -> loopback wire -> parse -> adopt ->
        decode submit. A replica lost since placement re-scores here —
        the handoff follows the work, not the corpse."""
        sid = handoff.req_id
        t = self._tracked.get(sid)
        if t is None or t.tier != "prefill":
            return  # cancelled between chunk passes and handoff
        rep = self._replicas.get(t.replica or "")
        if rep is None or not rep.alive:
            ranked = self._rank(t.prompt, int(t.prompt.shape[0]))
            if not ranked:
                self._fail(sid, RuntimeError("no live replicas"))
                return
            rep, why = ranked[0]
            self._record_decision("replaced", sid, rep.name, why, ranked)
        t0 = time.perf_counter()
        try:
            ranges = self._head_ranges(rep, handoff)
            msg = pack_handoff(
                handoff, wire_codec=self.wire_codec, head_ranges=ranges
            )
            wire_bytes = sum(
                p.nbytes if isinstance(p, memoryview) else len(p)
                for p in frame_parts(msg)
            )
            landed = unpack_handoff(loopback(msg))
            adopted = rep.engine.adopt_prefill_pages(
                landed.prompt,
                landed.blocks,
                landed.page_size,
                landed.kv_dtype,
            )
        except (HandoffError, ValueError) as e:
            self._fail(sid, e)
            return
        wall = time.perf_counter() - t0
        reg = global_metrics()
        # Same wire books as the single-replica DisaggServer — one
        # dashboard reads both deployments.
        reg.inc("disagg.handoff_bytes", float(wire_bytes))
        reg.inc("disagg.pages_streamed", float(handoff.n_pages))
        reg.observe("disagg.handoff_s", wall)
        global_flight_recorder().record(
            "kv_handoff",
            request=sid,
            replica=rep.name,
            pages=handoff.n_pages,
            adopted=adopted,
            bytes=wire_bytes,
            tiles=len(ranges) if ranges else 1,
            wall_s=round(wall, 6),
        )
        try:
            self._decode_submit(t, rep)
        except (ValueError, TypeError, QueueFullError) as e:
            self._fail(sid, e)

    # -- leave edges / re-placement ----------------------------------------

    def _leave_edge(self, name: str) -> None:
        rep = self._replicas.get(name)
        if rep is None or not rep.alive:
            return
        t0 = time.perf_counter()
        rep.alive = False
        self._drop_lease(rep)
        # Forget placements onto the corpse: those prefixes re-home
        # (memory of a re-placement below, rendezvous otherwise).
        for k in [
            k for k, v in self._placed_prefix.items() if v == name
        ]:
            del self._placed_prefix[k]
        moved = 0
        stranded = [
            self._tracked[sid]
            for sid in sorted(rep.sids)
            if sid in self._tracked
        ]
        rep.sids.clear()
        for t in stranded:
            if t.tier == "done":
                continue
            if t.tier == "decode":
                try:
                    rep.engine.cancel(t.rid)
                except Exception:  # noqa: BLE001 — a dead engine may
                    pass  # refuse; the re-place below is the recovery
            if t.tier == "prefill":
                # The prefill tier outlives the replica; the handoff
                # re-scores at landing (_land). Nothing to move yet.
                t.replica = None
                continue
            ranked = self._rank(
                t.prompt, int(t.prompt.shape[0]), exclude={name}
            )
            placed = False
            for cand, why in ranked:
                try:
                    cand.engine.admission_check(
                        t.kwargs.get("slo"), request=t.sid
                    )
                    self._decode_submit(t, cand)
                except (QueueFullError, ValueError) as e:  # noqa: PERF203
                    last = e
                    continue
                t.replaced += 1
                moved += 1
                if self.cfg.policy == "affinity":
                    self._remember_placement(t.prompt, cand.name)
                self._record_decision(
                    "replaced", t.sid, cand.name, why, ranked
                )
                placed = True
                break
            if not placed:
                self._fail(
                    t.sid,
                    last if ranked else RuntimeError("no live replicas"),
                )
        wall = time.perf_counter() - t0
        self.replaced += moved
        if moved:
            global_metrics().inc("router.replaced_total", float(moved))
        global_flight_recorder().record(
            "replica_leave",
            replica=name,
            reason="lost",
            moved=moved,
            wall_s=round(wall, 6),
            fleet=len(self._live()),
        )
        if wall > self.cfg.recovery_budget_s:
            log.error(
                "leave-edge re-place for %s took %.3fs (budget %.3fs)",
                name, wall, self.cfg.recovery_budget_s,
            )

    # -- tick loop ---------------------------------------------------------

    def attach_autoscaler(self, autoscaler: "FleetAutoscaler") -> None:
        self._autoscaler = autoscaler

    def tick(self) -> int:
        """One fleet scheduling round; returns the fleet's active-slot
        count. Order matters: leave edges first (a dead replica must
        not receive this round's landings), then leases, prefill
        landings, autoscale, one decode tick per live replica."""
        while self._pending_leaves:
            self._leave_edge(self._pending_leaves.pop(0))
        now = time.monotonic()
        if self._registry is not None and not self._closed:
            for rep in self._live():
                if not self._registry.heartbeat(
                    rep.lease_key, self.cfg.lease_ttl_s
                ):
                    # TTL lapsed between ticks (long compile gap) but
                    # the engine is self-evidently alive — keepalive
                    # re-register, etcd semantics (DisaggServer's
                    # discipline). An EXTERNAL deregister is different:
                    # the watcher queued a leave edge above and the
                    # replica is no longer in _live().
                    rep.lease_token = self._registry.register(
                        rep.lease_key,
                        meta=self._lease_meta(rep),
                        ttl_s=self.cfg.lease_ttl_s,
                    )
                cap = getattr(rep.engine, "_capacity", None)
                lease_s = cap.cfg.lease_refresh_s if cap else 0.0
                if lease_s > 0 and now - rep.cap_last >= lease_s:
                    rep.cap_last = now
                    rep.lease_token = self._registry.register(
                        rep.lease_key,
                        meta=self._lease_meta(rep),
                        ttl_s=self.cfg.lease_ttl_s,
                    )
        if self.prefill is not None:
            for handoff in self.prefill.step():
                self._land(handoff)
            if self.prefill.failed_jobs:
                for sid, err in self.prefill.failed_jobs:
                    self._fail(sid, RuntimeError(err))
                self.prefill.failed_jobs.clear()
        if self._autoscaler is not None:
            self._autoscaler.step(now)
        active = 0
        failed: list[str] = []
        for rep in self._live():
            try:
                active += rep.engine.tick()
            except Exception as e:  # noqa: BLE001 — one replica's
                # crash must not take the fleet down: mark it failed
                # and re-place its work (same edge as a lost lease).
                log.exception("replica %s tick failed: %s", rep.name, e)
                failed.append(rep.name)
            st = rep.engine.stats()
            if st.get("active") or st.get("queued"):
                rep.idle_since = None
            elif rep.idle_since is None:
                rep.idle_since = now
        for name in failed:
            self._leave_edge(name)
        self._claim_finished()
        return active

    def _claim_finished(self) -> None:
        """Move engine-finished results into the router's done map —
        replicas' ``_done`` dicts must not grow while a driver only
        polls the router."""
        for rep in self._live():
            if not rep.sids:
                continue
            cv = getattr(rep.engine, "_cv", None)
            eng_done = getattr(rep.engine, "_done", None)
            if cv is None or eng_done is None:
                continue
            with cv:
                for sid in list(rep.sids):
                    t = self._tracked.get(sid)
                    if t is None or t.tier != "decode":
                        continue
                    if t.rid in eng_done:
                        self._done[sid] = eng_done.pop(t.rid)
                        rep.sids.discard(sid)
                        t.tier = "done"
                        t.kwargs = t.prompt = None

    def drain(self) -> int:
        """Commit every live replica's in-flight pipelined round (the
        phase boundary the harness reaches for)."""
        return sum(rep.engine.drain() for rep in self._live())

    def _busy(self) -> bool:
        if self.prefill is not None and self.prefill.pending():
            return True
        for rep in self._live():
            st = rep.engine.stats()
            if st.get("active") or st.get("queued"):
                return True
        return any(
            t.tier != "done"
            for t in self._tracked.values()
            if t.sid not in self._done
        )

    def run(self, max_ticks: int = 100_000) -> dict[int, np.ndarray]:
        """Tick until every submitted request completed; returns
        ``{router_id: tokens}`` (failed/cancelled requests map to
        empty arrays) and clears the finished set."""
        ticks = 0
        while self._busy():
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(f"run() exceeded {max_ticks} ticks")
        self.drain()
        self.tick()  # claim the drained round's results
        out = dict(self._done)
        self._done = {}
        for sid in out:
            self._tracked.pop(sid, None)
        return out

    def result(self, sid: int, max_ticks: int = 100_000) -> np.ndarray:
        """Drive ticks until ``sid`` finishes; returns (and claims)
        its tokens — empty for a failed or cancelled request, never a
        wedge."""
        ticks = 0
        while True:
            if sid in self._done:
                self._tracked.pop(sid, None)
                return self._done.pop(sid)
            t = self._tracked.get(sid)
            if t is None:
                raise KeyError(f"unknown request {sid}")
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"result({sid}) exceeded {max_ticks} ticks"
                )

    # -- harness / exporter surface ----------------------------------------

    @property
    def lm(self):
        return self._live()[0].engine.lm

    @property
    def prompt_buckets(self):
        return self._live()[0].engine.prompt_buckets

    def capacity_book(self) -> dict | None:
        """The fleet as ONE capacity source (what a router process
        hands ``serve_metrics(capacity_provider=...)``): the best
        replica's book shape with fleet-summed headroom, the prefill
        tier nested like a DisaggServer's."""
        live = self._live()
        books = [
            b for b in (self._book(r) for r in live) if b is not None
        ]
        if not books:
            return None
        book = dict(books[0])
        hr: dict = {"replicas": len(live)}
        for b in books:
            for k, v in (b.get("headroom") or {}).items():
                if isinstance(v, (int, float)):
                    hr[k] = hr.get(k, 0) + v
        book["headroom"] = hr
        if self.prefill is not None:
            book["prefill"] = prefill_tier_book(self.prefill)
        return book

    def stats(self) -> dict:
        """Fleet-summed driver stats plus the router's own books.
        ``queued`` covers the whole fleet INCLUDING the prefill tier
        (a driver's drain loop must see tiered work)."""
        live = self._live()
        out: dict = {}
        for rep in live:
            for k, v in rep.engine.stats().items():
                if isinstance(v, (int, float)) and not isinstance(
                    v, bool
                ):
                    out[k] = out.get(k, 0) + v
        if live:
            out["ticks"] = max(
                rep.engine.stats().get("ticks", 0) for rep in live
            )
        if self.prefill is not None:
            pf = self.prefill.stats()
            out["prefill_queued"] = pf["queued"]
            out["prefill_active"] = pf["active"]
            out["queued"] = out.get("queued", 0) + pf["queued"] + (
                pf["active"]
            )
        out.update(
            replicas_live=len(live),
            replicas_total=len(self._replicas),
            placed=self.placed,
            shed=self.shed,
            replaced=self.replaced,
            router_failed=self.failed,
        )
        return out

    def close(self, close_engines: bool = False) -> None:
        """Release every lease and stop. Engines are the caller's
        unless ``close_engines`` (autoscaler-spawned fleets)."""
        self._closed = True
        for rep in self._replicas.values():
            if rep.alive:
                self._drop_lease(rep)
        if close_engines:
            for rep in self._replicas.values():
                try:
                    rep.engine.close()
                except Exception:  # noqa: BLE001
                    pass


class FleetAutoscaler:
    """Scale the fleet on the same books the router places by.

    UP: fleet queue occupancy (queued / summed queue bound, live
    stats) holds above ``RouterConfig.scale_up_queue_frac`` for
    ``autoscale_dwell_s`` and the fleet is below ``max_replicas`` —
    ``spawn()`` builds a replica (name, engine) and the router joins
    it, BEFORE attainment breaks (pressure is the leading signal; a
    missed SLO is the lagging one). DOWN: a replica sits fully idle
    for ``scale_down_idle_s`` and the fleet is above ``min_replicas``
    — graceful detach (it holds no work by definition). Both edges
    land in the flight stream (``scale_up`` / ``scale_down``)."""

    def __init__(
        self,
        router: FleetRouter,
        spawn: Callable[[int], tuple[str, object]],
        config: RouterConfig | None = None,
    ):
        self.router = router
        self.spawn = spawn
        self.cfg = config or router.cfg
        self._pressure_since: float | None = None
        self._spawned = 0
        self.scale_ups = 0
        self.scale_downs = 0
        router.attach_autoscaler(self)

    def _pressure(self) -> float:
        queued = bound = 0
        for rep in self.router._live():
            st = rep.engine.stats()
            queued += int(st.get("queued", 0))
            # The queue bound lives in the book's headroom; fall back
            # to slots when the capacity plane is off.
            book = rep.engine.capacity_book() if callable(
                getattr(rep.engine, "capacity_book", None)
            ) else None
            hr = (book or {}).get("headroom") or {}
            bound += int(hr.get("queue_bound", 0)) or len(
                rep.engine.slots
            )
        return queued / bound if bound else 0.0

    def step(self, now: float) -> None:
        router, cfg = self.router, self.cfg
        live = router._live()
        frac = self._pressure()
        if frac >= cfg.scale_up_queue_frac and len(live) < (
            cfg.max_replicas
        ):
            if self._pressure_since is None:
                self._pressure_since = now
            elif now - self._pressure_since >= cfg.autoscale_dwell_s:
                self._pressure_since = None
                self._spawned += 1
                name, engine = self.spawn(self._spawned)
                router.add_replica(name, engine)
                self.scale_ups += 1
                global_flight_recorder().record(
                    "scale_up",
                    replica=name,
                    queue_frac=round(frac, 4),
                    fleet=len(router._live()),
                )
        else:
            self._pressure_since = None
        if len(router._live()) > cfg.min_replicas:
            for rep in router._live():
                if rep.idle_since is None or rep.sids:
                    continue
                if now - rep.idle_since < cfg.scale_down_idle_s:
                    continue
                router.detach(rep.name)
                self.scale_downs += 1
                global_flight_recorder().record(
                    "scale_down",
                    replica=rep.name,
                    fleet=len(router._live()),
                )
                break  # at most one retirement per tick
