"""Fault-tolerant pipelined decode sessions: MPMD generation with replay.

The SPMD ring (``parallel.pipeline_decode``) is the throughput/capacity
path — one XLA program, no failure domain smaller than the whole mesh.
This module is the *adaptive* counterpart, the Gen-2 star applied to
generation: decoder stages run on :class:`~adapt_tpu.control.worker.
StageWorker` s (device-owning executors with heartbeats, kill modes and a
deadline watchdog — the reference ``Node``, ``/root/reference/src/
node.py``), microbatches flow through them concurrently, and a worker
that crashes or hangs MID-DECODE is replaced without losing the session.

The hard part vs stateless serving (``runtime.pipeline.ServingPipeline``)
is that decode stages carry *state*: each stage holds its blocks' KV
caches, advanced one position per pass. A lost worker therefore loses
cache state that later passes depend on. Recovery is REPLAY: committed
tokens (every token the session has sampled) are a complete recipe for
every stage's cache — re-run prefill plus "forced" decode passes that
feed the known tokens and discard the logits, through the SAME jitted
stage programs (jit cache hit, no recompile — the <2 s rebind budget,
SURVEY.md §7.4). Exactly-once is structural: a token is appended only
once per (microbatch, pass) by the single event loop, and results from
a pre-recovery epoch are discarded by epoch tag (the reference's
stale-result guard, ``src/dispatcher.py:121-151``).

Scheduling: an event loop drives M microbatches through K stage workers
(submit (m, k+1) the moment (m, k) completes; stage workers execute
their inboxes serially), so stage k runs microbatch m while stage k-1
runs m+1 — the reference's decoupled pump/collect
(``src/dispatcher.py:99-119``) specialized to a token loop. Sampling
runs host-side per pass with the same per-row-key helper the compiled
paths use (``sample_next_tokens``), so output is token-for-token
identical to single-program ``generate()`` (tested, including under
mid-decode kills).
"""

from __future__ import annotations

import itertools
import queue
import time
import weakref
from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from adapt_tpu.config import FaultConfig
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.control.worker import StageWorker, Task, TaskResult
from adapt_tpu.models.transformer_lm import (
    TransformerLM,
    _left_align,
    sample_next_tokens,
    validate_generate_args,
)
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.profiling import (
    aggregate_size_fn,
    global_compile_sentinel,
)
from adapt_tpu.utils.tracing import global_flight_recorder, global_tracer

log = get_logger("decode_pipeline")

#: Live PipelinedDecoders (weak): per-stage compile watches SUM across
#: them (profiling.aggregate_size_fn) — a second decoder must not
#: silently unwatch the first.
_LIVE_DECODERS: "weakref.WeakSet[PipelinedDecoder]" = weakref.WeakSet()


def _program_size(which: str, i: int):
    """Extractor for the decoder stage watches: stage ``i``'s
    ``which`` jit cache size, None when this decoder has no stage
    ``i``."""
    def extract(dec):
        if i >= len(dec.programs):
            return None
        return getattr(dec.programs[i], which)._cache_size()
    return extract


class _ReplayFailure(RuntimeError):
    """A replay step failed (its worker died/hung mid-recovery); carries
    the stage to recover next so the session's retry loop — not the
    caller — handles cascading faults."""

    def __init__(self, stage: int, message: str):
        super().__init__(message)
        self.stage = stage


@dataclass(frozen=True)
class _StageProgram:
    """One stage's two compiled entry points (shared across rebinds — a
    replacement worker reuses the jit cache, weights move, nothing
    recompiles)."""

    index: int
    first: bool
    last: bool
    block_range: tuple[int, int]
    prefill_fn: Callable  # (vars, (x, pos_ids?, vf?)) -> (out, caches)
    decode_fn: Callable  # (vars, (x, caches, index, vf?)) -> (out, caches)
    variables: Any  # host master copy (rebind source)


def _build_stage_programs(
    lm: TransformerLM, variables, boundaries: Sequence[int],
    kv_quant: bool = False,
) -> list[_StageProgram]:
    """Cut the decoder into stages at block ``boundaries`` (stage i runs
    blocks [boundaries[i], boundaries[i+1])); stage 0 owns the embed,
    the last stage owns the head. ``kv_quant`` stores stage KV caches
    int8 (absmax per vector, generate()'s scheme) — replay rebuilds
    quantized caches identically, so recovery parity carries over."""
    g = lm.graph
    embed = g.node("embed").module
    head = g.node("head").module
    blocks = [g.node(n).module for n in lm.block_names]
    edges = [0, *boundaries, lm.depth]
    if any(b <= a for a, b in zip(edges, edges[1:])):
        # Non-monotonic/out-of-range cuts would silently run blocks twice
        # or skip them — wrong tokens with no error. Fail eagerly instead
        # (same convention as validate_generate_args).
        raise ValueError(
            f"boundaries {list(boundaries)} must be strictly increasing "
            f"within (0, {lm.depth})"
        )
    programs = []
    n_stages = len(edges) - 1
    for i in range(n_stages):
        lo, hi = edges[i], edges[i + 1]
        first, last = i == 0, i == n_stages - 1
        names = lm.block_names[lo:hi]
        stage_vars = {n: variables[n] for n in names}
        if first:
            stage_vars["embed"] = variables["embed"]
        if last:
            stage_vars["head"] = variables["head"]
        mods = blocks[lo:hi]

        def prefill_fn(svars, payload, _mods=mods, _first=first, _last=last,
                       _names=names):
            # payload = (ids-or-h, pos_ids-or-None, valid_from-or-None);
            # None members change the payload pytree structure, so the
            # dense and ragged variants jit-compile separately with no
            # runtime branching.
            x, pos_ids, vf = payload
            if _first:
                if pos_ids is not None:
                    h = embed.apply(
                        svars["embed"], x, pos_ids,
                        method="embed_positions",
                    )
                else:
                    h = embed.apply(svars["embed"], x)
            else:
                h = x
            caches = []
            for name, m in zip(_names, _mods):
                h, ck, cv = m.apply(
                    svars[name], h, lm.max_len, vf, kv_quant,
                    method="prefill",
                )
                caches.append((ck, cv))
            out = (
                head.apply(svars["head"], h[:, -1:, :])[:, 0] if _last else h
            )
            return out, tuple(caches)

        def decode_fn(svars, payload, _mods=mods, _first=first, _last=last,
                      _names=names):
            x, caches, index, vf = payload
            if _first:
                if vf is not None:
                    x = embed.apply(
                        svars["embed"], x[:, None], (index - vf)[:, None],
                        method="embed_positions",
                    )
                else:
                    x = embed.apply(
                        svars["embed"], x[:, None], index, method="embed_at"
                    )
            new_caches = []
            for name, m, (ck, cv) in zip(_names, _mods, caches):
                x, ck, cv = m.apply(
                    svars[name], x, ck, cv, index, vf, kv_quant,
                    method="decode_step",
                )
                new_caches.append((ck, cv))
            out = head.apply(svars["head"], x)[:, 0] if _last else x
            return out, tuple(new_caches)

        programs.append(
            _StageProgram(
                index=i,
                first=first,
                last=last,
                block_range=(lo, hi),
                prefill_fn=jax.jit(prefill_fn),
                decode_fn=jax.jit(decode_fn),
                variables=stage_vars,
            )
        )
    return programs


#: Binding-key offset separating a stage's prefill program from its decode
#: program on the same worker (StageWorker bindings are keyed by int).
_PREFILL_KEY = 1000


@dataclass
class _MicrobatchState:
    """Where one microbatch is in its token loop."""

    prompt: Any  # this microbatch's (aligned) prompt slice (replay anchor)
    tokens: list  # committed sampled tokens, np arrays (mb,)
    done_rows: np.ndarray  # EOS latch per row
    caches: list  # per-stage cache pytrees (device-resident)
    phase: str = "prefill"  # prefill | decode | finished
    stage: int = 0  # stage currently (or next) running
    passno: int = 0  # decode pass number (consumes token `passno`)
    carry: Any = None  # activation flowing between stages
    pos_ids: Any = None  # ragged: per-row logical positions (mb, s0)
    vf: Any = None  # ragged: per-row left-pad counts (mb,)


class PipelinedDecoder:
    """Adaptive multi-stage KV-cache generation over stage workers.

    ``boundaries`` are block cut points (e.g. ``[2]`` splits a 4-block LM
    into two stages of two blocks). Stage i runs on ``devices[i]``;
    devices beyond the stage count are failover spares (a stage whose
    worker dies rebinds to the next spare, else doubles up on a survivor).
    """

    def __init__(
        self,
        lm: TransformerLM,
        variables,
        boundaries: Sequence[int],
        devices: Sequence[jax.Device] | None = None,
        fault: FaultConfig | None = None,
        kv_cache_dtype: str = "native",
    ):
        self.lm = lm
        self.fault = fault or FaultConfig()
        if kv_cache_dtype not in ("native", "int8"):
            raise ValueError(
                f"kv_cache_dtype={kv_cache_dtype!r}: expected 'native' "
                "or 'int8'"
            )
        self.kv_cache_dtype = kv_cache_dtype
        self.programs = _build_stage_programs(
            lm, variables, boundaries, kv_quant=kv_cache_dtype == "int8"
        )
        # Compile-sentinel watch (utils.profiling): recovery re-binds a
        # stage to a spare device WITHOUT recompiling (the <2 s budget);
        # post-warmup cache growth here means a recovery actually paid
        # for an XLA compile — counted and logged, not silent. Watches
        # sum over the weakly-held live-decoder set (two concurrent
        # decoders aggregate, neither is silently unwatched; a
        # collected decoder's programs drop out).
        _LIVE_DECODERS.add(self)
        sentinel = global_compile_sentinel()
        for i in range(len(self.programs)):
            sentinel.register(
                f"decode.stage{i}.prefill",
                size_fn=aggregate_size_fn(
                    _LIVE_DECODERS, _program_size("prefill_fn", i)
                ),
            )
            sentinel.register(
                f"decode.stage{i}.decode",
                size_fn=aggregate_size_fn(
                    _LIVE_DECODERS, _program_size("decode_fn", i)
                ),
            )
        devices = list(devices if devices is not None else jax.devices())
        if not devices:
            raise ValueError("no devices")
        self._spares = devices[len(self.programs):]
        self._stage_devices = [
            devices[i % len(devices)] for i in range(len(self.programs))
        ]
        self.registry = WorkerRegistry(default_ttl_s=self.fault.lease_ttl_s)
        self.results: "queue.Queue[TaskResult]" = queue.Queue()
        self._wid = itertools.count()
        self._rid = itertools.count()
        self.epoch = 0
        self.workers: list[StageWorker] = [
            self._spawn(i, self._stage_devices[i])
            for i in range(len(self.programs))
        ]

    # -- workers -----------------------------------------------------------

    def _spawn(self, stage: int, device: jax.Device) -> StageWorker:
        w = StageWorker(
            worker_id=f"decode-w{next(self._wid)}-s{stage}",
            device=device,
            registry=self.registry,
            result_queue=self.results,
            fault=self.fault,
        ).start()
        prog = self.programs[stage]
        # Pre-place ONCE: configure's internal device_put then aliases the
        # already-resident tree, so the prefill and decode bindings share
        # one weight copy (not two — this path exists for models that
        # press HBM limits).
        dev_vars = jax.device_put(prog.variables, device)
        w.configure(stage, prog.decode_fn, dev_vars)
        w.configure(stage + _PREFILL_KEY, prog.prefill_fn, dev_vars)
        return w

    def kill_worker(self, stage: int, mode: str = "crash") -> None:
        """Chaos hook (SURVEY.md §5): kill the worker serving a stage."""
        self.workers[stage].kill(mode)

    def shutdown(self) -> None:
        for w in self.workers:
            w.stop()

    def __enter__(self) -> "PipelinedDecoder":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- generation --------------------------------------------------------

    def generate(
        self,
        prompt,
        steps: int,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
        rng: jax.Array | None = None,
        prompt_lengths: jax.Array | None = None,
        num_microbatches: int | None = None,
        on_token: Callable[[int, int], None] | None = None,
    ) -> np.ndarray:
        """Token-for-token ``generate()`` semantics, served through the
        stage workers with mid-decode failover. ``on_token(m, s)`` fires
        after microbatch ``m`` commits token ``s`` (test/chaos hook).
        Covers the sampling knobs, EOS, ragged prompts
        (``prompt_lengths``), and int8 stage caches (constructor
        ``kv_cache_dtype``). Scope note: stages run on in-process
        device-owning workers — the failure domain the chaos hooks
        model. For multi-HOST scale, the SPMD path
        (``parallel.pipeline_decode``) runs over any jax Mesh (ICI/DCN);
        a cross-host MPMD decode session (server-side session caches
        over ``comm.remote``) is deliberately not claimed here."""
        prompt = jnp.asarray(prompt)
        b, s0 = prompt.shape
        lengths, rng, do_sample = validate_generate_args(
            self.lm, prompt, steps, temperature, top_k, rng,
            prompt_lengths, self.kv_cache_dtype, top_p=top_p,
        )
        if prompt_lengths is not None:
            prompt, pos_ids, valid_from = _left_align(prompt, lengths)
        else:
            pos_ids = valid_from = None
        n_stages = len(self.programs)
        # Default: as many microbatches as keep all stages busy, rounded
        # down to a divisor of the batch.
        M = num_microbatches or max(
            d for d in range(1, min(b, n_stages) + 1) if b % d == 0
        )
        if b % M:
            raise ValueError(f"batch {b} not divisible by {M} microbatches")
        mb = b // M
        temp = jnp.asarray(temperature, jnp.float32)
        rng_next, key0 = jax.random.split(rng)
        step_keys = [key0] + (
            list(jax.random.split(rng_next, steps - 1)) if steps > 1 else []
        )

        states = [
            _MicrobatchState(
                prompt=prompt[m * mb:(m + 1) * mb],
                tokens=[],
                done_rows=np.zeros((mb,), bool),
                caches=[None] * n_stages,
                carry=prompt[m * mb:(m + 1) * mb],
                pos_ids=(
                    pos_ids[m * mb:(m + 1) * mb]
                    if pos_ids is not None
                    else None
                ),
                vf=(
                    valid_from[m * mb:(m + 1) * mb]
                    if valid_from is not None
                    else None
                ),
            )
            for m in range(M)
        ]
        # rid -> (deadline, microbatch, stage, submit perf-time)
        deadlines: dict[int, tuple[float, int, int, float]] = {}
        # Consecutive unrecovered faults (reset whenever any microbatch
        # makes progress): bounds a flapping stage without capping how
        # many *independent* faults a long session may survive.
        consecutive_failures = 0
        token_dtype = prompt.dtype  # hoisted: no per-token host fetch

        def sample(m: int, logits, key):
            st = states[m]
            toks = np.asarray(
                sample_next_tokens(
                    logits, key, temp,
                    do_sample=do_sample, top_k=top_k, top_p=top_p,
                    row_offset=m * mb,
                )
            ).astype(token_dtype)
            if eos_id is not None:
                toks = np.where(st.done_rows, eos_id, toks)
                st.done_rows = st.done_rows | (toks == eos_id)
            st.tokens.append(toks)
            if on_token is not None:
                on_token(m, len(st.tokens) - 1)

        def submit(m: int) -> None:
            st = states[m]
            prog = self.programs[st.stage]
            rid = next(self._rid)
            if st.phase == "prefill":
                key = st.stage + _PREFILL_KEY
                payload = (
                    st.carry,
                    st.pos_ids if st.stage == 0 else None,
                    st.vf,
                )
            else:
                key = st.stage
                payload = (
                    st.carry,
                    st.caches[st.stage],
                    jnp.asarray(s0 + st.passno, jnp.int32),
                    st.vf,
                )
            # Stage workers drain their inboxes serially, so queue wait
            # counts toward the deadline — scale it by the tasks already
            # ahead, or a healthy stage with a deep inbox (every
            # microbatch bursts to stage 0 at session start) gets
            # declared dead. task_deadline_s itself must still exceed
            # one task's worst case incl. first-compile (FaultConfig
            # docs).
            depth_ahead = self.workers[st.stage].queue_depth
            deadlines[rid] = (
                time.monotonic()
                + self.fault.task_deadline_s * (depth_ahead + 1),
                m,
                st.stage,
                time.perf_counter(),  # span anchor: submit -> result
            )
            self.workers[prog.index].submit(
                Task(
                    request_id=rid,
                    stage_index=key,
                    attempt=self.epoch,
                    payload=payload,
                )
            )

        def advance(m: int, output, caches) -> None:
            """One (m, stage) result: store cache, route onward."""
            nonlocal consecutive_failures
            consecutive_failures = 0
            st = states[m]
            stage = st.stage
            st.caches[stage] = caches
            last = stage == len(self.programs) - 1
            if not last:
                st.carry = output
                st.stage += 1
                submit(m)
                return
            if st.phase == "prefill":
                sample(m, output, step_keys[0])
                st.phase = "decode"
                st.passno = 0
            else:
                sample(m, output, step_keys[st.passno + 1])
                st.passno += 1
            if len(st.tokens) >= steps:
                st.phase = "finished"
                return
            st.stage = 0
            st.carry = jnp.asarray(st.tokens[-1])
            submit(m)

        for m in range(M):
            submit(m)

        while any(st.phase != "finished" for st in states):
            try:
                res = self.results.get(timeout=self.fault.watchdog_period_s)
            except queue.Empty:
                res = None
            failed_stage = None
            if res is not None:
                if res.attempt != self.epoch or res.request_id not in deadlines:
                    continue  # stale epoch / already-recovered task
                _, m, stage, t_sub = deadlines.pop(res.request_id)
                if res.error is not None:
                    log.error(
                        "decode stage %d failed: %s", stage, res.error
                    )
                    failed_stage = stage
                else:
                    tracer = global_tracer()
                    if tracer.enabled:
                        # Submit -> result for one (microbatch, stage)
                        # pass, tagged with the task's request/attempt
                        # ids (attempt == recovery epoch) — the stitched
                        # timeline that shows pipeline occupancy and
                        # where a recovery re-drove the session.
                        tracer.add_span(
                            "decode.pass",
                            start=t_sub,
                            end=tracer.now(),
                            request=res.request_id,
                            attempt=res.attempt,
                            microbatch=m,
                            stage=stage,
                        )
                    advance(m, *res.output)
            if failed_stage is None:
                now = time.monotonic()
                for _rid, (t, _m, stage, _t0) in deadlines.items():
                    if t < now:
                        failed_stage = stage
                        log.warning(
                            "decode stage %d missed its deadline "
                            "(worker %s dead or hung)",
                            stage,
                            self.workers[stage].worker_id,
                        )
                        global_flight_recorder().record(
                            "decode_deadline_miss",
                            stage=stage,
                            worker=self.workers[stage].worker_id,
                        )
                        break
            if failed_stage is not None:
                # A replay step can itself hit a second fault (another
                # worker died or hung); _ReplayFailure routes that stage
                # back here instead of aborting the session while retry
                # budget remains.
                while failed_stage is not None:
                    consecutive_failures += 1
                    if consecutive_failures > self.fault.max_retries:
                        raise RuntimeError(
                            f"decode session failed: stage {failed_stage} "
                            f"unrecoverable after {self.fault.max_retries} "
                            "consecutive retries"
                        )
                    try:
                        self._recover(failed_stage, states, s0, deadlines)
                        failed_stage = None
                    except _ReplayFailure as e:
                        log.error("replay hit a second fault: %s", e)
                        failed_stage = e.stage
                # Re-drive every unfinished microbatch from stage 0 of its
                # current pass (replay restored all pre-pass caches).
                for m, st in enumerate(states):
                    if st.phase == "finished":
                        continue
                    st.stage = 0
                    if st.phase == "decode":
                        st.carry = jnp.asarray(st.tokens[-1])
                    else:
                        st.carry = prompt[m * mb:(m + 1) * mb]
                    submit(m)

        out = np.stack(
            [np.stack(st.tokens, axis=1) for st in states], axis=0
        )  # (M, mb, steps)
        return out.reshape(b, steps)

    # -- recovery ----------------------------------------------------------

    def _recover(self, stage: int, states, s0: int, deadlines) -> None:
        """Replace the stage's worker and rebuild mid-decode microbatches'
        caches by replaying committed tokens (prefill + forced decode
        passes through the same jitted programs — no recompile). The
        epoch bump invalidates every in-flight result; microbatches still
        in prefill need no replay (the event loop re-drives their prefill
        from scratch) and finished ones need no caches at all."""
        t0 = time.monotonic()
        self.epoch += 1
        deadlines.clear()
        dead = self.workers[stage]
        dead.kill("crash")  # also silences a hung worker's exec loop
        self.registry.deregister(dead.worker_id)
        device = (
            self._spares.pop(0)
            if self._spares
            else self._stage_devices[(stage + 1) % len(self._stage_devices)]
        )
        self._stage_devices[stage] = device
        self.workers[stage] = self._spawn(stage, device)
        global_metrics().inc("decode.recoveries")

        def run(stage_idx, key, payload):
            """Synchronous replay step. The event loop is parked inside
            _recover, so pulling self.results here is single-consumer;
            pre-recovery stragglers are discarded by (rid, epoch) tag.
            Failures raise _ReplayFailure naming the stage so the
            session's retry loop recovers it in turn."""
            worker = self.workers[stage_idx]
            rid = next(self._rid)
            # Pre-recovery tasks may still occupy this worker's inbox
            # (their results get epoch-discarded but they DO execute) —
            # scale the wait like submit() does.
            depth_ahead = worker.queue_depth
            worker.submit(
                Task(
                    request_id=rid,
                    stage_index=key,
                    attempt=self.epoch,
                    payload=payload,
                )
            )
            deadline = time.monotonic() + self.fault.task_deadline_s * (
                depth_ahead + 1
            )
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise _ReplayFailure(
                        stage_idx, f"replay timed out on stage {stage_idx}"
                    )
                try:
                    res = self.results.get(timeout=remaining)
                except queue.Empty:
                    continue
                if res.request_id != rid or res.attempt != self.epoch:
                    continue  # pre-recovery straggler
                if res.error is not None:
                    raise _ReplayFailure(
                        stage_idx,
                        f"replay failed on stage {stage_idx}: {res.error}",
                    )
                return res.output

        for st in states:
            if st.phase != "decode":
                continue
            # Prefill over the prompt rebuilds position-[0, s0) caches in
            # every stage...
            x = st.prompt
            for k in range(len(self.programs)):
                x, caches = run(
                    k,
                    k + _PREFILL_KEY,
                    (x, st.pos_ids if k == 0 else None, st.vf),
                )
                st.caches[k] = caches
            # ...then forced passes replay committed tokens 0..n-2 (the
            # last committed token is consumed by the pass the event loop
            # re-drives after recovery).
            for p in range(len(st.tokens) - 1):
                x = jnp.asarray(st.tokens[p])
                for k in range(len(self.programs)):
                    x, caches = run(
                        k,
                        k,
                        (
                            x,
                            st.caches[k],
                            jnp.asarray(s0 + p, jnp.int32),
                            st.vf,
                        ),
                    )
                    st.caches[k] = caches
        log.warning(
            "decode session recovered stage %d in %.2fs (epoch %d)",
            stage,
            time.monotonic() - t0,
            self.epoch,
        )
        global_flight_recorder().record(
            "decode_recovery",
            stage=stage,
            epoch=self.epoch,
            worker=self.workers[stage].worker_id,
            duration_s=round(time.monotonic() - t0, 4),
        )
