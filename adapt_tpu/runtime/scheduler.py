"""Multi-tenant traffic control in front of the continuous batcher:
bounded admission, weighted fair queueing, and closed-loop degradation.

The batcher's admission used to be one FIFO deque with no bound: under
adversarial traffic (one tenant flooding, heavy-tailed lengths) it
admits in arrival order until it drowns — no tenant can be protected,
nothing sheds load, and a full slot map queues unboundedly. This module
is the CONTROL half of the multi-tenant story (the MEASUREMENT half is
``benchmarks/load`` + the ``slo.*``/goodput telemetry):

- :class:`AdmissionQueue` — the submit queue, scheduler-shaped. Every
  request lands in its tenant's FIFO queue inside its PRIORITY CLASS
  (``config.SLOSpec.priority``; higher drains strictly first), classes
  drain their tenants by DEFICIT ROUND-ROBIN (``config.TenantQuota``
  weights: a weight-2 tenant drains twice the requests per round), and
  two bounds reject synchronously with :class:`QueueFullError` — the
  global ``max_queue_depth`` and the per-tenant ``burst`` cap. With a
  single tenant and uniform priority the queue degrades to exactly the
  FIFO it replaces (same pop order, same head-of-line semantics), so a
  scheduler-less batcher behaves as before — just bounded. With
  ``config.SchedulerConfig.cache_aware`` on, the pop additionally
  scans a bounded window of the selected tenant queue and admits the
  candidate whose prompt prefix is hottest/longest in the pager's
  radix tree first (probe installed by the paged batcher) — priority
  classes and DRR fairness are untouched; only same-tenant,
  same-class arrival-order ties re-order, and only toward work whose
  KV is already resident.
- **Preemption** lives in ``runtime/continuous`` (it needs the slot
  machinery): when the queue's top class has waited past its TTFT
  headroom, the batcher preempts the lowest-priority decode slot via
  the elastic-recovery replay path — this module only nominates the
  candidate (:meth:`AdmissionQueue.preempt_candidate`).
- :class:`DegradationController` — the closed loop. Reads the
  telemetry the batcher already keeps (queue depth, slot occupancy,
  windowed TTFT attainment) once per tick and walks a fixed shed
  ladder with hysteresis, cheapest knob first::

      1. shrink draft_k        (speculation trades draft compute for
                                target bandwidth — under overload the
                                batch is compute-bound, so proposals
                                past the first are the cheapest work
                                to drop)
      2. raise busy threshold  (disaggregated serving: stop paying the
                                decode tier's handoff-landing work for
                                mid-length prompts)
      3. evict cold pages      (one-shot sweep: capacity-neutral —
                                alloc already evicts on demand — but
                                keeps the allocator on its free-list
                                fast path and signals that cache
                                residency has been sacrificed)
      4. reject best-effort    (``priority < 0`` submits fail with
                                QueueFullError until the load clears)

  Each transition emits a ``degradation_step`` flight event, moves the
  ``scheduler.degraded_total`` counter and the
  ``scheduler.degradation_level`` gauge. De-escalation retraces the
  ladder in reverse as the backlog drains.

Thread-safety: the queue is mutated only under the batcher's handoff
condition (``_cv``) — the same discipline as the deque it replaces.
The controller runs on the ticking thread.

``docs/SERVING.md`` "Traffic control" covers sizing the knobs;
``docs/OBSERVABILITY.md`` catalogs the ``scheduler.*`` metrics and the
``request_rejected`` / ``preempted`` / ``degradation_step`` flight
events.
"""

from __future__ import annotations

import collections
import dataclasses
import time
import weakref
from typing import Any

from adapt_tpu.config import SchedulerConfig
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.tracing import global_flight_recorder

log = get_logger("scheduler")


class QueueFullError(RuntimeError):
    """Admission control rejected a submit SYNCHRONOUSLY: the global
    ``max_queue_depth`` bound, the tenant's ``burst`` cap, or the
    degradation ladder's best-effort shed. The request was never
    accepted — no id to wait on, nothing journaled as pending,
    ``result()`` cannot wedge. Recorded as a ``request_rejected``
    flight event + ``scheduler.rejected_total``."""


def request_priority(req) -> int:
    """Scheduling class of a request-shaped object (anything carrying
    ``.slo``): ``SLOSpec.priority``, or 0 without an SLO."""
    slo = getattr(req, "slo", None)
    return int(slo.priority) if slo is not None else 0


def request_tenant(req) -> str:
    slo = getattr(req, "slo", None)
    return slo.tenant if slo is not None else "default"


class AdmissionQueue:
    """Bounded, weighted-fair admission queue: per-tenant FIFO queues
    inside strict priority classes, drained by deficit round-robin.

    API mirrors the ``collections.deque`` the batcher used, so the
    integration seams stay small: ``append`` (checked — raises
    :class:`QueueFullError`), ``appendleft`` (unchecked front
    re-insert for pool-pressure retries / recovery replays /
    preemption victims), ``popleft`` (the scheduler's pick),
    ``remove_id`` (cancel), ``clear``/``extend`` (recovery's FIFO
    rebuild), ``len``/iteration.

    Constructed WITHOUT a config (``cfg=None`` — the scheduler-less
    batcher default), the queue is STRICT FIFO: priority and tenant
    labels on requests are carried but inert, so a batcher that never
    opted into traffic control keeps its exact pre-scheduler admission
    order — the only behavioral change is the (default, generous)
    depth bound. An explicit config turns the classes/DRR machinery
    on; one tenant + one priority class still degrades to FIFO."""

    def __init__(self, cfg: SchedulerConfig | None = None):
        #: FIFO mode: no explicit config -> priority/tenant inert.
        self._fifo = cfg is None
        self.cfg = cfg or SchedulerConfig()
        #: priority -> tenant -> FIFO deque of requests.
        self._classes: dict[int, dict[str, collections.deque]] = {}
        #: priority -> DRR ring of tenants with queued work.
        self._rings: dict[int, collections.deque[str]] = {}
        #: (priority, tenant) -> outstanding DRR credit.
        self._deficit: dict[tuple[int, str], float] = {}
        self._depth = 0
        #: Queued requests per tenant (all classes) — burst-cap
        #: accounting and the ``scheduler.queue_depth.<tenant>``
        #: gauges. Tenants stay as zero entries once seen (so gauges
        #: drop to 0 instead of going stale) up to ``_MAX_TENANTS``;
        #: past it, drained tenants are evicted — a client minting a
        #: fresh tenant label per request must not grow this map (or
        #: the gauge registry, which the batcher prunes in step) for
        #: the process lifetime.
        self._tenant_depth: dict[str, int] = {}
        #: Degradation rung 4: reject ``priority < 0`` admits.
        self.shed_best_effort = False
        #: Cache-aware pick (``SchedulerConfig.cache_aware``): the
        #: batcher installs a callable ``req -> orderable score``
        #: (radix-resident prefix length, heat) and ``_pick`` scans a
        #: bounded window of the selected tenant queue for the hottest
        #: candidate instead of taking the head. None -> strict FIFO
        #: within the tenant queue, exactly the pre-radix behavior.
        self.prefix_probe = None
        #: req_ids re-inserted at the front (``appendleft``): pool-
        #: pressure put-backs and preemption victims must keep strict
        #: head-of-line service — the cache-aware scan is suppressed
        #: while one waits, else a hotter newcomer could starve a
        #: request the batcher already promised to retry next.
        self._front: set[int] = set()

    # -- bounds ------------------------------------------------------------

    def _weight(self, tenant: str) -> float:
        q = self.cfg.quotas.get(tenant)
        return q.weight if q is not None else self.cfg.default_weight

    def check(self, tenant: str, priority: int) -> None:
        """Raise :class:`QueueFullError` iff an admit for
        ``(tenant, priority)`` would be rejected right now — the one
        bound-check body ``append`` and the disaggregated pre-check
        share."""
        if self.shed_best_effort and priority < 0:
            raise QueueFullError(
                "best-effort admission shed (degradation ladder)"
            )
        if self._depth >= self.cfg.max_queue_depth:
            raise QueueFullError(
                f"queue depth {self._depth} at max_queue_depth="
                f"{self.cfg.max_queue_depth}"
            )
        q = self.cfg.quotas.get(tenant)
        if (
            q is not None
            and q.burst is not None
            and self._tenant_depth.get(tenant, 0) >= q.burst
        ):
            raise QueueFullError(
                f"tenant {tenant!r} at burst cap {q.burst}"
            )

    # -- deque-shaped mutation ---------------------------------------------

    def _key(self, req) -> tuple[str, int]:
        """Scheduling key of a request: FIFO mode folds everything
        into one class/queue (insertion order IS the pop order)."""
        if self._fifo:
            return "default", 0
        return request_tenant(req), request_priority(req)

    def _push(self, req, *, front: bool) -> None:
        tenant, prio = self._key(req)
        tenants = self._classes.setdefault(prio, {})
        q = tenants.get(tenant)
        if q is None:
            q = tenants[tenant] = collections.deque()
        ring = self._rings.setdefault(prio, collections.deque())
        if tenant not in ring:
            ring.append(tenant)
        if front:
            q.appendleft(req)
        else:
            q.append(req)
        self._depth += 1
        self._tenant_depth[tenant] = (
            self._tenant_depth.get(tenant, 0) + 1
        )

    def append(self, req) -> None:
        """Checked admit — raises :class:`QueueFullError` at a bound."""
        self.check(*self._key(req))
        self._push(req, front=False)

    def appendleft(self, req) -> None:
        """UNCHECKED front re-insert (its tenant queue's head): pool-
        pressure retries put back a request they just popped, and
        recovery replays / preemption victims re-queue work already
        accepted — a bound here would drop an in-flight request.

        The re-insert also restores the tenant's SERVICE TURN: it
        jumps to the front of its class ring and gets the DRR unit
        its earlier pop charged refunded — classic DRR charges only
        service actually rendered, and every front re-insert is a pop
        whose service did not happen (pool-pressure put-back) or was
        undone (replay / preemption). Without both, a large request
        that fails allocation loses its turn to every other tenant's
        smaller requests each tick and can starve indefinitely; with
        them, the next pop in its class returns exactly this request
        — the head-of-line discipline FIFO mode gets for free."""
        tenant, prio = self._key(req)
        self._push(req, front=True)
        self._front.add(req.req_id)
        if self._fifo:
            return
        ring = self._rings[prio]
        if ring and ring[0] != tenant:
            ring.remove(tenant)
            ring.appendleft(tenant)
        self._deficit[(prio, tenant)] = (
            self._deficit.get((prio, tenant), 0.0) + 1.0
        )

    #: Drained-tenant zero entries retained for gauge continuity.
    _MAX_TENANTS = 256

    def _account_pop(self, tenant: str) -> None:
        self._depth -= 1
        self._tenant_depth[tenant] -= 1
        if (
            self._tenant_depth[tenant] == 0
            and len(self._tenant_depth) > self._MAX_TENANTS
        ):
            del self._tenant_depth[tenant]

    def popleft(self):
        """The scheduler's pick: highest priority class first; within
        it, deficit round-robin over the tenant ring (one visit refills
        ``quantum * weight`` credit; a request costs 1; an exhausted
        tenant rotates to the back). Raises ``IndexError`` when empty,
        like the deque."""
        for prio in sorted(self._classes, reverse=True):
            req = self._pop_class(prio)
            if req is not None:
                return req
        raise IndexError("pop from an empty AdmissionQueue")

    def _pop_class(self, prio: int):
        tenants = self._classes.get(prio)
        ring = self._rings.get(prio)
        while ring:
            t = ring[0]
            q = tenants.get(t)
            if not q:
                # Stale ring entry (emptied by remove_id/clear).
                ring.popleft()
                self._deficit.pop((prio, t), None)
                tenants.pop(t, None)
                continue
            d = self._deficit.get((prio, t), 0.0)
            if d < 1.0:
                # Start of this tenant's turn: one refill per turn.
                d += self.cfg.quantum * self._weight(t)
                if d < 1.0:
                    # Fractional weight: credit accumulates across
                    # rounds until it covers one request.
                    self._deficit[(prio, t)] = d
                    ring.rotate(-1)
                    continue
            req = self._pick(q)
            self._account_pop(t)
            d -= 1.0
            if not q:
                # Tenant drained: leave the ring, reset its credit
                # (idle tenants must not bank service).
                ring.popleft()
                self._deficit.pop((prio, t), None)
                tenants.pop(t, None)
            elif d < 1.0:
                # Turn exhausted: rotate to the back of the round.
                self._deficit[(prio, t)] = d
                ring.rotate(-1)
            else:
                self._deficit[(prio, t)] = d
            return req
        # Class fully drained.
        self._classes.pop(prio, None)
        self._rings.pop(prio, None)
        return None

    def _pick(self, q):
        """Take one request from tenant queue ``q``: strict FIFO head,
        unless cache-aware ordering is on AND a probe is installed AND
        the head is not a front re-insert — then scan the first
        ``cache_aware_window`` entries and take the one with the
        hottest/longest radix-resident prefix (STRICTLY greater score
        wins, so equal-score candidates keep arrival order and a cold
        queue degrades to exact FIFO). The window bounds the scan cost
        per pop and the queue-jump distance: entry ``window`` onward
        can be bypassed at most ``window - 1`` times per pop, so no
        request waits unboundedly behind an endless hot stream."""
        probe = self.prefix_probe
        if (
            probe is None
            or not self.cfg.cache_aware
            or len(q) < 2
            or q[0].req_id in self._front
        ):
            req = q.popleft()
        else:
            n = min(len(q), max(1, self.cfg.cache_aware_window))
            best, best_score = 0, None
            for i in range(n):
                try:
                    score = probe(q[i])
                except Exception:  # probe must never break admission
                    score = None
                if score is not None and (
                    best_score is None or score > best_score
                ):
                    best, best_score = i, score
            if best == 0:
                req = q.popleft()
            else:
                req = q[best]
                del q[best]
        self._front.discard(req.req_id)
        return req

    def remove_id(self, req_id: int):
        """Remove and return the queued request with ``req_id``
        (cancel path), or None."""
        for prio, tenants in self._classes.items():
            for t, q in tenants.items():
                for i, req in enumerate(q):
                    if req.req_id == req_id:
                        del q[i]
                        self._account_pop(t)
                        self._front.discard(req_id)
                        return req
        return None

    def clear(self) -> None:
        self._classes.clear()
        self._rings.clear()
        self._deficit.clear()
        self._front.clear()
        self._depth = 0
        for t in list(self._tenant_depth):
            if len(self._tenant_depth) > self._MAX_TENANTS:
                del self._tenant_depth[t]
            else:
                self._tenant_depth[t] = 0

    def extend(self, reqs) -> None:
        """UNCHECKED bulk append in order — recovery's FIFO rebuild of
        already-accepted work."""
        for r in reqs:
            self._push(r, front=False)

    def __len__(self) -> int:
        return self._depth

    def __iter__(self):
        for prio in sorted(self._classes, reverse=True):
            for q in list(self._classes[prio].values()):
                yield from list(q)

    # -- scheduler views ---------------------------------------------------

    def depths(self) -> dict[str, int]:
        """Queued requests per tenant (zero entries for tenants seen
        before) — the ``scheduler.queue_depth.<tenant>`` gauges."""
        return dict(self._tenant_depth)

    def pressure(self) -> tuple[int, int, dict[str, int]]:
        """``(depth, bound, per-tenant depths)`` in one call — the
        capacity book's queue-pressure read (``runtime/capacity``),
        kept here so the book and the admission bound can never read
        different notions of "full"."""
        return (
            self._depth,
            int(self.cfg.max_queue_depth),
            dict(self._tenant_depth),
        )

    def preempt_candidate(self):
        """The waiting request preemption would serve: the tenant-queue
        head in the highest non-empty priority class that has burned
        the LARGEST FRACTION of its TTFT budget (no budget -> nothing
        to protect -> never a reason to preempt). Fraction, not raw
        wait: an old head with a lax 10s budget must not shadow a
        younger head already past 80% of a 0.5s one — the trigger
        compares against the budget, so the nomination must too.
        Returns ``(request, priority)`` or None. Non-mutating — DRR
        state does not advance. FIFO mode (no scheduler config) never
        nominates anyone."""
        if self._fifo:
            return None
        now = time.perf_counter()
        for prio in sorted(self._classes, reverse=True):
            tenants = self._classes[prio]
            best, best_frac = None, -1.0
            for q in tenants.values():
                if not q:
                    continue
                r = q[0]
                if r.slo is None or not r.slo.ttft_budget_s:
                    continue
                waited = now - (
                    getattr(r, "t_requeued", 0.0) or r.t_submit
                )
                frac = waited / r.slo.ttft_budget_s
                if frac > best_frac:
                    best, best_frac = r, frac
            if any(tenants.values()):
                # Only the TOP non-empty class may preempt; a budgeted
                # request in a lower class never preempts past it.
                return (best, prio) if best is not None else None
        return None


class DegradationController:
    """The closed loop: per-tick pressure evaluation + the shed ladder
    (see the module docstring). Owned by a scheduler-configured
    ``ContinuousBatcher``; a ``DisaggServer`` fronting that batcher
    attaches itself so the busy-threshold rung has a target."""

    #: Fixed rung order, cheapest shed first. Rungs whose capability
    #: is absent (no draft model, no disagg tier, dense layout) apply
    #: as no-ops, so the level number always means the same thing.
    LADDER = (
        "draft_k",
        "busy_threshold",
        "evict_cached",
        "reject_best_effort",
    )

    @property
    def rung(self) -> str:
        """Name of the deepest rung currently applied (``""`` at level
        0) — the capacity book's human-readable degradation field."""
        return self.LADDER[self.level - 1] if self.level > 0 else ""

    def __init__(self, cfg: SchedulerConfig):
        self.cfg = cfg
        self.level = 0
        self._t_change = 0.0
        self._t_att = 0.0
        self._att_low = False
        self._slo_seen = (0, 0)
        self._disagg: Any = None  # weakref.ref when attached
        self._saved_disagg_cfg = None
        #: Which server _saved_disagg_cfg belongs to (weakref): a
        #: revert must never clobber a DIFFERENT server — one attached
        #: after the save — with a retired server's stale config.
        self._saved_disagg_for: Any = None

    def attach_disagg(self, server) -> None:
        """Give the busy-threshold rung a target (weakly held — the
        controller must never pin a retired server). Attaching while
        the rung is HELD applies it to the new server immediately
        (saving ITS config), so a server swapped in mid-overload
        degrades like the one it replaced instead of serving the
        undegraded thresholds until the next escalation."""
        self._disagg = weakref.ref(server)
        if self.level > self.LADDER.index("busy_threshold"):
            self._saved_disagg_cfg = server.cfg
            self._saved_disagg_for = weakref.ref(server)
            server.cfg = dataclasses.replace(
                server.cfg,
                busy_prompt_threshold=server.cfg.prompt_threshold,
            )

    # -- pressure ----------------------------------------------------------

    def _windowed_attainment_low(self, bat, now: float) -> bool:
        """Windowed TTFT attainment below the floor? Window = one dwell
        period of the batcher's met/missed totals (cheap deltas of ints
        the commit path already keeps)."""
        if now - self._t_att < self.cfg.degrade_dwell_s:
            return self._att_low
        tot = bat._slo_totals
        met = tot["ttft_met"] - self._slo_seen[0]
        missed = tot["ttft_missed"] - self._slo_seen[1]
        self._slo_seen = (tot["ttft_met"], tot["ttft_missed"])
        self._t_att = now
        if met + missed >= 4:
            self._att_low = (
                met / (met + missed) < self.cfg.degrade_attainment
            )
        else:
            self._att_low = False
        return self._att_low

    def step(self, bat) -> None:
        """One control evaluation (ticking thread, host arithmetic
        only): escalate/de-escalate at most one rung per dwell.

        Staleness bound under the pipelined tick runtime
        (``config.RuntimeConfig(pipeline_depth=2)``): this runs at the
        top of the DISPATCH half, so the occupancy/attainment inputs
        read here predate the in-flight tick's commit — slots that
        tick retires still count occupied, and its SLO verdicts are
        not yet in ``_slo_totals``. The error is bounded by exactly
        ONE tick (at most ``chunk`` tokens per slot of pending
        retirement, one tick of attainment movement), which is well
        inside the controller's own ``degrade_dwell_s`` smoothing —
        the ladder can react one tick late, never wrongly-direction.
        Queue depth is exact (submissions are immediate, not
        pipelined)."""
        cfg = self.cfg
        now = time.perf_counter()
        with bat._cv:
            queued = len(bat._queue)
        occupancy = sum(
            1 for s in bat.slots if s.req is not None
        ) / max(1, len(bat.slots))
        qfrac = queued / max(1, cfg.max_queue_depth)
        att_low = self._windowed_attainment_low(bat, now)
        overload = occupancy >= cfg.degrade_occupancy and (
            qfrac >= cfg.degrade_queue_high or (att_low and queued > 0)
        )
        calm = qfrac <= cfg.degrade_queue_low and not att_low
        if now - self._t_change >= cfg.degrade_dwell_s:
            if overload and self.level < len(self.LADDER):
                step = self.LADDER[self.level]
                self._apply(bat, step)
                self.level += 1
                self._t_change = now
                global_metrics().inc("scheduler.degraded_total")
                global_metrics().set_gauge(
                    "scheduler.degradation_level", float(self.level)
                )
                global_flight_recorder().record(
                    "degradation_step",
                    level=self.level,
                    step=step,
                    direction="up",
                    queued=queued,
                    occupancy=round(occupancy, 3),
                )
                log.warning(
                    "degradation up -> level %d (%s): queued=%d "
                    "occupancy=%.2f attainment_low=%s",
                    self.level, step, queued, occupancy, att_low,
                )
            elif calm and self.level > 0:
                self.level -= 1
                step = self.LADDER[self.level]
                self._revert(bat, step)
                self._t_change = now
                global_metrics().set_gauge(
                    "scheduler.degradation_level", float(self.level)
                )
                global_flight_recorder().record(
                    "degradation_step",
                    level=self.level,
                    step=step,
                    direction="down",
                    queued=queued,
                    occupancy=round(occupancy, 3),
                )
                log.info(
                    "degradation down -> level %d (reverted %s)",
                    self.level, step,
                )

    # -- the rungs ---------------------------------------------------------

    def _apply(self, bat, step: str) -> None:
        if step == "draft_k" and bat._spec is not None:
            bat.set_draft_k(max(1, bat._spec.draft_k // 2))
        elif step == "busy_threshold":
            srv = self._disagg() if self._disagg is not None else None
            if srv is not None:
                self._saved_disagg_cfg = srv.cfg
                self._saved_disagg_for = weakref.ref(srv)
                srv.cfg = dataclasses.replace(
                    srv.cfg,
                    busy_prompt_threshold=srv.cfg.prompt_threshold,
                )
        elif step == "evict_cached" and bat._paged:
            # ONE-SHOT sweep at escalation, deliberately not re-run
            # while the rung holds: allocation already evicts cold
            # pages on demand (Pager.can_alloc counts the LRU), so
            # this rung is capacity-NEUTRAL by construction — what it
            # sheds is the cache's speculative value (prefix-hit
            # prefill savings) in exchange for keeping the allocator
            # on its free-list fast path through the overload, and it
            # is the operator-visible signal that residency has been
            # sacrificed. A per-tick sweep would additionally wipe
            # preemption victims' prompt pages before their
            # re-admission could prefix-hit them — strictly more
            # prefill work, exactly when the system can least afford
            # it.
            bat._pager.evict_cached()
        elif step == "reject_best_effort":
            bat._queue.shed_best_effort = True

    def _revert(self, bat, step: str) -> None:
        if step == "draft_k" and bat._spec is not None:
            bat.set_draft_k(bat._spec.draft_k)
        elif step == "busy_threshold":
            srv = self._disagg() if self._disagg is not None else None
            saved_for = (
                self._saved_disagg_for()
                if self._saved_disagg_for is not None
                else None
            )
            if (
                srv is not None
                and self._saved_disagg_cfg is not None
                and saved_for is srv  # never clobber a DIFFERENT server
            ):
                srv.cfg = self._saved_disagg_cfg
            self._saved_disagg_cfg = None
            self._saved_disagg_for = None
        elif step == "reject_best_effort":
            bat._queue.shed_best_effort = False
        # "evict_cached" has nothing to restore: the cache refills
        # from traffic.
