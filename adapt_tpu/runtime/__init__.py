from adapt_tpu.runtime.pipeline import LocalPipeline, ServingPipeline

__all__ = ["LocalPipeline", "ServingPipeline"]
