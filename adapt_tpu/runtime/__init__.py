from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.decode_pipeline import PipelinedDecoder
from adapt_tpu.runtime.disagg import DisaggServer, PrefillWorker
from adapt_tpu.runtime.paged import Pager
from adapt_tpu.runtime.pipeline import LocalPipeline, ServingPipeline
from adapt_tpu.runtime.scheduler import (
    AdmissionQueue,
    DegradationController,
    QueueFullError,
)

__all__ = [
    "AdmissionQueue",
    "ContinuousBatcher",
    "DegradationController",
    "DisaggServer",
    "LocalPipeline",
    "Pager",
    "PipelinedDecoder",
    "PrefillWorker",
    "QueueFullError",
    "ServingPipeline",
]
