from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.decode_pipeline import PipelinedDecoder
from adapt_tpu.runtime.disagg import DisaggServer, PrefillWorker
from adapt_tpu.runtime.paged import Pager
from adapt_tpu.runtime.pipeline import LocalPipeline, ServingPipeline

__all__ = [
    "ContinuousBatcher",
    "DisaggServer",
    "LocalPipeline",
    "Pager",
    "PipelinedDecoder",
    "PrefillWorker",
    "ServingPipeline",
]
