"""Serving pipelines: the reference drivers' semantics, two ways.

``LocalPipeline`` — the fast path with *static* stage->device binding
(Gen-1 chain topology, ``/root/reference/src/node.py:163-179``): stages are
jit programs pinned to devices, activations hop device-to-device directly
(ICI on a real pod), a thread per stage keeps every stage busy so requests
pipeline (the reference's decoupled pump/collect, ``src/dispatcher.py:
99-119``). No adaptivity; maximum throughput.

``ServingPipeline`` — the adaptive path (Gen-2 star): wraps
``control.Dispatcher`` + workers for late binding, membership, watchdog
re-dispatch. Same queue-in/queue-out API, so the two are interchangeable in
drivers and benchmarks — the A/B the reference runs by hand
(``test/test.py`` vs ``test/local_infer.py``) is a constructor swap here.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from collections.abc import Iterable, Sequence
from typing import Any

import jax

from adapt_tpu.config import ServeConfig
from adapt_tpu.control.dispatcher import Dispatcher
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.core.stage import CompiledStage, compile_stages
from adapt_tpu.graph.partition import PartitionPlan
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.profiling import (
    aggregate_size_fn,
    global_compile_sentinel,
    global_engine_obs,
)
from adapt_tpu.utils.tracing import global_tracer

log = get_logger("pipeline")

_SENTINEL = object()

#: Live LocalPipelines (weak): the per-stage compile watches SUM across
#: them (profiling.aggregate_size_fn), so a blue/green second pipeline
#: aggregates rather than silently replacing the first one's watch.
_LIVE_PIPELINES: "weakref.WeakSet[LocalPipeline]" = weakref.WeakSet()


class _StageError:
    """Error marker propagated through the stage queues so a failing stage
    can't strand the stream consumer."""

    def __init__(self, stage_index: int, exc: Exception):
        self.stage_index = stage_index
        self.exc = exc


def codec_hop_transform(codec_cfg):
    """Build a ``hop_transform`` from a :class:`~adapt_tpu.config.
    CodecConfig`: every activation hop pays the codec round-trip, the
    reference's zfp+lz4-per-hop cost model (``src/dispatcher.py:92-98``).
    Returns None for the 'none' codec — in-process hops are
    device-to-device and need no transform at all."""
    from adapt_tpu.comm.codec import get_codec, pack, unpack

    if codec_cfg.name == "none":
        return None
    codec = get_codec(codec_cfg.name, tolerance=codec_cfg.tolerance)

    def hop(activation, stage_index):
        return unpack(pack(codec, activation))

    return hop


class LocalPipeline:
    """Static-chain pipelined inference over a device list."""

    def __init__(
        self,
        plan: PartitionPlan,
        variables,
        devices: Sequence[jax.Device] | None = None,
        donate_activations: bool = False,
        hop_transform=None,
    ):
        """``hop_transform(activation, stage_index) -> activation`` is
        applied to every stage output before it is handed to the next stage
        (and to the final result) — the reference compresses every hop this
        way (zfp+lz4 on each activation, ``src/dispatcher.py:92-98``); pass
        a codec round-trip here to model/pay that DCN-boundary cost."""
        devices = list(devices if devices is not None else jax.devices())
        self.plan = plan
        self.hop_transform = hop_transform
        self.stages: list[CompiledStage] = compile_stages(
            plan, variables, devices, donate_activations=donate_activations
        )
        # Compile-sentinel watch (utils.profiling): a static-chain
        # stage's jit should compile once per device kind; growth after
        # warmup is a counted, logged recompile event. Watches sum over
        # the weakly-held live-pipeline set: two concurrent pipelines
        # aggregate (neither is silently unwatched), and telemetry
        # never pins a torn-down pipeline's jit wrappers.
        _LIVE_PIPELINES.add(self)
        sentinel = global_compile_sentinel()
        for i in range(len(self.stages)):
            sentinel.register(
                f"pipeline.stage{i}",
                size_fn=aggregate_size_fn(
                    _LIVE_PIPELINES,
                    lambda p, i=i: (
                        p.stages[i].fn._cache_size()
                        if i < len(p.stages) else None
                    ),
                ),
            )

    @classmethod
    def from_config(
        cls,
        plan: PartitionPlan,
        variables,
        devices: Sequence[jax.Device] | None = None,
        config: ServeConfig | None = None,
        donate_activations: bool = False,
    ) -> "LocalPipeline":
        """LocalPipeline with the hop transform derived from
        ``config.codec`` — the one knob that also configures every
        gateway-joined remote worker (``comm.remote.WorkerGateway``)."""
        config = config or ServeConfig()
        return cls(
            plan,
            variables,
            devices=devices,
            donate_activations=donate_activations,
            hop_transform=codec_hop_transform(config.codec),
        )

    def infer(self, x) -> jax.Array:
        """Single-request path (latency)."""
        for stage in self.stages:
            x = stage(x)
            if self.hop_transform is not None:
                x = self.hop_transform(x, stage.spec.index)
        return x

    def warmup(self, example) -> None:
        jax.block_until_ready(self.infer(example))

    def stream(self, inputs: Iterable[Any]) -> list[jax.Array]:
        """Throughput path: a thread per stage connected by depth-bounded
        queues; all stages run concurrently on their devices (XLA dispatch
        is async, so device i computes request r while device i+1 computes
        r-1 — true pipelining).

        Dispatch never host-syncs per hop: compute is async XLA dispatch,
        and when a ``hop_transform`` is configured (the codec round-trip —
        the one blocking host fetch on this path) it runs on a dedicated
        per-stage hop thread, so stage i computes request r+1 while its
        hop for request r is still fetching/encoding — the MPMD analog of
        the SPMD overlap schedule (``parallel.pipeline_spmd``)."""
        n_stages = len(self.stages)
        qs: list[queue.Queue] = [queue.Queue(maxsize=4) for _ in range(n_stages + 1)]
        outputs: list[jax.Array] = []
        abort = threading.Event()

        def put_or_abort(q: queue.Queue, item) -> bool:
            while not abort.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def get_or_abort(q: queue.Queue):
            while not abort.is_set():
                try:
                    return q.get(timeout=0.1)
                except queue.Empty:
                    continue
            return _SENTINEL

        # With a hop transform, each stage is TWO loops bridged by its
        # own depth-bounded queue: the compute loop dispatches the jit
        # (async) and hands the un-synced device array to the hop loop,
        # which pays the blocking host round-trip. Without one, compute
        # feeds the next stage directly (device-to-device, no host sync
        # anywhere).
        hop_qs: list[queue.Queue | None] = [
            queue.Queue(maxsize=2) if self.hop_transform is not None else None
            for _ in range(n_stages)
        ]

        tracer = global_tracer()
        # Engine-tier phase timing (obs_engine): stage/hop dispatch
        # histograms, one branch per item when disabled. span=False —
        # the pipeline.stage/pipeline.hop tracer spans above each site
        # already cover the same window.
        eobs = global_engine_obs()

        def stage_loop(i: int):
            stage = self.stages[i]
            out_q = hop_qs[i] or qs[i + 1]
            seq = 0
            while True:
                item = get_or_abort(qs[i])
                if item is _SENTINEL or isinstance(item, _StageError):
                    put_or_abort(out_q, item)
                    break
                try:
                    # Span = the jit DISPATCH (XLA compute is async);
                    # `seq` is the stream ordinal — together with the
                    # hop spans below, Perfetto shows stage i computing
                    # request r+1 while its hop for r is in flight.
                    eo_on = eobs.enabled
                    t_ph = eobs.now() if eo_on else 0.0
                    with tracer.span(
                        "pipeline.stage", stage=i, seq=seq
                    ):
                        y = stage(item)
                    if eo_on:
                        eobs.phase("stage", t_ph, span=False)
                except Exception as e:  # noqa: BLE001 — surface to caller
                    put_or_abort(out_q, _StageError(stage.spec.index, e))
                    break
                seq += 1
                if not put_or_abort(out_q, y):
                    break

        def hop_loop(i: int):
            stage = self.stages[i]
            seq = 0
            while True:
                y = get_or_abort(hop_qs[i])
                if y is _SENTINEL or isinstance(y, _StageError):
                    put_or_abort(qs[i + 1], y)
                    break
                try:
                    # The blocking host round-trip (codec fetch/encode):
                    # the span PR-1's hop threads exist to overlap.
                    eo_on = eobs.enabled
                    t_ph = eobs.now() if eo_on else 0.0
                    with tracer.span("pipeline.hop", stage=i, seq=seq):
                        y = self.hop_transform(y, stage.spec.index)
                    if eo_on:
                        eobs.phase("hop", t_ph, span=False)
                except Exception as e:  # noqa: BLE001 — surface to caller
                    put_or_abort(qs[i + 1], _StageError(stage.spec.index, e))
                    break
                seq += 1
                if not put_or_abort(qs[i + 1], y):
                    break

        threads = [
            threading.Thread(target=stage_loop, args=(i,), daemon=True)
            for i in range(n_stages)
        ] + [
            threading.Thread(target=hop_loop, args=(i,), daemon=True)
            for i in range(n_stages)
            if hop_qs[i] is not None
        ]
        for t in threads:
            t.start()

        def feed():
            for x in inputs:
                if not put_or_abort(qs[0], x):
                    return
            put_or_abort(qs[0], _SENTINEL)

        feeder = threading.Thread(target=feed, daemon=True)
        feeder.start()
        error: _StageError | None = None
        while True:
            y = qs[-1].get()
            if isinstance(y, _StageError):
                error = y
                break
            if y is _SENTINEL:
                break
            outputs.append(y)
        if error is not None:
            # Unblock producers so no threads leak, then surface the error.
            abort.set()
        feeder.join()
        for t in threads:
            t.join()
        if error is not None:
            raise RuntimeError(
                f"stage {error.stage_index} failed during stream"
            ) from error.exc
        return outputs

    def throughput(self, inputs: Sequence[Any]) -> tuple[list, float]:
        """Timed stream: returns (outputs, wall_seconds) — the reference's
        benchmark measurement (``test/test.py:25-37``)."""
        start = time.perf_counter()
        outputs = self.stream(inputs)
        if outputs:
            jax.block_until_ready(outputs[-1])
        return outputs, time.perf_counter() - start


class ServingPipeline:
    """Adaptive serving: dispatcher + workers + membership + watchdog.

    ``gateway_model_config`` (optional) makes the pipeline *elastic*: a
    ``comm.remote.WorkerGateway`` starts with the dispatcher, and any
    machine can then join the pool at runtime with
    ``python -m adapt_tpu.comm.remote --connect HOST:{gateway_port}`` —
    the reference's worker-self-registration story
    (``src/node_state.py:17-20``) as one constructor argument. The dict is
    the model recipe joiners rebuild stages from (``model``, ``cuts``,
    ``num_classes``, ``input_shape``, and any extra factory arguments
    under ``model_kwargs`` — e.g. ``{"stem": "s2d"}`` — see
    ``RemoteStageServer._build_stage``); codecs come from
    ``config.codec``. The recipe must rebuild the exact graph this
    pipeline's ``plan`` partitioned, or joiners' weights won't fit."""

    def __init__(
        self,
        plan: PartitionPlan,
        variables,
        devices: Sequence[jax.Device] | None = None,
        config: ServeConfig | None = None,
        gateway_model_config: dict | None = None,
        gateway_host: str = "127.0.0.1",
        gateway_port: int = 0,
        gateway_secret: str | None = None,
        journal_dir: str | None = None,
    ):
        """``journal_dir`` (optional): write-ahead journal accepted
        requests + the dial-out worker table there, so a crashed serving
        process can be rebuilt with ``Dispatcher.recover`` — see
        :mod:`adapt_tpu.control.journal`."""
        devices = list(devices if devices is not None else jax.devices())
        self.config = config or ServeConfig()
        self.registry = WorkerRegistry(
            default_ttl_s=self.config.fault.lease_ttl_s
        )
        journal = None
        if journal_dir is not None:
            from adapt_tpu.control.journal import DispatcherJournal

            journal = DispatcherJournal(journal_dir)
        self.dispatcher = Dispatcher(
            plan,
            variables,
            registry=self.registry,
            config=self.config,
            journal=journal,
        )
        self.workers = self.dispatcher.spawn_workers(devices)
        self._journal_dir = journal_dir
        self.gateway = None
        if gateway_model_config is not None:
            from adapt_tpu.comm.remote import WorkerGateway

            self.gateway = WorkerGateway(
                self.dispatcher,
                gateway_model_config,
                host=gateway_host,
                port=gateway_port,
                secret=gateway_secret,
            )

    @property
    def gateway_port(self) -> int | None:
        """Port joiners dial. None until :meth:`start` binds the gateway
        (or when no gateway was configured) — never the 0 placeholder."""
        if self.gateway is None or not self.gateway.port:
            return None
        return self.gateway.port

    def start(self) -> "ServingPipeline":
        if self._journal_dir is not None:
            # Only dial-out remote workers are journaled (in-process
            # workers die with this process; gateway joiners redial on
            # their own — see Dispatcher.attach_worker). A journal over a
            # purely in-process pool can replay REQUESTS but will never
            # re-adopt a worker, so Dispatcher.recover would find an
            # empty pool. Checked at start — not in __init__, where
            # spawn_workers has only built in-process workers and the
            # attach_worker(RemoteWorkerProxy) calls that make the
            # journal useful haven't happened yet.
            with self.dispatcher._workers_lock:
                pool = list(self.dispatcher._workers.values())
            if not any(
                getattr(w, "chain_address", None) is not None for w in pool
            ):
                log.warning(
                    "journal_dir=%r configured but the worker pool holds "
                    "no journaled (dial-out remote) workers: after a "
                    "crash, recovery cannot re-adopt any worker from "
                    "this journal",
                    self._journal_dir,
                )
        self.dispatcher.start()
        if self.gateway is not None:
            self.gateway.start()
        return self

    def shutdown(self) -> None:
        if self.gateway is not None:
            self.gateway.stop()
        self.dispatcher.shutdown()

    def __enter__(self) -> "ServingPipeline":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def infer(self, x, timeout: float | None = 120.0):
        return self.dispatcher.infer(x, timeout)

    def warmup(self, example, timeout: float | None = 300.0) -> None:
        self.dispatcher.warmup(example, timeout)

    def stream(self, inputs: Iterable[Any], timeout_per_request: float = 120.0):
        return self.dispatcher.serve_stream(inputs, timeout_per_request)

    def throughput(self, inputs: Sequence[Any]) -> tuple[list, float]:
        start = time.perf_counter()
        outputs = self.stream(inputs)
        if outputs:
            jax.block_until_ready(outputs[-1])
        return outputs, time.perf_counter() - start

    def kill_worker(self, index: int, mode: str = "crash") -> None:
        """Chaos hook (SURVEY.md §5): kill one worker by index."""
        self.workers[index].kill(mode)

    def metrics(self) -> dict:
        return global_metrics().snapshot()

    def serve_observability(self, port: int = 0, host: str = "127.0.0.1"):
        """Start the process observability exporter (``/metrics``,
        ``/trace.json``, ``/debug/events``, the ``/fleet/*``
        federation views and ``/debug/request/<id>`` forensics — see
        ``utils.exporter``) on a daemon thread; returns the HTTP
        server (``.server_address[1]`` is the bound port; ``port=0``
        picks a free one). The endpoints cover everything in this
        process — this pipeline's dispatcher and workers, any
        ContinuousBatcher, the tracer ring, the flight recorder —
        plus every remote worker pushing telemetry reports to this
        process's proxies. The dispatcher's journal (when configured)
        feeds the forensics bundle's submit-meta section, and the
        worker registry is scanned for lease-advertised HTTP-pull
        telemetry endpoints."""
        from adapt_tpu.utils.exporter import serve_metrics
        from adapt_tpu.utils.telemetry import global_federated_store

        global_federated_store().attach_registry(
            self.dispatcher.registry
        )
        return serve_metrics(
            port=port,
            host=host,
            journal=getattr(self.dispatcher, "_journal", None),
        )
