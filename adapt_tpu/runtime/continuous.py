"""Continuous batching: requests join and leave a RUNNING decode batch.

``generate()`` serves one static batch: every row starts together and the
program runs to the longest request's end — a short request pays for the
longest, and a request arriving mid-flight waits for the whole batch.
This module is the slot-based serving loop modern LM servers run instead:
a fixed number of SLOTS decode in lockstep as ONE compiled step per tick
(static shapes — XLA-friendly), and each slot independently admits a new
request the moment its current one finishes. No reference analog (the
reference is CNN-only request/response, SURVEY.md §2.2); this is the
"request-level concurrency" column (§2.2) applied to autoregressive
serving, TPU-first:

- **One compiled decode step for any slot mix.** Per-slot sequence
  lengths ride as a (B,) position vector; `decode_step`'s per-row cache
  write (a vmapped dynamic_update_slice — one scatter) puts each slot's
  token at its own position, and the live mask `positions <= pos[row]`
  keeps every slot's attention window independent. Inactive slots point
  at a trash cache slot (``max_len``) and compute garbage that nothing
  reads — branchless, so the step never recompiles as slots churn.
- **Chunked ticks.** One tick runs a fixed CHUNK of decode steps as a
  single compiled ``lax.scan`` with ONE host sync at the end — the
  per-token host round trip that makes naive continuous batching lose
  to ``generate()``'s fused scan is paid once per chunk instead.
  Requests finishing mid-chunk compute a garbage tail that the host
  truncates (bounded waste: < chunk steps per retirement); admission
  and EOS detection happen at chunk boundaries (``chunk`` is the
  latency/efficiency knob, and ``chunk=1`` is the fully reactive mode).
- **Bucketed prefill.** Prompts compile per bucket length (powers of two
  by default), not per prompt length: a new request pads to the smallest
  bucket, runs the full causal prefill (the measured flash dispatch),
  and its K/V insert into the slot caches is one compiled
  dynamic_update_slice per block.
- **Exact per-request streams.** Sampling uses each request's OWN key
  schedule (the same split/fold pattern as ``generate``), so a request
  served through the batcher emits token-for-token what ``generate``
  would have emitted for it alone — tested with staggered arrivals and
  mixed greedy/sampled traffic. Slot scheduling is invisible in outputs.

``kv_cache_dtype="int8"`` stores KV caches quantized (absmax per K/V
vector, the same scheme as ``generate``): ~2-4x the resident context
per slot and proportionally less per-step cache traffic. Quantization
is a CACHE-LAYOUT property, not a mode of one path — it composes with
every layout and decode family: dense strips and paged pools both
become ``(int8 values, f32 scales)`` pytree pairs (the scale plane is
one f32 per vector, page-addressed by the same table, so prefix-shared
pages carry their scales), speculative verify quantizes its
multi-token appends through the same scheme, and under tensor
parallelism both members head-shard together. Greedy quantized streams
are bit-identical to the same-quantized solo
``generate(kv_cache_dtype="int8")`` on the whole-prompt prefill paths;
prefix-cache suffix passes and chunked prefill attend the
already-quantized earlier window (there is no native copy), so those
admissions carry the cache's quantization error into the first
token's logits — the same class of fine print as chunk fp contraction
widths, one quantization step coarser (tested via top-1-agreement
bounds vs fp32 rather than exact equality).

``kv_layout="paged"`` swaps the per-slot ``max_len`` strips for a shared
page POOL (``runtime/paged`` allocator + ``ops/paged_attention``'s
scalar-prefetch kernel): each request reserves just the pages its
window needs and frees them on retirement, so HBM scales with resident
tokens instead of ``slots x max_len`` — size it with ``pool_pages``
(default: worst case, i.e. no saving until you lower it). Admission is
FIFO all-or-nothing: a request that doesn't fit waits (head-of-line, no
preemption in v1); one that can NEVER fit raises at ``submit``.

``prefill_chunk`` (paged only, a multiple of ``page_size``) turns a
long prompt's admission into CHUNKED PREFILL: one page-aligned chunk
pass per tick, interleaved with the decode batch, so a long admission
never stalls the requests already decoding (the Sarathi-style
latency/throughput knob; ``None`` = whole-prompt prefill, the default).
Numerical contract: greedy streams match solo ``generate()`` (tested);
chunk boundaries change fp contraction widths, so cached K/V can
differ at ulp scale from the one-pass values — a high-temperature
categorical draw at an exact tie may pick differently (equivalence is
distributional there, not bitwise).

Paged slots get PREFIX CACHING for free: a full page of prompt K/V is
content-addressed (hash of the whole token prefix it depends on) and
refcounted, so a request whose prompt starts with an already-resident
prefix — the shared-system-prompt workload — shares those pages (live
or retired) and prefills only its suffix in one ``verify_chunk`` pass.
Retired pages linger as an evict-under-pressure LRU. Hit/miss/cached
counts surface in :meth:`stats`; outputs stay token-identical to solo
``generate()`` on every tested workload, including two live requests
sharing pages and sampled streams — with the same fine print as
chunked prefill: the suffix pass's contraction width differs from the
one-pass prefill's, so a categorical draw at an exact fp tie could in
principle diverge (greedy cannot, short of an exact argmax tie).

``top_k`` is per-REQUEST despite being shape-like (see
``_truncate_rows``); ticks with no truncating request skip the filter
entirely via a static flag.

**Device-resident hot path.** All per-slot sampling state (last token,
cache position, temperature, top_k, top_p, key schedule, key cursor)
lives in pre-allocated batched DEVICE arrays (``_dstate``), not host
scalars: admitting a slot stages its whole row with one donated jitted
``dynamic_update_slice`` setter (O(1) fused transfers — packed int/float
scalar vectors plus the key block — instead of one ``jnp.asarray`` per
field), retiring one clears the row the same way, and the steady-state
decode tick stages NOTHING — ``_step_chunk`` reads and re-writes the
donated state in place, gathering each step's per-slot keys from the
resident schedules. Every host->device staging transfer in this module
goes through :meth:`_h2d`, so ``stats()["h2d_transfers"]`` measures the
host overhead directly (``benchmarks/micro/tick_host_overhead.py``
asserts the steady-state tick stays at zero).

**Request timelines** (``docs/OBSERVABILITY.md``): every request's
lifecycle (submitted -> admitted -> prefill -> first token -> each
decode commit -> finished/cancelled) is stamped on the perf-counter
clock and fed to the process registry as the serving SLO histograms —
``continuous.queue_wait_s``, ``continuous.ttft_s``,
``continuous.itl_s`` (inter-token latency, flushed once per tick) and
``continuous.request_latency_s``. One branch (``obs_timeline``)
disables the histograms; flight-recorder lifecycle events
(admit/finish/cancel — per-request, not per-token) are always on, and
spans (prefill, decode chunk) additionally require the global tracer.

**SLO tracking + goodput** (``docs/OBSERVABILITY.md`` "Workload
telemetry"): ``submit(slo=config.SLOSpec(ttft_budget_s=...,
itl_budget_s=..., tenant=...))`` attaches a latency budget that the
SAME lifecycle stamps evaluate — TTFT once at the first emitted token,
ITL at each later commit. Results feed ``slo.ttft_attainment`` /
``slo.itl_attainment`` gauges, ``slo.{ttft,itl}_{met,missed}_total``
counters, per-tenant ``slo.{met,missed}_total.<tenant>`` request
verdicts at finish, one ``slo_missed`` flight event at a request's
FIRST violation, and ``continuous.goodput_tokens_s`` — tokens/s from
requests still inside budget over a rolling window
(``goodput_window_s``), next to cumulative
``continuous.{tokens,good_tokens}_total`` counters for windowed
phase deltas. All of it is host arithmetic on stamps already taken,
flushed to the registry once per tick: zero extra h2d transfers, zero
compiled-program impact, and ``obs_timeline=False`` one-branch-disables
it with the rest of the timeline. ``benchmarks/load`` drives this
instrumentation into goodput-vs-offered-load curves.

**Batched speculative decoding** (``draft_lm=``/``draft_variables=`` +
``config.SpeculativeConfig``): every serving tick becomes a fixed-shape
``draft_k + 1``-step draft scan over ALL slots
(``models/speculative.draft_chunk`` — the same jit the single-request
loop runs, batch-shaped) followed by ONE fused verify pass
(``_spec_verify``), then per-slot longest-agreeing-prefix acceptance.
Rows DESYNCHRONIZE — slot A commits 5 tokens this tick while slot B
commits 1 — but positions, page tables and cache write masks are all
per-slot device vectors, so the two compiled programs never change
shape and nothing recompiles (guarded by a compile-count test).
Rejected speculation needs no rollback on either cache: each layout
carries ``draft_k`` SLACK positions (dense strips grow by ``draft_k``,
paged admissions reserve the slack pages), so overshoot writes land
past every slot's accepted position and are overwritten by later
rounds — the same trash-slot/masked-write discipline as the rest of
this module. Per-row greedy LOSSLESSNESS is the tested contract: each
request's stream equals its solo ``generate()`` token-for-token
whatever the draft proposes and however acceptance staggers across
slots. ``temperature > 0`` requests are served via SPECULATIVE
SAMPLING (the same verify pass, static ``sample`` flag): each
proposal is accepted with the target's own probability of that token
under the request's temperature/top-k/top-p processing and a
rejection resamples from the residual distribution — provably the
target's sampling distribution per position (lossless in
DISTRIBUTION; greedy rows in the same batch still commit their exact
argmax stream). The draft model keeps its own dense slot strips
(it exists to be small — paging its cache buys capacity that is not
the bottleneck) and is fully prefilled per admission; EOS/stop/cancel
latch at acceptance boundaries through the ordinary commit path. The
steady-state spec tick stages ZERO host arrays and performs ONE fused
device->host fetch (tokens + logprobs + accepted counts) — the PR-1
fused-staging contract, extended.

Request lifecycle niceties: ``submit(stop=[[...], ...])`` ends a stream
at the first emitted occurrence of any stop token-sequence (host-side
tail check — the emitted prefix still equals solo ``generate()``), and
:meth:`cancel` drops a queued request or retires a mid-flight one at
the next commit boundary with its partial stream as the result (slot
and pages free immediately after).

**Tensor-parallel serving** (``mesh=`` + ``config.ParallelConfig{tp}``;
``docs/SERVING.md`` "Tensor-parallel serving"): the whole request tier
runs SPMD over a mesh's ``tp`` axis. Weights place by the megatron-style
rules in ``parallel/sharding.lm_tp_rules`` (qkv/mlp-in column-split,
attn-out/mlp-out row-split — exactly ONE psum pair per block per token,
so the decode tick's latency does not drown in ICI hops), and the KV
caches — dense slot strips and paged pools alike — shard on their HEAD
axis (GQA-aware: kv_heads % tp == 0), so per-device KV bytes are the
logical bytes / tp: models whose weights + KV exceed one chip's HBM
serve, and models that fit stop leaving N-1 chips idle. Everything the
host touches stays REPLICATED — page tables, the device-resident
sampling state, staged admission vectors — so admission, commit, cancel,
prefix caching and the pager are sharding-blind, and all the hot-path
invariants survive unchanged and re-pinned by tests: zero host arrays
per steady-state tick, the two-program compile footprint, buffer
donation, and per-row greedy losslessness vs single-device
``generate()`` on both layouts including speculative mode (the draft
model deliberately replicates — it is small by construction and a
replicated draft scan is collective-free). ``stats()`` reports
``cache_bytes`` (logical) next to ``cache_bytes_per_device``; the
``memory.*_per_device`` gauges mirror it at scrape.

**Elastic mesh recovery** (``health=`` +
``control.registry.DeviceHealthMonitor``, knobs in
``config.RecoveryConfig``; ``docs/SERVING.md`` "Elastic recovery"):
losing one chip of the tp mesh no longer kills every in-flight
request. The monitor feeds the TTL-lease membership machinery — a
simulated kill (or a real lease expiry) arrives as a ``leave`` event,
and the next tick re-shards: the mesh rebuilds from the surviving
devices (tp=4 -> tp=2; largest divisor the survivors can host),
weights re-place by the megatron rules, the program families re-lower
with explicit shardings (sentinel warmups re-armed — ONE expected
variant per family, no phantom alarms), and live KV/sampling state
migrates via an explicit redistribution plan
(``parallel.sharding.KVReshardPlan``: per-shard device-to-device
moves for surviving shards, host staging only for the lost shard's
heads), so migrated greedy requests finish **bit-identical** to an
uninterrupted run. Requests that do not migrate (mid-chunked-prefill,
or ``policy="replay"``) REPLAY from the journal (``journal=`` — a
``control.journal.DispatcherJournal`` that records every submit's
payload + sampling knobs and every finish's done mark), re-entering
through the paged prefix cache when the prompt pages are still
resident — identical tokens, paid by a suffix prefill instead of
state migration. Lifecycle: ``device_lost`` / ``mesh_reshard`` /
``kv_migrated`` / ``replayed_from_journal`` flight events,
``recovery.wall_s`` histogram and ``recovery.{migrated,replayed,
dropped}_total`` counters.

**Traffic control** (``scheduler=`` + ``config.SchedulerConfig``;
``runtime/scheduler``; ``docs/SERVING.md`` "Traffic control"): the
submit queue is a bounded ``AdmissionQueue`` — per-tenant quotas
(weights + burst caps), deficit-round-robin weighted fair queueing
inside strict priority classes (``SLOSpec.priority``), and explicit
synchronous rejection (``QueueFullError`` + ``request_rejected``
flight event) at the global or per-tenant bound, so a full slot map
no longer queues unboundedly and ``result()`` never wedges on a
request that was never accepted. A high-priority request that burns
its TTFT headroom waiting preempts the lowest-priority decode slot
through the recovery REPLAY path (prompt pages into the prefix LRU,
journal-requeue, ``stream_skip``-suppressed re-delivery — exactly-once
across preemption, SLO verdicts carried). A per-tick
``DegradationController`` sheds load before preemption has to:
shrink ``draft_k``, raise the disaggregated busy threshold, evict
cold cached pages, reject best-effort admits. Without a
``SchedulerConfig`` the queue degrades to the bounded FIFO.

Not in scope (v1): pipeline-parallel slots (compose with the pipelined
decoders for models bigger than a TP group).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import weakref
from functools import partial
from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import (
    Mesh,
    NamedSharding,
    PartitionSpec as P,
    SingleDeviceSharding,
)

from adapt_tpu.config import (
    CacheTierConfig,
    CapacityConfig,
    KernelConfig,
    ObservabilityConfig,
    ParallelConfig,
    PrefillConfig,
    RecoveryConfig,
    RuntimeConfig,
    SchedulerConfig,
    SLOSpec,
    SpeculativeConfig,
)
from adapt_tpu.control.registry import weak_watch
from adapt_tpu.models.speculative import accept_speculation, draft_chunk
from adapt_tpu.models.transformer_lm import (
    TransformerLM,
    chosen_logprob,
    nucleus_filter,
    validate_tp,
)
from adapt_tpu.ops.decode_attention import check_head_parity
from adapt_tpu.ops.quantize import dequantize_params, quantize_params
from adapt_tpu.parallel.sharding import (
    fetch_head_shards,
    kv_head_sharding,
    lm_tp_rules,
    plan_kv_handoff,
    plan_kv_reshard,
    tree_shardings,
)
from adapt_tpu.parallel.sp_prefill import SPPrefiller, build_sp_mesh
from adapt_tpu.runtime.capacity import CapacityModel
from adapt_tpu.runtime.paged import (
    HostKVTier,
    Pager,
    insert_prefill_pages,
)
from adapt_tpu.runtime.scheduler import (
    AdmissionQueue,
    DegradationController,
    QueueFullError,
    request_priority,
    request_tenant,
)
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.profiling import (
    aggregate_size_fn,
    device_local_nbytes,
    global_compile_sentinel,
    global_engine_obs,
    program_cost_analysis,
    register_memory_source,
    register_roofline_source,
    unregister_memory_source,
    unregister_roofline_source,
)
from adapt_tpu.utils.tracing import global_flight_recorder, global_tracer

log = get_logger("continuous")


class DeviceLostError(RuntimeError):
    """A device of the batcher's mesh was reported dead and automatic
    resharding is off (``config.RecoveryConfig.auto_reshard=False``), or
    recovery itself is impossible (every device lost, or the surviving
    pool cannot support ``min_tp``). Call
    :meth:`ContinuousBatcher.recover` — or re-raise to the serving
    layer."""

#: Live batchers (weak — telemetry must never pin a retired batcher's
#: device arrays). The ONE "continuous.prefill" sentinel watch sums the
#: per-instance prefill jit families over this set
#: (profiling.aggregate_size_fn), so a second batcher's construction
#: aggregates rather than silently replacing the first one's watch,
#: and closing the last batcher prunes the watch.
_LIVE_BATCHERS: "weakref.WeakSet[ContinuousBatcher]" = weakref.WeakSet()


def _prefill_family_size(bat: "ContinuousBatcher") -> int:
    # list(): a ticking thread may be inserting a new bucket's jit
    # closure while an exporter scrape sums.
    return sum(f._cache_size() for f in list(bat._prefill_cache.values()))


@dataclasses.dataclass
class _Request:
    req_id: int
    prompt: np.ndarray  # (s0,) int32
    steps: int
    temperature: float
    top_k: int  # == vocab -> no truncation
    top_p: float  # == 1.0 -> no nucleus truncation
    eos_id: int | None
    folded_keys: np.ndarray  # (steps, 2) uint32 — pre-folded per-step keys
    #: Host-side stop sequences: the stream ends (inclusive) at the
    #: first emitted occurrence of any of these token tuples.
    stop: tuple[tuple[int, ...], ...] = ()
    #: Optional streaming callback (req_id, token, index) per commit.
    on_token: Callable[[int, int, int], None] | None = None
    #: Tokens already DELIVERED before an elastic-recovery replay
    #: re-queued this request: the re-run regenerates indices
    #: 0..skip-1 identically (greedy, or the journaled key schedule),
    #: so ``on_token`` suppresses them — the client's transcript stays
    #: exactly-once — and the TTFT stamp (already taken at the original
    #: first token) is not re-observed.
    stream_skip: int = 0
    #: Snapshot of the tokens (and logprobs) already delivered when an
    #: elastic-recovery replay re-queued this request: a cancel landing
    #: before the re-run catches up (queued, or live mid-regeneration)
    #: resolves result() with these — result() must never contradict
    #: the stream the client already received.
    delivered_tokens: np.ndarray | None = None
    delivered_lps: np.ndarray | None = None
    #: Perf-clock stamp of the last token the client RECEIVED before a
    #: replay re-queued this request: the first post-regeneration
    #: token's ITL gap measures from here, so the kill-to-recovery
    #: stall the client actually experienced is judged against the
    #: budget exactly like a migrated request's is.
    t_last_delivered: float = 0.0
    #: Perf-clock stamp of the recovery re-queue (0.0 = first life):
    #: the re-admission's queue-wait sample measures from here — from
    #: t_submit it would span the whole first life plus the recovery,
    #: which is not a queue wait.
    t_requeued: float = 0.0
    #: Lifecycle anchor (perf-counter clock, stamped by submit):
    #: queue-wait, TTFT and request latency all measure from here.
    t_submit: float = 0.0
    #: Optional latency budget (``config.SLOSpec``): TTFT judged at the
    #: first emitted token, ITL per commit; evaluation rides the
    #: obs_timeline gate.
    slo: SLOSpec | None = None
    #: Set at the request's FIRST budget violation and carried across
    #: recovery replays: the client experienced the miss, so a second
    #: life must not re-enter goodput, re-fire ``slo_missed``, or
    #: finish with a ``met`` tenant verdict.
    slo_violated: bool = False
    #: ``submit_fanout`` group id (-1 = ordinary request). Consumed at
    #: admission (cleared there, so a pool-pressure re-queue or a
    #: recovery replay never double-decrements the group).
    fanout_group: int = -1
    #: Submit-time TTFT forecast (``runtime/capacity``; 0.0 = no
    #: capacity model, or nothing learned yet). Compared against the
    #: realized TTFT at first-token commit — the forecaster's
    #: self-calibration loop.
    ttft_forecast_s: float = 0.0


@dataclasses.dataclass
class _FanoutGroup:
    """One :meth:`ContinuousBatcher.submit_fanout` group's shared
    bookkeeping. ``remaining`` counts siblings not yet admitted (or
    cancelled); the group dies when it reaches zero. For GREEDY groups
    the first admitted sibling also records its last prompt page
    (``page`` — rc-claimed via ``Pager.retain`` so it outlives that
    sibling's retirement) and its first token/logprob: later siblings
    whose prefix probe matches every earlier page take the
    copy-on-write fork — one device page copy plus the cached first
    commit — instead of recomputing the suffix forward. Sampled
    groups leave ``page`` unset: each sibling needs fresh last-position
    logits to draw its own first token from, so the suffix pass runs
    anyway (the full prefix pages still share through the probe)."""

    remaining: int
    greedy: bool
    page: int | None = None
    first: int | None = None
    first_lp: float | None = None


@dataclasses.dataclass
class _Slot:
    idx: int = -1  # position in the slot list (page-table row)
    req: _Request | None = None
    #: chunked prefill progress: next position to prefill, or -1 when
    #: not mid-prefill (the slot decodes). A slot with pf_done >= 0
    #: holds its request but sits out the decode batch.
    pf_done: int = -1
    s0: int = 0  # prompt length
    #: cache position where the next tick's CONSUMED token (last_token,
    #: stream index emitted-1) writes its K/V: s0 + emitted - 1.
    pos: int = 0
    emitted: int = 0
    last_token: int = 0
    tokens: list = dataclasses.field(default_factory=list)
    lps: list = dataclasses.field(default_factory=list)
    #: Timeline stamps (perf-counter): first emitted token (0.0 = none
    #: yet) and last emitted token — feed the TTFT and
    #: inter-token-latency histograms (queue wait measures from
    #: ``req.t_submit`` at admission). ``obs_count`` is the token count
    #: as of the last stamp: an ITL sample is recorded only when the
    #: previous commit also stamped, so toggling ``obs_timeline`` off
    #: and back on mid-request cannot inject one giant gap sample.
    t_first: float = 0.0
    t_last: float = 0.0
    obs_count: int = 0
    #: SLO state: True until the request's first budget violation —
    #: only its tokens count toward goodput (requests with no SLOSpec
    #: have nothing to violate and stay True).
    slo_ok: bool = True


class _AsyncFetch:
    """One tick's device→host result fetch with a ``.ready()`` /
    ``.commit()`` split — the SHARED helper behind both the plain-tick
    fetch and ``_spec_verify``'s ``(toks, lps, acc)`` fetch.

    Construction starts the D2H copy immediately
    (``copy_to_host_async`` on every leaf), so the transfer overlaps
    whatever host work runs between dispatch and commit — in the
    synchronous loop that is the tracer/phase bookkeeping (the old
    path double-synced: dispatch enqueued the programs, then
    ``jax.device_get`` started a cold blocking copy); in the pipelined
    loop it is the WHOLE next tick's scheduler pass and dispatch.
    ``commit()`` blocks until the copy lands and returns host numpy
    arrays (cached — commit is idempotent); ``wait_s`` records how
    long it actually blocked, which is the non-overlapped device wall
    the ``runtime.overlap_ratio`` gauge is computed from."""

    __slots__ = ("_arrays", "_host", "wait_s")

    def __init__(self, arrays: tuple):
        self._arrays = arrays
        self._host: tuple | None = None
        self.wait_s = 0.0
        for a in arrays:
            # Plain numpy (already host) has no async-copy hook.
            start = getattr(a, "copy_to_host_async", None)
            if start is not None:
                start()

    def ready(self) -> bool:
        """True when every leaf's device computation + D2H copy has
        completed — ``commit()`` would return without blocking."""
        if self._host is not None:
            return True
        return all(
            bool(getattr(a, "is_ready", lambda: True)())
            for a in self._arrays
        )

    def commit(self) -> tuple:
        """Block until the results land; return host numpy arrays."""
        if self._host is None:
            t0 = time.perf_counter()
            self._host = tuple(
                np.asarray(a) for a in jax.device_get(self._arrays)
            )
            self.wait_s = time.perf_counter() - t0
            self._arrays = ()  # drop the device references
        return self._host


@dataclasses.dataclass
class _InFlight:
    """One dispatched-but-uncommitted decode tick (``pipeline_depth >=
    2``; the synchronous loop builds one and commits it immediately).

    ``reqs``/``lives`` capture per-slot BINDING IDENTITY at dispatch:
    commit applies a slot's results only when the slot still holds the
    same request object AND the same life (``slot.tokens`` list
    identity — a preemption can release and re-admit the SAME request
    object within the one-tick lag, and its fresh life must not
    receive the old life's tick). Rows whose binding changed are
    skipped: the tick decoded a bounded garbage tail for them (the
    same < chunk-steps-per-retirement waste discipline mid-chunk
    finishes already have)."""

    fetch: _AsyncFetch
    #: Per-slot request captured at dispatch (None = not in the decode
    #: batch that tick) + the life marker (slot.tokens list identity).
    reqs: list
    lives: list
    n_active: int = 0
    #: Speculative-round metadata (None = lockstep chunk tick):
    #: (draft_k_eff, tree_width, active slot indices) captured at
    #: dispatch — set_draft_k may change the live values mid-lag.
    spec: tuple | None = None
    #: Tracer span start for decode_chunk/verify (0.0 = untraced at
    #: dispatch) and EngineObs stamp for the decode/verify phase
    #: (0.0 = obs_engine off at dispatch) — commit closes them only
    #: when both ends were armed (the mid-flight-toggle guard).
    t_span: float = 0.0
    t_eo: float = 0.0
    #: perf_counter at dispatch start / dispatch end. commit reads
    #: them for runtime.overlap_ratio (1 - blocked-fetch-wait over the
    #: dispatch-to-commit wall) and engine.phase.commit_lag_s.
    t0: float = 0.0
    t_dispatched: float = 0.0
    #: Span tags captured at dispatch.
    req_ids: tuple = ()


class ContinuousBatcher:
    """Slot-based continuous batching over one LM — on one device, or
    tensor-parallel over a mesh's ``tp`` axis (``mesh=`` +
    ``config.ParallelConfig``; weights and KV head-sharded, control
    plane replicated — see the module docstring).

    ``slots`` is the lockstep decode width (static); ``top_k`` here is
    only the DEFAULT for requests that do not pass their own (per-row
    truncation: ``_truncate_rows``). Drive it with :meth:`submit` +
    :meth:`run` (or :meth:`tick` for manual control).
    """

    #: Max UNCLAIMED logprob streams retained (oldest evicted past it).
    _LPS_CAP = 4096

    def __init__(
        self,
        lm: TransformerLM,
        variables,
        slots: int = 8,
        top_k: int | None = None,
        prompt_buckets: tuple[int, ...] | None = None,
        chunk: int = 8,
        kv_cache_dtype: str = "native",
        kv_layout: str = "slots",
        page_size: int = 128,
        pool_pages: int | None = None,
        prefill_chunk: int | None = None,
        draft_lm: TransformerLM | None = None,
        draft_variables=None,
        speculative: SpeculativeConfig | None = None,
        mesh: Mesh | None = None,
        parallel: ParallelConfig | None = None,
        recovery: RecoveryConfig | None = None,
        health=None,
        journal=None,
        scheduler: SchedulerConfig | None = None,
        kernel: KernelConfig | None = None,
        cache_tier: CacheTierConfig | None = None,
        prefill: PrefillConfig | None = None,
        sp_mesh: Mesh | None = None,
        runtime: RuntimeConfig | None = None,
        observability: ObservabilityConfig | None = None,
        capacity: CapacityConfig | None = None,
    ):
        self.lm = lm
        # -- tensor parallelism (mesh-native serving) ----------------------
        # ``mesh`` + ``config.ParallelConfig{tp}`` shard the serving tier
        # over the mesh's tp axis: variables place by the megatron rules
        # (parallel.sharding.lm_tp_rules — one psum pair per block), KV
        # caches/pools shard on their HEAD axis (per-device KV bytes ==
        # logical / tp), and every jitted program compiles under GSPMD
        # with explicit cache shardings, so the collectives are inserted
        # by the compiler — the host-side admission/commit logic below
        # is sharding-blind (page tables and _dstate stay replicated).
        if parallel is not None and parallel.tp > 1 and mesh is None:
            raise ValueError(
                f"ParallelConfig(tp={parallel.tp}) requires a mesh"
            )
        self._mesh = mesh
        self._axis = (parallel or ParallelConfig()).axis
        if mesh is not None:
            axis = self._axis
            if axis not in mesh.shape:
                raise ValueError(
                    f"mesh has no {axis!r} axis (axes: "
                    f"{tuple(mesh.axis_names)})"
                )
            tp = int(mesh.shape[axis])
            if parallel is not None and parallel.tp != tp:
                raise ValueError(
                    f"ParallelConfig.tp={parallel.tp} != mesh {axis!r} "
                    f"size {tp}"
                )
            validate_tp(lm, tp)
            self._tp = tp
            if tp == 1:
                # Degenerate mesh: a size-1 tp axis partitions nothing,
                # and 1-device meshes are where jax's sharding
                # normalization is quirkiest — XLA hands back
                # equivalent-but-UNEQUAL NamedShardings (P() vs
                # P(None, 'tp', None)) for physically identical
                # outputs, and every flip is a phantom jit variant in
                # the next consumer. Run the ordinary single-device
                # path instead: same program, no GSPMD, exact
                # compile-count parity with the no-mesh batcher (the
                # tp=1 column of benchmarks/micro/tp_decode.py is this
                # path). The local too: every placement site below
                # branches on it. The ONE thing kept from the mesh is
                # its device: everything commits there via
                # SingleDeviceSharding (the tp=1 REMNANT discipline
                # recover() installs), so ``health=`` can track it —
                # a loss raises DeviceLostError instead of silently
                # dispatching onto the dead chip forever.
                dev0 = list(mesh.devices.flat)[0]
                mesh = None
                self._mesh = None
                self._repl = SingleDeviceSharding(dev0)
                self._kv_sharding = None
                variables = jax.device_put(variables, self._repl)
            else:
                #: Replicated placement for everything the host stages
                #: (prompt ids, fused admission vectors, page tables,
                #: _dstate) — admission/commit logic is sharding-blind.
                self._repl = NamedSharding(mesh, P())
                #: KV caches shard on the HEAD axis (dim 1 of both the
                #: dense (slots, kvh, L, hd) strips and the paged
                #: (pages, kvh, P, hd) pools — and of the int8 scale
                #: planes: both members of a quantized (values, scales)
                #: pair pin to the SAME spec, parallel.sharding's one
                #: definition).
                self._kv_sharding = kv_head_sharding(mesh, axis)
                variables = jax.device_put(
                    variables,
                    tree_shardings(
                        variables, mesh,
                        rules=partial(lm_tp_rules, axis=axis),
                    ),
                )
        else:
            self._tp = 1
            self._repl = None
            self._kv_sharding = None
        self.variables = variables
        self.slots = [_Slot(idx=i) for i in range(slots)]
        self.top_k = top_k
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.chunk = chunk
        if speculative is not None and draft_lm is None:
            raise ValueError(
                "speculative config requires draft_lm/draft_variables"
            )
        if draft_lm is not None:
            if draft_variables is None:
                raise ValueError("draft_lm requires draft_variables")
            if draft_lm.vocab != lm.vocab:
                raise ValueError(
                    f"draft vocab {draft_lm.vocab} != target vocab "
                    f"{lm.vocab}"
                )
            if draft_lm.max_len < lm.max_len:
                # The draft prefills the same prompt buckets and decodes
                # the same positions as the target; a shorter draft
                # context would silently truncate them.
                raise ValueError(
                    f"draft max_len {draft_lm.max_len} < target max_len "
                    f"{lm.max_len}"
                )
            self._spec = speculative or SpeculativeConfig()
            if self._spec.draft_weight_dtype == "int8":
                # Store the draft's matrix weights blockwise int8
                # (replicated under TP, so this is a direct per-chip
                # HBM cut); the draft programs dequantize at use
                # (draft_chunk / _draft_prefill_fn), so the f32 weights
                # never persist.
                draft_variables = quantize_params(draft_variables)
        else:
            self._spec = None
        self._spec_k = self._spec.draft_k if self._spec else 0
        #: EFFECTIVE proposals per round — the degradation ladder's
        #: first rung shrinks it at runtime (:meth:`set_draft_k`).
        #: Cache geometry, admission slack and the idle sentinel all
        #: size for the CONFIGURED ``draft_k`` (the maximum), so a
        #: shrunk round's writes always land inside reserved space;
        #: only the per-tick draft scan and verify chunk narrow.
        self._spec_k_eff = self._spec_k
        #: draft_k values whose spec-program variants have already
        #: been granted a compile allowance (each distinct k lowers
        #: one fresh draft/verify variant; toggling back reuses it).
        self._spec_k_granted = {self._spec_k}
        self._draft_lm = draft_lm
        self._draft_variables = draft_variables
        #: TREE-DRAFT width (``SpeculativeConfig.tree_width``): 0 =
        #: chain speculation; w >= 1 adds w sibling leaf rows to every
        #: verify chunk and up to ONE bonus committed token per round
        #: (the leaf + the target's prediction after it). Geometry
        #: below (cache slack, table width, idle sentinel, admission
        #: reservation) all widen by w so leaf writes land in reserved
        #: masked space.
        self._spec_w = self._spec.tree_width if self._spec else 0
        #: Decode-kernel dispatch knobs threaded into every decode/
        #: verify program this batcher lowers (static per batcher —
        #: the jit families key on self).
        self._kernel = kernel or KernelConfig()
        if kv_cache_dtype not in ("native", "int8", "int4"):
            raise ValueError(
                f"kv_cache_dtype={kv_cache_dtype!r}: expected 'native', "
                "'int8' or 'int4'"
            )
        if kv_layout not in ("slots", "paged"):
            raise ValueError(
                f"kv_layout={kv_layout!r}: expected 'slots' or 'paged'"
            )
        #: Quantized KV caches: absmax per K/V vector, same scheme as
        #: generate(kv_cache_dtype=...) — ~2-4x (int8) / ~4-8x (int4,
        #: two nibbles packed per int8 lane) more resident context per
        #: slot and correspondingly less per-step cache traffic vs
        #: native. Composes with EVERY layout and mode: dense strips
        #: and paged pools both become (values, scales) pytree pairs,
        #: speculative verify quantizes its multi-token appends, and
        #: under TP both members head-shard together — quantization is
        #: a cache-layout property, not a special mode of one path.
        self._kv_dtype = kv_cache_dtype
        self._kv_quant = kv_cache_dtype != "native"
        #: paged caches: per-block page POOLS + a shared page table
        #: (``runtime/paged`` allocator, ``ops/paged_attention`` kernel)
        #: — HBM scales with resident tokens, not slots x max_len.
        self._paged = kv_layout == "paged"
        if prefill_chunk is not None:
            if not self._paged:
                raise ValueError(
                    "prefill_chunk requires kv_layout='paged' (chunk "
                    "passes run over the page-strip machinery)"
                )
            if prefill_chunk < page_size or prefill_chunk % page_size:
                raise ValueError(
                    f"prefill_chunk must be a positive multiple of "
                    f"page_size {page_size}, got {prefill_chunk}"
                )
        self._prefill_chunk = prefill_chunk
        if top_k is not None and not (1 <= top_k <= lm.vocab):
            raise ValueError(f"top_k {top_k} outside [1, {lm.vocab}]")
        if prompt_buckets is None:
            prompt_buckets, b = [], 8
            while b < lm.max_len:
                prompt_buckets.append(b)
                b *= 2
            prompt_buckets.append(lm.max_len)
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        g = lm.graph
        self._embed = g.node("embed").module
        self._head = g.node("head").module
        self._blocks = [g.node(n).module for n in lm.block_names]
        block0 = self._blocks[0]
        #: Sliding-window models: decode masking lives in the model;
        #: the batcher's job is page RECYCLING behind the window.
        self._window = getattr(block0, "window", None)
        # One trash slot for idle rows, plus draft_k (+ tree_width leaf
        # rows) SLACK positions in speculative mode: a verify chunk
        # writes draft_k + 1 + tree_width tokens from each slot's
        # position (trash included), and the rejected overshoot must
        # land in masked space, never shift onto live rows (append_kv
        # clamps).
        self._cache_len = lm.max_len + 1 + self._spec_k + self._spec_w
        self._trash = lm.max_len
        # Slot caches hold KV heads: fewer than query heads under GQA
        # (the whole point — slots cost kv_heads/heads the HBM).
        heads, head_dim = block0.cache_heads, block0.head_dim
        if kv_cache_dtype == "int4" and head_dim % 2:
            raise ValueError(
                f"kv_cache_dtype='int4' packs two nibbles per int8 "
                f"lane and needs an even head_dim, got {head_dim}"
            )
        #: VALUE-plane lane width: head_dim, halved for packed int4.
        self._kv_width = (
            head_dim // 2 if kv_cache_dtype == "int4" else head_dim
        )

        if self._paged:
            if page_size < 1:
                raise ValueError(f"page_size must be >= 1, got {page_size}")
            self._page = page_size
            # Table width covers max_len plus the speculative overshoot
            # slack (verify writes reach position + draft_k +
            # tree_width).
            pps = -(-(lm.max_len + self._spec_k + self._spec_w)
                    // page_size)
            worst = slots * pps + 1  # every slot full + trash page
            if pool_pages is None:
                pool_pages = worst
            if pool_pages < 2:
                raise ValueError(
                    f"pool_pages must be >= 2, got {pool_pages}"
                )
            self._pager = Pager(
                pool_pages, slots, pps, page_tokens=page_size
            )
            self._pool_pages = pool_pages

            def one_cache():
                if self._kv_quant:
                    # (values, scales) POOL pair: the scale plane is one
                    # f32 per cached vector, page-addressed by the SAME
                    # table — prefix-shared pages carry their scales.
                    # int4 pools halve the value plane's lane width
                    # (two nibbles per int8 lane).
                    return (
                        jnp.zeros(
                            (pool_pages, heads, page_size,
                             self._kv_width),
                            jnp.int8,
                        ),
                        jnp.zeros(
                            (pool_pages, heads, page_size, 1), jnp.float32
                        ),
                    )
                return jnp.zeros(
                    (pool_pages, heads, page_size, head_dim), block0.dtype
                )

        else:
            self._pager = None

            def one_cache():
                if self._kv_quant:
                    return (
                        jnp.zeros(
                            (slots, heads, self._cache_len,
                             self._kv_width),
                            jnp.int8,
                        ),
                        jnp.zeros(
                            (slots, heads, self._cache_len, 1), jnp.float32
                        ),
                    )
                return jnp.zeros(
                    (slots, heads, self._cache_len, head_dim), block0.dtype
                )

        # -- hierarchical KV cache tier (docs/SERVING.md §3) ---------------
        if cache_tier is not None and not self._paged:
            raise ValueError(
                "cache_tier requires kv_layout='paged' (the spill "
                "tier lives under the paged prefix cache — dense slot "
                "strips have no page unit to spill)"
            )
        #: Host-DRAM spill tier under the prefix LRU: evicted rc=0
        #: pages spill (budgeted per tick) instead of dying, and the
        #: admission probe consults the tier before declaring a prefix
        #: miss — host hits readmit through the adopt_cached /
        #: _adopt_pages landing path and then admit as ordinary
        #: prefix-cache hits.
        self._tier_cfg = cache_tier
        self._tier = HostKVTier(cache_tier) if cache_tier else None
        #: Per-tick tier work budgets (reset at tick entry; seeded here
        #: so pre-first-tick evictions can spill too).
        self._spill_budget = (
            cache_tier.spill_pages_per_tick if cache_tier else 0
        )
        self._readmit_budget = (
            cache_tier.readmit_pages_per_tick if cache_tier else 0
        )
        if self._paged:
            # Always installed, tier or not: the hook records the
            # radix_evict flight event for every cached-prefix death
            # (spill/drop routing inside it stays tier-gated).
            self._pager.evict_hook = self._on_page_evict
        #: Instance-lifetime tier books (stats() mirrors of the
        #: cache_tier.* registry counters).
        self._tier_spilled = 0
        self._tier_readmitted = 0
        self._tier_dropped = 0
        #: High-water of the tier's own overflow-drop count already
        #: bridged to cache_tier.dropped_total (flushed per tick).
        self._tier_drop_seen = 0
        self._caches = [(one_cache(), one_cache()) for _ in lm.block_names]
        if mesh is not None:
            # Head-sharded KV: each device holds kv_heads / tp of every
            # slot strip (or pool page) — THE capacity win TP buys.
            self._caches = jax.device_put(self._caches, self._kv_sharding)
        #: What the SAME cache geometry would cost in the native dtype
        #: — the denominator of the memory.kv_bytes_ratio gauge, so the
        #: int8 capacity win (values + scale planes vs native) is
        #: directly observable on dashboards. Native batchers read 1.0.
        if self._paged:
            cache_positions = pool_pages * page_size
        else:
            cache_positions = slots * self._cache_len
        self._native_cache_bytes = (
            2
            * len(lm.block_names)
            * cache_positions
            * heads
            * head_dim
            * jnp.dtype(block0.dtype).itemsize
        )
        #: Idle-row cache position: slot layout parks garbage writes at
        #: the trash strip; paged layout uses a negative sentinel that
        #: stays negative across a whole tick's position advance
        #: (chunk steps, or the spec tick's up-to-draft_k+1(+1 with a
        #: tree-draft bonus) commit), routing every garbage write to
        #: the trash page.
        adv = (
            (self._spec_k + 1 + (1 if self._spec_w else 0))
            if self._spec
            else self.chunk
        )
        self._idle_pos = -(adv + 1) if self._paged else self._trash
        #: Draft-model slot caches (speculative mode): dense per-slot
        #: strips with the same draft_k + 1 slack as the single-request
        #: loop — the draft is small by construction, so slots x max_len
        #: dense strips cost what paging would save on the big model.
        if self._spec:
            dblock = draft_lm.graph.node(draft_lm.block_names[0]).module
            self._draft_blocks = [
                draft_lm.graph.node(n).module
                for n in draft_lm.block_names
            ]
            self._draft_embed = draft_lm.graph.node("embed").module
            # Tree drafts run one extra scan step (the leaf token's own
            # cache write), so the draft strip carries one more slack
            # position.
            dclen = (
                draft_lm.max_len + self._spec_k + 1
                + (1 if self._spec_w else 0)
            )

            def draft_cache():
                return jnp.zeros(
                    (slots, dblock.cache_heads, dclen, dblock.head_dim),
                    dblock.dtype,
                )

            self._draft_caches = [
                (draft_cache(), draft_cache())
                for _ in draft_lm.block_names
            ]
            if mesh is not None:
                # The DRAFT stays fully replicated: it is small by
                # construction (sharding it buys HBM that is not the
                # bottleneck and would force its head counts to divide
                # tp), and a replicated draft scan is collective-free —
                # the spec tick's ICI budget goes to the target's one
                # psum pair per block.
                self._draft_variables = jax.device_put(
                    draft_variables, self._repl
                )
                self._draft_caches = jax.device_put(
                    self._draft_caches, self._repl
                )
        else:
            self._draft_caches = None
        #: Speculation lifetime counters (instance-scoped, like the
        #: admit/complete counts): drafted proposals vs accepted ones.
        self._spec_drafted = 0
        self._spec_accepted = 0
        #: Host->device staging transfers (every jnp.asarray/device_put
        #: this module issues goes through _h2d). The fused-staging
        #: contract: ZERO on a steady-state decode tick, O(1) per
        #: admission/retirement — benchmarks/micro and tests assert it.
        self._h2d_count = 0
        #: Device-resident per-slot sampling state ("dstate"): one row
        #: per slot, written only by the donated jitted setters
        #: (_stage_slot / _clear_slot) and _step_chunk itself.
        self._dstate = {
            # last committed token (next decode input)
            "tok": jnp.zeros((slots,), jnp.int32),
            # cache position the next consumed token writes at
            "pos": jnp.full((slots,), self._idle_pos, jnp.int32),
            # per-slot folded key schedule + cursor: keys[b, kbase[b]+j]
            # samples step j of the next chunk (clipped to nkeys-1, the
            # final-key convention for steps past the request's end)
            "keys": jnp.zeros((slots, lm.max_len, 2), jnp.uint32),
            "kbase": jnp.zeros((slots,), jnp.int32),
            "nkeys": jnp.ones((slots,), jnp.int32),
            "temp": jnp.zeros((slots,), jnp.float32),
            "top_k": jnp.full((slots,), lm.vocab, jnp.int32),
            "top_p": jnp.ones((slots,), jnp.float32),
            # live-row mask: the step advances pos/kbase/tok only here,
            # re-parking idle rows at the sentinel every chunk
            "active": jnp.zeros((slots,), bool),
        }
        if mesh is not None:
            # Per-slot sampling state replicates: it is O(slots) scalars
            # — sharding it would trade nothing for collectives in the
            # setters.
            self._dstate = jax.device_put(self._dstate, self._repl)
        #: Device copy of the pager's page table, re-uploaded only when
        #: the host table actually changed (admission/retirement/window
        #: recycling) — a steady-state paged tick stages nothing.
        self._table_dev = None
        self._table_snapshot = None
        # -- sequence-parallel long-context prefill ------------------------
        #: ``config.PrefillConfig{sp_threshold, sp_width}``: admissions
        #: of at least the threshold prefill SP-SHARDED across a
        #: dedicated ``(sp,)`` / ``(sp, tp)`` mesh
        #: (``parallel/sp_prefill.SPPrefiller`` — ring-transported
        #: window, chunk-oracle attention) and their pages land through
        #: :meth:`adopt_prefill_pages` exactly like a disaggregated
        #: handoff, so the request then admits as a prefix-cache hit
        #: and the decode tier's mesh/programs are untouched. Byte-
        #: equal to the collocated chunked prefill (pinned), so greedy
        #: streams stay bit-identical. The prefiller's tp must MATCH
        #: this batcher's (its pages must be what THIS batcher's own
        #: chunked prefill would write, which is tp-sharded math for
        #: tp > 1).
        self._sp_cfg = prefill
        self._sp: SPPrefiller | None = None
        self._sp_prefills = 0
        #: Consecutive sp-dispatch failures: past the breaker the
        #: prefiller retires (every long admission was paying a doomed
        #: dispatch — e.g. a dead ring-only device no batcher-mesh
        #: event will ever recover) until a recovery rebuilds it.
        self._sp_failures = 0
        if prefill is not None and prefill.enabled:
            if not self._paged:
                raise ValueError(
                    "PrefillConfig sp prefill requires "
                    "kv_layout='paged' (the sp pages land through the "
                    "paged prefix cache)"
                )
            mesh_sp = sp_mesh
            if mesh_sp is None:
                mesh_sp = build_sp_mesh(
                    prefill.sp_width, self._tp, prefill.sp_axis,
                    self._axis,
                )
            if self._tp > 1:
                sp_tp_axis = self._axis
                if (
                    sp_tp_axis not in mesh_sp.shape
                    or int(mesh_sp.shape[sp_tp_axis]) != self._tp
                ):
                    raise ValueError(
                        f"sp_mesh must carry the batcher's tp axis "
                        f"{sp_tp_axis!r} at size {self._tp} — sp pages "
                        "must be what this batcher's own tp-sharded "
                        "chunked prefill would write"
                    )
            else:
                sp_tp_axis = (
                    self._axis if self._axis in mesh_sp.shape else None
                )
            self._sp = SPPrefiller(
                lm, self.variables, mesh_sp, self._page,
                kv_cache_dtype=kv_cache_dtype,
                sp_axis=prefill.sp_axis,
                tp_axis=sp_tp_axis,
                name="batcher-sp",
            )
            global_metrics().set_gauge(
                "prefill.sp_width", float(self._sp.sp)
            )
        # -- traffic control (docs/SERVING.md "Traffic control") -----------
        #: The submit queue is a runtime/scheduler.AdmissionQueue even
        #: without an explicit SchedulerConfig: bounded (the default
        #: max_queue_depth — a full slot map used to queue
        #: unboundedly) but otherwise STRICT FIFO, so a batcher that
        #: never opted into traffic control keeps its exact
        #: pre-scheduler admission order. An explicit config adds
        #: tenant quotas, weighted fair queueing, priority classes,
        #: preemption and the degradation controller.
        self._sched = scheduler
        self._queue: AdmissionQueue = AdmissionQueue(scheduler)
        if (
            self._paged
            and scheduler is not None
            and scheduler.cache_aware
        ):
            # Cache-aware admission ordering (SchedulerConfig
            # .cache_aware): among one class's queued candidates, the
            # queue prefers the request with the longest (then hottest)
            # RESIDENT radix prefix — a read-only token walk over the
            # pager's radix index, no rc movement, no page claims. The
            # probe returns None on a cold prompt so a probe-less
            # window stays byte-exact FIFO.
            def _probe(r, _pager=self._pager):
                pages, tokens, heat = _pager.radix_probe(r.prompt)
                return (tokens, heat) if pages else None

            self._queue.prefix_probe = _probe
        self._controller = (
            DegradationController(scheduler)
            if scheduler is not None and scheduler.degrade
            else None
        )
        #: Traffic-control books (instance-lifetime, _cv-guarded —
        #: mirrors of the scheduler.{rejected,preempted}_total
        #: counters).
        self._rejected = 0
        self._preempted = 0
        #: Tenants currently holding a scheduler.queue_depth gauge —
        #: tick prunes gauges the queue's bounded tenant map evicted,
        #: so adversarial fresh-label floods cannot grow the registry.
        self._gauged_tenants: set[str] = set()
        self._done: dict[int, np.ndarray] = {}
        #: Per-request logprob streams, claimable via logprobs() after
        #: the tokens are fetched. BOUNDED: callers that never claim
        #: them (the common tokens-only usage) must not leak — beyond
        #: _LPS_CAP unclaimed entries the oldest are evicted
        #: (insertion-ordered dict).
        self._done_lps: dict[int, np.ndarray] = {}
        self._cancelled: set[int] = set()
        #: req_id the ticking thread popped but has not yet bound to a
        #: slot — the only window where a live request is in neither
        #: the queue nor a slot (cancel() must still see it as live).
        self._admitting: int | None = None
        #: Copy-on-write fan-out (submit_fanout) books: group id ->
        #: _FanoutGroup. Mutations are _cv-guarded (submit and cancel
        #: run on client threads); pager claims only ever move on the
        #: ticking thread — client-side group deaths park their claimed
        #: page in ``_fanout_release``, drained at the next admission
        #: sweep (the pager is not thread-safe).
        self._fanout_groups: dict[int, _FanoutGroup] = {}
        self._fanout_next = 0
        self._fanout_release: list[int] = []
        self._next_id = 0
        self._prefill_cache: dict[int, Any] = {}  # bucket -> jitted fn
        # Instance-lifetime counts (stats() must not read the PROCESS
        # counters — two batchers would report each other's traffic).
        self._admitted = 0
        self._completed = 0
        self._ticks = 0
        #: Prompt tokens THIS batcher prefilled in-tick (full
        #: admissions, suffix passes, chunk passes — positions actually
        #: computed, prefix-cache hits excluded). Mirrored as the
        #: ``continuous.prefill_tokens_total`` counter so benches can
        #: report prefill-tokens/s and decode-tokens/s separately —
        #: the ratio disaggregation moves (handed-off requests prefill
        #: in the prefill tier, so only their suffix lands here).
        self._prefill_tokens = 0
        #: Request-timeline SLO histograms (queue-wait / TTFT /
        #: inter-token-latency / request latency). ON by default — the
        #: hot-path cost is one perf_counter stamp per committed token
        #: (ITL samples batch into ONE registry-lock acquisition per
        #: tick via observe_many); set False to measure the floor
        #: (benchmarks/micro/obs_overhead.py). Flight-recorder lifecycle
        #: events (admit/finish/cancel) are always-on, independent of
        #: this flag.
        self.obs_timeline = True
        self._itl_pending: list[float] = []
        self._ttft_pending: list[float] = []
        # -- pipelined tick runtime (config.RuntimeConfig) -----------------
        # depth=1: tick() dispatches and commits synchronously (the
        # historical loop, byte-identical scheduling). depth=2: tick()
        # dispatches tick t, then commits tick t-1's _InFlight while t
        # runs on device — one tick of results stays in flight between
        # calls, drained at every pipeline boundary (run() exit,
        # recover(), drain(), server-loop stop).
        self._runtime = runtime or RuntimeConfig()
        self._depth = self._runtime.pipeline_depth
        self._inflight: _InFlight | None = None
        #: SLO accounting (docs/OBSERVABILITY.md "Workload telemetry").
        #: Hot path touches only these plain ints (one attribute inc
        #: per evaluated stamp); the registry sees them once per tick
        #: in _obs_flush. Keys: ttft_met/ttft_missed/itl_met/itl_missed
        #: (this tick's pending) and the instance-lifetime mirrors.
        self._slo_pending = {
            "ttft_met": 0, "ttft_missed": 0,
            "itl_met": 0, "itl_missed": 0,
        }
        self._slo_totals = {
            "ttft_met": 0, "ttft_missed": 0,
            "itl_met": 0, "itl_missed": 0,
        }
        #: Committed tokens this tick (all, and from requests still
        #: inside budget) — flushed as continuous.{tokens,good_tokens}
        #: counters and folded into the goodput gauge.
        self._tick_tokens = 0
        self._tick_good_tokens = 0
        #: Rolling (t, good_tokens) per-tick samples spanning
        #: goodput_window_s — continuous.goodput_tokens_s is their rate
        #: (idle ticks append zeros, so the gauge decays instead of
        #: scraping the last busy tick's rate forever). The window is
        #: ``ObservabilityConfig.goodput_window_s``, shared with the
        #: capacity plane's windowed views.
        self._obs_cfg = observability or ObservabilityConfig()
        self.goodput_window_s = self._obs_cfg.goodput_window_s
        self._goodput_samples: collections.deque[tuple[float, int]] = (
            collections.deque()
        )
        # -- capacity / placement-signal plane (runtime/capacity) ----------
        #: The self-describing replica book: headroom, self-calibrating
        #: TTFT forecaster, prefix-affinity sketch, hysteresis health.
        #: Feeds are O(1) stamps on the submit/admit/commit sites;
        #: rebuilds ride the _obs_flush seam, rate-limited. None when
        #: ``CapacityConfig(enabled=False)`` — zero extra work anywhere
        #: (the obs_overhead capacity arm's floor).
        cap_cfg = capacity or CapacityConfig()
        self._capacity: CapacityModel | None = (
            CapacityModel(
                cap_cfg, kind="decode",
                window_s=self.goodput_window_s,
            )
            if cap_cfg.enabled
            else None
        )
        #: Previous _obs_flush stamp — the tick-gap EWMA feed (the
        #: forecaster's "how long until a queued request's next pickup
        #: opportunity" term). 0.0 until the first flush.
        self._cap_last_flush = 0.0
        #: Engine-tier observability (utils.profiling): per-phase tick
        #: timing behind the process-global EngineObs gate (one branch
        #: per phase when off), plus the compile sentinel sampled once
        #: per tick. Registration re-arms each program's warmup window —
        #: jit caches key on ``self``, so a fresh batcher legitimately
        #: compiles its own first variants.
        self._eobs = global_engine_obs()
        self._sentinel = global_compile_sentinel()
        self._sentinel.register(
            "continuous.step_chunk", type(self)._step_chunk
        )
        self._sentinel.register(
            "continuous.stage_slot", type(self)._stage_slot
        )
        self._sentinel.register(
            "continuous.clear_slot", type(self)._clear_slot
        )
        self._sentinel.register("continuous.insert", type(self)._insert)
        if self._paged:
            # Disaggregated-handoff landing program (adopt_prefill_pages
            # — dispatched only when a prefill tier streams pages in).
            self._sentinel.register(
                "continuous.adopt_pages", type(self)._adopt_pages
            )
            # Copy-on-write fan-out fork (one variant ever: no static
            # shape axis — dispatched only by submit_fanout siblings).
            self._sentinel.register(
                "continuous.fork_page", type(self)._fork_page
            )
        if self._spec:
            self._sentinel.register(
                "continuous.spec_verify", type(self)._spec_verify
            )
            self._sentinel.register("speculative.draft_chunk", draft_chunk)
        # The prefill family is a per-instance dict of jit closures
        # (bucket/suffix/draft variants): ONE shared watch sums the
        # cache sizes over every live batcher (weakly held), so a
        # second batcher aggregates instead of replacing the first's
        # watch. A late new-bucket admission fires the sentinel by
        # design — that tick really did pay a compile.
        _LIVE_BATCHERS.add(self)
        self._sentinel.register(
            "continuous.prefill",
            size_fn=aggregate_size_fn(_LIVE_BATCHERS, _prefill_family_size),
        )
        #: Pull-style memory accounting: dense strip / pool / draft
        #: bytes and paged occupancy served as memory.* gauges at every
        #: exporter scrape (weakly held — see utils.profiling).
        register_memory_source("continuous", self)
        #: Roofline source: XLA cost_analysis of the decode-path
        #: programs (lazy, cached — see _program_costs) + the engine
        #: phase walls, served as engine.{flops,bytes_accessed,mbu,mfu}
        #: gauges at scrape.
        self._roofline_costs: dict | None = None
        register_roofline_source("continuous", self)
        # Threaded serving (start()/result()/stop()): one condition
        # guards every mutation of the queue/done handoff state and the
        # server-thread lifecycle; compiled work runs outside the lock,
        # on the server thread only.
        self._cv = threading.Condition()
        self._server: threading.Thread | None = None
        self._stopping = False
        #: Exception that killed the server thread's tick (re-raised to
        #: result() waiters instead of a misleading timeout).
        self._server_error: BaseException | None = None
        # -- elastic mesh recovery (docs/SERVING.md "Elastic recovery") ----
        #: Knobs: auto-reshard at tick vs raise DeviceLostError,
        #: migrate-vs-replay policy, min surviving tp.
        self._recovery = recovery or RecoveryConfig()
        #: ``control.registry.DeviceHealthMonitor`` (duck-typed): the
        #: batcher registers its mesh devices as TTL-lease members and
        #: subscribes to ``leave`` events — a simulated kill (or a real
        #: lease expiry) lands in ``_lost_pending`` and the next tick
        #: re-shards (or raises, per ``auto_reshard``).
        self._health = health
        #: Optional ``control.journal.DispatcherJournal``: submits are
        #: journaled (payload + sampling-knob meta), finishes done-
        #: marked, and non-migratable requests at recovery REPLAY from
        #: the journaled record — re-entering through the paged prefix
        #: cache when the prompt pages are still resident.
        self._journal = journal
        if journal is not None:
            # Serving over an existing WAL (crash recovery) must not
            # recycle ids: a fresh counter reaching a still-pending id
            # would os.replace that request's journaled payload and
            # done-mark it away — the exact hazard
            # journal.next_request_id exists to prevent.
            self._next_id = max(self._next_id, journal.next_request_id)
        #: Membership keys of devices reported lost but not yet
        #: recovered from (guarded by ``_cv``; consumed at tick entry).
        self._lost_pending: list[str] = []
        #: The devices serving this batcher, in tp-axis order — kept
        #: distinct from ``_mesh`` because a tp=1 batcher (constructed
        #: with a 1-device mesh, or the remnant a recovery down to tp=1
        #: leaves) sets ``_mesh = None`` (single-device discipline)
        #: while its device must STILL be trackable and
        #: recoverable-from: losing it has to raise, not silently
        #: dispatch onto a dead chip.
        if self._mesh is not None:
            self._mesh_devices: list = list(self._mesh.devices.flat)
        elif isinstance(self._repl, SingleDeviceSharding):
            self._mesh_devices = list(self._repl.device_set)
        else:
            self._mesh_devices = []
        self._mesh_device_ids: set[int] = {
            int(d.id) for d in self._mesh_devices
        }
        #: Static re-trace key for the programs that bake concrete
        #: sharding constraints into their jaxprs (``_shard_kv`` /
        #: ``_repl_state``): jit caches TRACES on avals + statics only,
        #: so without this a post-recovery dispatch would reuse a jaxpr
        #: whose constraints still name the dead device. Bumped once
        #: per recovery.
        self._mesh_epoch = 0
        #: Per program family, the static-variant keys THIS batcher has
        #: dispatched under the current mesh epoch (step_chunk's
        #: (truncate, nucleus) combos, stage_slot's key buckets,
        #: _insert's prompt buckets). ``recover()`` sizes each family's
        #: expected-compile allowance from these — every variant in use
        #: re-traces after the epoch bump, so a mixed-traffic batcher
        #: legitimately re-lowers MORE than one variant per family.
        #: Ticking-thread only (dispatch sites), like the caches.
        self._variants: dict[str, set] = {}
        #: Cumulative expected-compile allowances THIS batcher granted
        #: at its recoveries (program -> units) — close() disarms them
        #: so unconsumed slack cannot outlive the granter on the shared
        #: class-level sentinel watches.
        self._granted: dict[str, int] = {}
        # Instance-lifetime recovery books (stats() mirrors of the
        # recovery.* registry counters).
        self._recoveries = 0
        self._recovery_migrated = 0
        self._recovery_replayed = 0
        self._recovery_dropped = 0
        self._last_recovery_wall_s = 0.0
        #: close() flips this: a retired batcher must stop consuming
        #: membership events (its compiled state is gone).
        self._retired = False
        if health is not None and self._mesh_devices:
            health.track(self._mesh_devices)
            # Weak subscription (control.registry.weak_watch): the
            # watcher list has no unwatch and outlives any batcher — a
            # bound method there would pin a retired batcher's weights
            # and KV pools forever (the same discipline as
            # _LIVE_BATCHERS being a WeakSet). The shim dies into a
            # no-op when the batcher is collected, and goes quiet at
            # close() via _retired.
            weak_watch(health, self, "_on_device_event")
            # A device already dead at construction — or killed between
            # track() and watch() — delivers NO future 'leave' event
            # (its lease is gone and track() refuses to resurrect it),
            # so seed the pending set from the monitor's dead roster or
            # every tick dispatches onto the dead chip undetected.
            for did in sorted(health.dead_ids() & self._mesh_device_ids):
                self._on_device_event("leave", f"device:{did}")

    # -- compiled pieces ---------------------------------------------------

    def _h2d(self, x):
        """The ONE host->device staging funnel for this module: counts
        every transfer so tests and benchmarks/micro can assert the
        fused-staging contract (0 per steady tick, O(1) per admission)
        instead of trusting docstrings. Under a mesh, staged arrays are
        placed REPLICATED explicitly (a one-device-committed array mixed
        into a sharded program would force GSPMD reshards); one logical
        transfer either way. ``_repl`` (not ``_mesh``) is the guard: a
        batcher recovered down to tp=1 keeps staging onto its surviving
        device (``SingleDeviceSharding``) — ``jnp.asarray`` would land
        on the default device, which may be the dead one."""
        self._h2d_count += 1
        if self._repl is not None:
            return jax.device_put(x, self._repl)
        return jnp.asarray(x)

    def _shard_kv(self, caches):
        """Explicit in/out cache sharding for the compiled programs:
        pin every KV leaf (dense strips, pools, int8 scale planes) to
        the head-axis sharding so GSPMD partitions the decode math and
        inserts the block psums, instead of falling back to whatever
        propagation guesses. No-mesh batchers pay one branch.

        The CONCRETE sharding is baked into the traced jaxpr, and jit
        caches traces on avals + STATIC args only — which is why every
        program that calls this (or ``_repl_state``) carries a static
        ``epoch`` argument: elastic recovery bumps ``_mesh_epoch`` so
        the re-lowered families re-TRACE against the shrunk mesh
        instead of reusing a jaxpr whose constraints name dead
        devices."""
        if self._mesh is None:
            return caches
        return jax.tree.map(
            lambda c: lax.with_sharding_constraint(c, self._kv_sharding),
            caches,
        )

    def _repl_state(self, dstate):
        """Explicit in/out sharding for the per-slot sampling state:
        pinned REPLICATED through every donated program. Left to
        propagation, GSPMD may pick different output shardings for the
        pass-through leaves in different programs (observed: the key
        schedules came back head-split from the verify program but
        replicated from the admission setter), and a producer-to-
        producer sharding flip is a phantom jit variant in every
        consumer — the exact recompile class the sentinel exists to
        catch."""
        if self._mesh is None:
            return dstate
        return {
            k: lax.with_sharding_constraint(x, self._repl)
            for k, x in dstate.items()
        }

    @partial(
        jax.jit,
        static_argnums=(0,),
        static_argnames=("epoch",),
        donate_argnums=(1,),
    )
    def _stage_slot(self, dstate, ints, floats, keys, *, epoch=0):
        """Write one admitted request's whole sampling row into the
        donated device state: ``ints`` (6,) int32 = [slot, tok, pos,
        top_k, nkeys, kbase], ``floats`` (2,) f32 = [temp, top_p],
        ``keys`` (nkb, 2) uint32 = the folded key schedule padded to a
        power-of-two bucket (log2 compile variants; the pad tail is
        never read — the step clips the cursor to nkeys-1). O(1) fused
        transfers per admission, not one per field."""
        i = ints[0]
        d = dict(dstate)
        d["tok"] = dstate["tok"].at[i].set(ints[1])
        d["pos"] = dstate["pos"].at[i].set(ints[2])
        d["top_k"] = dstate["top_k"].at[i].set(ints[3])
        d["nkeys"] = dstate["nkeys"].at[i].set(ints[4])
        d["kbase"] = dstate["kbase"].at[i].set(ints[5])
        d["temp"] = dstate["temp"].at[i].set(floats[0])
        d["top_p"] = dstate["top_p"].at[i].set(floats[1])
        d["keys"] = lax.dynamic_update_slice(
            dstate["keys"], keys[None], (i, 0, 0)
        )
        d["active"] = dstate["active"].at[i].set(True)
        return self._repl_state(d)

    @partial(
        jax.jit,
        static_argnums=(0,),
        static_argnames=("epoch",),
        donate_argnums=(1,),
    )
    def _clear_slot(self, dstate, slot, *, epoch=0):
        """Retire one slot's device row: park its position at the idle
        sentinel and drop it from the active mask (the step re-parks it
        every chunk thereafter). Identity sampling knobs keep the
        garbage row off the truncate/nucleus sorts."""
        d = dict(dstate)
        d["pos"] = dstate["pos"].at[slot].set(self._idle_pos)
        d["tok"] = dstate["tok"].at[slot].set(0)
        d["kbase"] = dstate["kbase"].at[slot].set(0)
        d["nkeys"] = dstate["nkeys"].at[slot].set(1)
        d["temp"] = dstate["temp"].at[slot].set(0.0)
        d["top_k"] = dstate["top_k"].at[slot].set(self.lm.vocab)
        d["top_p"] = dstate["top_p"].at[slot].set(1.0)
        d["active"] = dstate["active"].at[slot].set(False)
        return self._repl_state(d)

    def _truncate_rows(self, lg, top_ks):
        """Per-row top-k filter with a TRACED k: keep logits >= the k-th
        largest (``sorted[V-k]`` — bitwise the same threshold
        generate()'s ``lax.top_k`` filter uses, so mixed-top_k batches
        match per-request ``generate`` without recompiling); k == V
        keeps everything. Costs a full (B, V) sort, so callers gate it
        behind a STATIC flag and skip it when no active request
        truncates — the hot path must not pay O(V log V) for a no-op
        (``sample_next_tokens``'s lax.top_k rule)."""
        v = lg.shape[-1]
        sorted_lg = jnp.sort(lg, axis=-1)  # ascending
        idx = jnp.clip(v - top_ks, 0, v - 1)
        kth = jnp.take_along_axis(sorted_lg, idx[:, None], axis=-1)
        return jnp.where(lg >= kth, lg, -jnp.inf)

    @partial(
        jax.jit,
        static_argnums=(0,),
        static_argnames=("truncate", "nucleus", "epoch"),
        donate_argnums=(2, 3),
    )
    def _step_chunk(self, variables, caches, dstate, table=None, *,
                    truncate, nucleus, epoch=0):
        """``chunk`` lockstep decode steps as one compiled scan over the
        DEVICE-RESIDENT slot state.

        ``dstate`` carries every per-slot input the old host-staged path
        transferred each tick (token, position, temps, top_ks, top_ps,
        key schedules) — donated in, advanced on device, returned out,
        so a steady-state tick stages zero host scalars. Each step's
        (B, 2) sampling keys gather from the resident per-slot schedules
        at ``kbase + j`` (clipped to ``nkeys - 1``: steps past a
        request's end sample with its final key — garbage the host
        truncation never reads). Greedy selection derives from
        ``temp == 0`` (submit's normalization). Static ``truncate`` /
        ``nucleus`` elide the top-k/top-p sorts when no active request
        needs them (at most 2x2 compiled variants). ``table`` (paged
        layout only) addresses each block's (k_pool, v_pool) through the
        shared page table — the cache plumbing is the ONLY thing that
        differs between layouts; the sampling schedule is this one body.
        Inactive rows re-park at the idle sentinel after the chunk's
        optimistic pos advance; rows whose request retires mid-chunk are
        cleared host-side (``_clear_slot``) before the next tick.
        Returns ((chunk, B) emitted tokens, logprobs, caches, dstate);
        ONE host sync per call, not per token."""
        paged = table is not None
        caches = self._shard_kv(caches)
        dstate = self._repl_state(dstate)
        C = self.chunk
        temps = dstate["temp"]
        top_ks = dstate["top_k"]
        top_ps = dstate["top_p"]
        greedy = temps == 0.0
        active = dstate["active"]
        kbase, nkeys = dstate["kbase"], dstate["nkeys"]
        # (B, C) key cursors -> (C, B, 2) per-step keys, one gather.
        cursor = jnp.clip(
            kbase[:, None] + jnp.arange(C)[None, :], 0,
            (nkeys - 1)[:, None],
        )
        keys = jnp.swapaxes(
            jnp.take_along_axis(
                dstate["keys"], cursor[:, :, None], axis=1
            ),
            0, 1,
        )

        def body(carry, step_keys):
            tokens, pos, caches = carry
            x = self._embed.apply(
                variables["embed"], tokens[:, None], pos[:, None],
                method="embed_positions",
            )
            new_caches = []
            for name, block, cache in zip(
                self.lm.block_names, self._blocks, caches
            ):
                if paged:
                    kp, vp = cache
                    x, kp, vp = block.apply(
                        variables[name], x, kp, vp, table, pos, None,
                        self._kernel.attn_impl,
                        self._kernel.decode_split,
                        method="decode_step_paged",
                    )
                    new_caches.append((kp, vp))
                else:
                    ck, cv = cache
                    x, ck, cv = block.apply(
                        variables[name], x, ck, cv, pos, None,
                        self._kv_quant, self._kernel.attn_impl,
                        self._kernel.decode_split,
                        method="decode_step",
                    )
                    new_caches.append((ck, cv))
            logits = self._head.apply(variables["head"], x)[:, 0]  # (B, V)
            pick_greedy = jnp.argmax(logits, axis=-1)
            lg = logits / jnp.maximum(temps, 1e-6)[:, None]
            if truncate:
                lg = self._truncate_rows(lg, top_ks)
            if nucleus:
                lg = nucleus_filter(lg, top_ps)
            pick_sampled = jax.vmap(jax.random.categorical)(step_keys, lg)
            nxt = jnp.where(greedy, pick_greedy, pick_sampled).astype(
                tokens.dtype
            )
            # One cheap (B, V) reduction per step, always emitted;
            # chosen_logprob is THE shared scoring convention.
            lp = chosen_logprob(logits, nxt)
            return (nxt, pos + 1, tuple(new_caches)), (nxt, lp)

        (_, _, caches), (toks, lps) = lax.scan(
            body, (dstate["tok"], dstate["pos"], tuple(caches)), keys
        )
        # Optimistic device-side advance: a surviving slot commits all C
        # tokens (any mid-chunk finish retires it and the host clears
        # its row), so pos/kbase/tok land exactly on the next tick's
        # entry invariants. Idle rows re-park at the sentinel — without
        # this, the scan's pos+1 increments would walk a retired paged
        # row's sentinel up into real page territory.
        new = dict(dstate)
        new["pos"] = jnp.where(active, dstate["pos"] + C, self._idle_pos)
        new["tok"] = jnp.where(active, toks[-1], 0)
        new["kbase"] = jnp.where(active, kbase + C, 0)
        return (
            toks, lps, self._shard_kv(list(caches)),
            self._repl_state(new),
        )

    @partial(
        jax.jit,
        static_argnums=(0,),
        static_argnames=("sample", "truncate", "nucleus", "epoch"),
        donate_argnums=(2, 3),
    )
    def _spec_verify(self, variables, caches, dstate, dtoks, table=None,
                     cands=None, *, sample=False, truncate=False,
                     nucleus=False, epoch=0):
        """The speculative tick's VERIFY program — the second of its
        exactly two compiled programs (the first is the shared
        ``models/speculative.draft_chunk`` scan).

        Static ``sample`` (with ``truncate``/``nucleus``, the
        _step_chunk flag discipline) turns on SPECULATIVE SAMPLING for
        ticks whose batch carries any ``temperature > 0`` row: each
        proposal is accepted with the target's own probability of that
        token under the row's processed distribution (the draft
        proposes its argmax — a delta proposal, so ``min(1, p/q)``
        reduces to ``p(token)``), a rejection resamples from the
        RESIDUAL distribution (proposal mass removed), and the position
        after a fully-accepted chain draws fresh — the standard
        correction, provably the target's per-position sampling
        distribution (lossless in DISTRIBUTION). Greedy rows in the
        same batch keep their exact argmax stream via the final
        select; all-greedy ticks compile ``sample=False``, whose
        program text is unchanged from the greedy-only version.

        Builds every slot's (draft_k + 1) chunk ``[last_token,
        proposals]`` ON DEVICE from the draft scan's output, runs one
        fused ``verify_chunk`` / ``verify_chunk_paged`` pass over all
        slots at their own positions (rows desynchronize; the program
        does not), reduces each row's longest agreeing prefix
        (``accept_speculation``), and advances the donated device state
        by each row's commit count — so the steady-state spec tick
        stages zero host arrays and the caller performs ONE fused
        device->host fetch of (tokens, logprobs, accepted). Inactive
        rows re-park at the idle sentinel; their writes are
        trash-routed by the verify primitives. Returns ((d+1, B)
        tokens, (d+1, B) logprobs, (B,) accepted counts, caches,
        dstate).

        TREE DRAFTS (``cands`` (B, w) — the draft's top-w ids for the
        position after the chain, ``SpeculativeConfig.tree_width``):
        the chunk grows w LEAF rows verified in the same pass under the
        tree mask. When a row's whole chain accepts AND its correction
        token (the target's own pick for the leaf position) matches a
        leaf, that leaf's cache entry is already written — the first
        matching leaf's K/V moves to the canonical ``pos + d + 1`` slot
        (one per-row gather/scatter per block; a no-op identity copy
        when the match IS the first leaf) — and the target's prediction
        AFTER that leaf commits as a BONUS token: up to d + 2 commits
        per verify pass. Outputs then carry d + 2 token rows and
        ``acc`` counts the bonus (commit limit stays ``acc + 1``)."""
        paged = table is not None
        tree = cands is not None
        w = cands.shape[1] if tree else 0
        caches = self._shard_kv(caches)
        dstate = self._repl_state(dstate)
        # The round's speculation depth comes from the DRAFT OUTPUT's
        # static shape, not self._spec_k: the degradation ladder
        # shrinks the effective draft_k at runtime (set_draft_k), and
        # each distinct depth is its own jit variant keyed by this
        # aval — reading the attribute would silently bake the
        # construction-time value into every variant. (Tree rounds
        # carry d + 2 draft rows: d proposals + the argmax leaf + the
        # leaf-coverage step.)
        d = dtoks.shape[0] - (2 if tree else 1)
        kc = d + 1 + w  # verify chunk rows: chain + leaves
        tok, pos = dstate["tok"], dstate["pos"]
        active = dstate["active"]
        props = jnp.swapaxes(dtoks[:d], 0, 1)  # (B, d)
        parts = [tok[:, None], props.astype(tok.dtype)]
        if tree:
            parts.append(cands.astype(tok.dtype))  # (B, w) leaf rows
        chunk = jnp.concatenate(parts, axis=1)  # (B, kc)
        # Chain rows embed at their own offsets; leaf rows share the
        # post-chain logical position d + 1 (their physical cache slots
        # d + 1 .. d + w stay distinct — the tree mask's contract).
        offs = jnp.minimum(jnp.arange(kc), d + 1)
        pos_ids = pos[:, None] + offs[None, :]
        x = self._embed.apply(
            variables["embed"], chunk, pos_ids, method="embed_positions"
        )
        new_caches = []
        for name, block, cache in zip(
            self.lm.block_names, self._blocks, caches
        ):
            if paged:
                kp, vp = cache
                x, kp, vp = block.apply(
                    variables[name], x, kp, vp, table, pos,
                    self._kernel.attn_impl, w,
                    self._kernel.decode_split,
                    method="verify_chunk_paged",
                )
                new_caches.append((kp, vp))
            else:
                ck, cv = cache
                x, ck, cv = block.apply(
                    variables[name], x, ck, cv, pos, w,
                    method="verify_chunk",
                )
                new_caches.append((ck, cv))
        logits = self._head.apply(variables["head"], x)  # (B, kc, V)
        preds = jnp.argmax(logits, axis=-1).astype(tok.dtype)
        lps = chosen_logprob(
            logits.reshape(-1, logits.shape[-1]), preds.reshape(-1)
        ).reshape(preds.shape)  # (B, kc)
        acc = accept_speculation(props, preds[:, : d + 1])  # (B,)
        if sample:
            nd = d + 1
            vocab = logits.shape[-1]
            temps = dstate["temp"]
            greedy = temps == 0.0
            kbase, nkeys = dstate["kbase"], dstate["nkeys"]
            # Key discipline matches _step_chunk: the token committed
            # at stream offset j consumes the key at kbase + j (kbase
            # advances by ncommit below). Each key splits once into an
            # acceptance subkey and a resample subkey.
            cursor = jnp.clip(
                kbase[:, None] + jnp.arange(nd)[None, :], 0,
                (nkeys - 1)[:, None],
            )
            skeys = jnp.take_along_axis(
                dstate["keys"], cursor[:, :, None], axis=1
            )  # (B, nd, 2)
            subkeys = jax.vmap(jax.vmap(jax.random.split))(skeys)
            k_acc, k_res = subkeys[:, :, 0, :], subkeys[:, :, 1, :]
            lg = (
                logits[:, :nd]
                / jnp.maximum(temps, 1e-6)[:, None, None]
            )
            flat = lg.reshape(-1, vocab)
            if truncate:
                flat = self._truncate_rows(
                    flat, jnp.repeat(dstate["top_k"], nd)
                )
            if nucleus:
                flat = nucleus_filter(
                    flat, jnp.repeat(dstate["top_p"], nd)
                )
            lgp = flat.reshape(lg.shape)  # processed logits (B, nd, V)
            p_prop = jnp.take_along_axis(
                jax.nn.log_softmax(lgp[:, :d], axis=-1),
                props[:, :, None].astype(jnp.int32), axis=2,
            )[..., 0]  # (B, d): log p_target(proposal_j)
            u = jax.vmap(jax.vmap(jax.random.uniform))(k_acc)  # (B, nd)
            ok = u[:, :d] < jnp.exp(p_prop)
            cum = jnp.cumprod(ok.astype(jnp.int32), axis=1)  # (B, d)
            acc_s = jnp.sum(cum, axis=1)
            # Residual for chain rows: proposal mass removed (a
            # proposal that is the only surviving token has p = 1, is
            # always accepted, and its empty residual is never read).
            # Row d has no proposal — a fresh full-distribution draw.
            res = jnp.where(
                jnp.arange(vocab)[None, None, :]
                == props[:, :, None].astype(jnp.int32),
                -jnp.inf, lgp[:, :d],
            )
            alt = jax.vmap(jax.vmap(jax.random.categorical))(
                k_res, jnp.concatenate([res, lgp[:, d:]], axis=1)
            ).astype(tok.dtype)  # (B, nd)
            out_s = jnp.concatenate(
                [
                    jnp.where(
                        cum.astype(bool), props.astype(tok.dtype),
                        alt[:, :d],
                    ),
                    alt[:, d:],
                ],
                axis=1,
            )  # (B, nd)
            lps_s = chosen_logprob(
                logits[:, :nd].reshape(-1, vocab), out_s.reshape(-1)
            ).reshape(out_s.shape)  # raw-logit scoring, like _step_chunk
            sel = greedy[:, None]
            preds = jnp.concatenate(
                [jnp.where(sel, preds[:, :nd], out_s), preds[:, nd:]],
                axis=1,
            )
            lps = jnp.concatenate(
                [jnp.where(sel, lps[:, :nd], lps_s), lps[:, nd:]],
                axis=1,
            )
            acc = jnp.where(greedy, acc, acc_s)
        out_preds, out_lps = preds, lps
        if tree:
            # Bonus acceptance: full chain + correction token == a leaf
            # candidate -> the leaf's K/V is in cache and the target's
            # prediction after it commits too.
            corr = preds[:, d]  # target's token for position pos + d + 1
            match = cands.astype(corr.dtype) == corr[:, None]  # (B, w)
            hit = jnp.logical_and(acc == d, jnp.any(match, axis=1))
            if sample:
                # Sampled rows take no tree bonus: the leaf's cached
                # K/V and the post-leaf prediction are argmax
                # artifacts — committing them would bias the stream.
                hit = jnp.logical_and(hit, greedy)
            s = jnp.argmax(match, axis=1)  # first matching leaf
            leaf_row = d + 1 + s
            bonus_tok = jnp.take_along_axis(
                preds, leaf_row[:, None], axis=1
            )[:, 0]
            bonus_lp = jnp.take_along_axis(
                lps, leaf_row[:, None], axis=1
            )[:, 0]
            out_preds = jnp.concatenate(
                [preds[:, : d + 1], bonus_tok[:, None]], axis=1
            )  # (B, d+2)
            out_lps = jnp.concatenate(
                [lps[:, : d + 1], bonus_lp[:, None]], axis=1
            )
            # Canonicalize the accepted leaf's cache entry: move leaf s
            # from physical pos + d + 1 + s to pos + d + 1. Rows with
            # s == 0, no hit, or inactive reduce to an identity
            # self-copy at a safe position (dead rows target the trash
            # page / trash strip — the ordinary garbage discipline).
            do = jnp.logical_and(hit, jnp.logical_and(s > 0, active))
            base = jnp.maximum(pos, 0) + d + 1
            p_dst = jnp.where(do, base, 0)
            p_src = jnp.where(do, base + s, 0)
            if paged:
                pg = self._page
                phys_dst = jnp.take_along_axis(
                    table, (p_dst // pg)[:, None], axis=1
                )[:, 0]
                phys_src = jnp.take_along_axis(
                    table, (p_src // pg)[:, None], axis=1
                )[:, 0]
                off_dst, off_src = p_dst % pg, p_src % pg

                def fix(pool):
                    vec = pool[phys_src, :, off_src, :]  # (B, kvh, wd)
                    return pool.at[phys_dst, :, off_dst, :].set(vec)

            else:

                def fix(cache):
                    vec = jax.vmap(
                        lambda c, i: lax.dynamic_slice(
                            c, (0, i, 0), (c.shape[0], 1, c.shape[2])
                        )
                    )(cache, p_src)
                    return jax.vmap(
                        lambda c, v, i: lax.dynamic_update_slice(
                            c, v, (0, i, 0)
                        )
                    )(cache, vec, p_dst)

            new_caches = [
                jax.tree.map(fix, pair) for pair in new_caches
            ]
            acc = acc + hit.astype(acc.dtype)
        ncommit = acc + 1
        last = jnp.take_along_axis(out_preds, acc[:, None], axis=1)[:, 0]
        # Optimistic device-side advance, exactly _step_chunk's
        # discipline: a surviving slot's entry invariants land on
        # pos + ncommit; retired slots are cleared host-side
        # (_clear_slot) before the next tick; idle rows re-park.
        new = dict(dstate)
        new["pos"] = jnp.where(active, pos + ncommit, self._idle_pos)
        new["tok"] = jnp.where(active, last, 0)
        new["kbase"] = jnp.where(active, dstate["kbase"] + ncommit, 0)
        return (
            jnp.swapaxes(out_preds, 0, 1),
            jnp.swapaxes(out_lps, 0, 1),
            acc,
            self._shard_kv(new_caches),
            self._repl_state(new),
        )

    @partial(
        jax.jit,
        static_argnums=(0,),
        static_argnames=("epoch",),
        donate_argnums=(1,),
    )
    def _adopt_pages(self, caches, pages, kvs, *, epoch=0):
        """Scatter STREAMED page-major KV chunks into the pool — the
        disaggregated-handoff landing program (``runtime/disagg`` ->
        :meth:`adopt_prefill_pages`). ``pages`` (nb,) physical page
        ids (power-of-two padded; pad entries point at the trash
        page), ``kvs`` mirrors ``caches``' per-block (K, V) structure
        with leaves ``(nb, kvh, page, w)`` already PLACED to the
        pool's sharding by the ``KVHandoffPlan`` — so under a
        head-sharded mesh this scatter is fully shard-local (each
        device writes only its resident heads; no collective, no
        replicated staging). One program for all blocks; specializes
        per page-count bucket (log2 variants)."""
        caches = self._shard_kv(caches)
        kvs = self._shard_kv(kvs)
        out = [
            jax.tree.map(
                lambda pool, kv: pool.at[pages].set(kv.astype(pool.dtype)),
                c_pair,
                n_pair,
            )
            for c_pair, n_pair in zip(caches, kvs)
        ]
        return self._shard_kv(out)

    @partial(
        jax.jit,
        static_argnums=(0,),
        static_argnames=("epoch",),
        donate_argnums=(1,),
    )
    def _fork_page(self, caches, srcdst, *, epoch=0):
        """Copy-on-write fork: duplicate ONE physical page — every
        block, both members of a quantized ``(values, scales)`` pair,
        so the copy's scales travel with its int8 values — from
        ``srcdst[0]`` into ``srcdst[1]``. The destination is a fan-out
        sibling's freshly allocated private copy of its group's last
        shared prompt page, taken at admission because the sibling's
        decode is about to WRITE into that page (the eager moment of
        "fork on first write": every sibling writes at its first
        step). Pure pool gather/scatter, no forward pass; shard-local
        under a head-sharded mesh (each device copies only its
        resident heads). One compiled variant ever — there is no
        static shape axis."""
        caches = self._shard_kv(caches)
        src, dst = srcdst[0], srcdst[1]
        out = [
            jax.tree.map(
                lambda pool: pool.at[dst].set(pool[src]), c_pair
            )
            for c_pair in caches
        ]
        return self._shard_kv(out)

    def adopt_prefill_pages(self, prompt, blocks, page_size: int,
                            quantized) -> int:
        """Land a disaggregated prefill's KV pages in this batcher's
        pool THROUGH THE PREFIX CACHE — the decode-side half of the
        ``runtime/disagg`` handoff. ``blocks`` is one ``(K, V)`` pair
        per decoder block, each member a page-major ``(n, kvh, page,
        hd)`` host array (or a ``(values, scales)`` tuple of them for
        int8 pools), holding the K/V of ``prompt``'s first ``n`` FULL
        pages exactly as this batcher's own chunked prefill would have
        written them.

        Pages register under the same content keys the admission
        prefix probe computes (``Pager.prefix_key``), park rc=0 in the
        prefix LRU, and their bytes scatter in via :meth:`_adopt_pages`
        — so a subsequent :meth:`submit` of the same prompt admits as
        a PREFIX-CACHE HIT and prefills only the suffix (the partial
        last page + first-token sampling). That reuse of the existing
        insertion path is what makes int8 pools (both members move
        under one :class:`~adapt_tpu.parallel.sharding.KVHandoffPlan`)
        and speculative mode (the draft prefills decode-side as
        always) compose with disaggregation for free, and keeps greedy
        streams bit-identical to the collocated path.

        Returns the number of pages actually adopted: already-resident
        keys dedupe (first writer won), and pool pressure adopts
        NOTHING (all-or-nothing, like admission) — the caller just
        submits and the request collocates its own prefill. Raises
        ``ValueError`` on geometry mismatches (layout, page size,
        quantization, block count/shapes) — a malformed handoff must
        fail by name, never scatter garbage into live pages."""
        # The device-lost gate tick() runs: a handoff landing between
        # ticks must not device_put shard slices onto a dead device or
        # dispatch the adoption program at a stale mesh epoch (the
        # disaggregated server lands handoffs BEFORE its decode tick).
        self._ensure_mesh()
        if not self._paged:
            raise ValueError(
                "adopt_prefill_pages requires kv_layout='paged' (the "
                "handoff lands through the paged prefix cache)"
            )
        if page_size != self._page:
            raise ValueError(
                f"handoff page size {page_size} != pool page size "
                f"{self._page}"
            )
        # ``quantized`` is the sender's kv dtype: a legacy bool (True =
        # int8) or the dtype string — int4 handoffs must land in int4
        # pools (the packed value width is part of the wire geometry).
        sender_dt = (
            quantized
            if isinstance(quantized, str)
            else ("int8" if quantized else "native")
        )
        if sender_dt != self._kv_dtype:
            raise ValueError(
                f"handoff kv dtype {sender_dt!r} but pool "
                f"kv_cache_dtype is {self._kv_dtype!r}"
            )
        if len(blocks) != len(self._blocks):
            raise ValueError(
                f"handoff has {len(blocks)} blocks, model has "
                f"{len(self._blocks)}"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        k0 = blocks[0][0]
        leaf0 = k0[0] if isinstance(k0, tuple) else k0
        n = int(leaf0.shape[0])
        if n < 1 or n > (prompt.shape[0] - 1) // self._page:
            raise ValueError(
                f"handoff covers {n} pages; prompt of "
                f"{prompt.shape[0]} tokens shares at most "
                f"{(prompt.shape[0] - 1) // self._page} full pages"
            )
        # EVERY block's geometry validates BEFORE any pager mutation:
        # adopt_cached registers prefix keys, and raising after it
        # would leave content keys pointing at never-written pages —
        # the next same-prefix admission would prefix-hit garbage.
        for b, (block, pair) in enumerate(zip(self._blocks, blocks)):
            for mname, member in zip(("K", "V"), pair):
                if isinstance(member, tuple) != self._kv_quant:
                    raise ValueError(
                        f"handoff block {b} {mname}: "
                        f"{'tuple' if isinstance(member, tuple) else 'array'}"
                        f" member in a "
                        f"{'quantized' if self._kv_quant else 'native'}"
                        " pool"
                    )
                leaves = member if isinstance(member, tuple) else (member,)
                for li, leaf in enumerate(leaves):
                    # Value plane carries the POOL's lane width (packed
                    # for int4), the scale plane one f32 per vector.
                    if li == 0:
                        width = block.head_dim // (
                            2 if self._kv_dtype == "int4" else 1
                        )
                    else:
                        width = 1
                    want = (n, block.cache_heads, self._page, width)
                    if tuple(np.shape(leaf)) != want:
                        raise ValueError(
                            f"handoff block {b} {mname}[{li}] shape "
                            f"{tuple(np.shape(leaf))} != expected {want}"
                        )
        keys = [
            Pager.prefix_key(prompt, (j + 1) * self._page)
            for j in range(n)
        ]
        adopted = self._pager.adopt_cached(keys)
        if not adopted:
            return 0
        ords = [i for i, _ in adopted]
        pages = [p for _, p in adopted]
        na = len(ords)
        nb = 1
        while nb < na:
            nb *= 2

        def select(kv):
            kv = np.asarray(kv)
            if na == nb and na == n:
                return kv  # common case: everything fresh, no copy
            out = np.zeros((nb,) + kv.shape[1:], kv.dtype)
            out[:na] = kv[ords]
            return out

        plan = plan_kv_handoff(
            self._kv_sharding if self._mesh is not None else self._repl
        )
        placed = [
            jax.tree.map(select, pair) for pair in blocks
        ]
        placed = [plan.place_tree(pair) for pair in placed]
        # Transfer accounting: one logical staging per placed leaf plus
        # the page-id vector (the same O(1)-per-event contract as
        # admission staging; steady ticks stay at zero), and the
        # plan's host->device byte count as a counter — per-shard
        # slices sum to the logical bytes, i.e. logical/tp per device.
        self._h2d_count += sum(
            len(jax.tree.leaves(pair)) for pair in placed
        )
        global_metrics().inc(
            "disagg.adopt_staged_bytes", float(plan.staged_bytes)
        )
        pages_dev = self._h2d(
            np.asarray(pages + [0] * (nb - na), np.int32)
        )
        self._variants.setdefault("continuous.adopt_pages", set()).add(nb)
        self._caches = self._adopt_pages(
            self._caches, pages_dev, placed, epoch=self._mesh_epoch
        )
        return na

    def _sp_admit(self, req: "_Request") -> None:
        """Sequence-parallel prefill of one long admission: run the
        sp-sharded whole-span program (``parallel/sp_prefill``) and
        land its page-major blocks through :meth:`adopt_prefill_pages`
        — the disaggregated-handoff landing path, loopbacked in
        process — so the admission below then prefix-hits every full
        page and pays only the suffix pass. Failures degrade to the
        ordinary (chunked) prefill: sp is an optimization, never a
        correctness gate."""
        s0 = req.prompt.shape[0]
        m = self._sp.covers(s0)
        if m < 1:
            return
        if self.prefix_cached(req.prompt) >= m:
            return  # hierarchy-resident: nothing to compute
        eo = self._eobs
        eo_on = eo.enabled
        t_ph = eo.now() if eo_on else 0.0
        tracer = global_tracer()
        t0 = tracer.now() if tracer.enabled else 0.0
        try:
            n, blocks = self._sp.prefill(req.prompt)
            adopted = self.adopt_prefill_pages(
                req.prompt, blocks, self._page,
                self._kv_dtype if self._kv_quant else False,
            )
        except Exception:  # noqa: BLE001 — degrade, never wedge
            log.exception(
                "sp prefill failed for request %d; admission falls "
                "back to the chunked path", req.req_id,
            )
            global_flight_recorder().record(
                "sp_prefill", request=req.req_id, pages=0,
                sp=self._sp.sp, ok=False,
            )
            self._sp_failures += 1
            if self._sp_failures >= 3:
                # Deterministic failure (a dead ring-only device, a
                # broken placement): stop paying a doomed dispatch per
                # long admission — retire the ring until a recovery
                # rebuilds it.
                log.warning(
                    "sp prefill disabled after %d consecutive "
                    "failures", self._sp_failures,
                )
                self._sp.close()
                self._sp = None
                global_metrics().set_gauge("prefill.sp_width", 1.0)
            return
        self._sp_failures = 0
        with self._cv:
            self._sp_prefills += 1
        # The sp tier computed n full pages of prompt positions — the
        # same prefill-work accounting as an in-tick chunk pass.
        self._count_prefill(n * self._page)
        if tracer.enabled:
            tracer.add_span(
                "batcher.sp_prefill",
                start=t0,
                end=tracer.now(),
                request=req.req_id,
                pages=n,
                adopted=adopted,
                sp=self._sp.sp,
            )
        if eo_on:
            # span=False: batcher.sp_prefill above is the tracer row.
            eo.phase("sp_prefill", t_ph, span=False)
        global_flight_recorder().record(
            "sp_prefill",
            request=req.req_id,
            pages=n,
            adopted=adopted,
            sp=self._sp.sp,
        )

    # -- hierarchical KV cache tier (host-DRAM spill under the Pager) ------

    def _fetch_page_host(self, page: int) -> list:
        """Host copy of one pool page's K/V across every block — the
        spill-side D2H. Per-shard slice fetches assembled on the host
        (``parallel.sharding.fetch_head_shards``): under tp each
        device ships only its resident heads, mirroring the readmit
        side's ``KVHandoffPlan`` per-shard placement — never a
        device-side gather. Pools are functional arrays, so the fetch
        reads the page's last-written bytes even when the allocator
        is about to hand the page to a new owner."""
        idx = int(page)
        return [
            jax.tree.map(lambda pool: fetch_head_shards(pool, idx), pair)
            for pair in self._caches
        ]

    def _spill_page(self, page: int, key: bytes) -> bool:
        """Capture one rc=0 page into the host tier (budget already
        checked by the caller). Idempotent for keys the tier holds."""
        raw, enc = self._tier.put(key, self._fetch_page_host(page))
        if raw == 0 and enc == 0:
            return False  # already host-resident: no new books
        self._tier_spilled += 1
        reg = global_metrics()
        reg.inc("cache_tier.spilled_total")
        reg.inc("cache_tier.codec_bytes_saved_total", float(raw - enc))
        global_flight_recorder().record(
            "kv_spill", page=int(page), bytes=int(enc), raw_bytes=int(raw)
        )
        return True

    def _on_page_evict(self, page: int, key: bytes) -> None:
        """``Pager.evict_hook``: a registered rc=0 page is leaving the
        pool (its radix node dies with it — the pager already dropped
        the key from the radix index). Every eviction records the
        ``radix_evict`` flight event; with a host tier installed,
        host-backed keys then evict for free while un-backed ones
        spill inside the per-tick budget, or count the content as
        dropped — the watermark pre-spill in :meth:`_tier_step` exists
        to make this branch rare."""
        global_flight_recorder().record(
            "radix_evict",
            page=int(page),
            prefix_tokens=len(key) // 4,  # int32 token-block key
        )
        tier = self._tier
        if tier is None:
            return
        if tier.contains(key):
            return  # content already host-resident: eviction is free
        if self._spill_budget <= 0:
            self._tier_dropped += 1
            global_metrics().inc("cache_tier.dropped_total")
            return
        self._spill_budget -= 1
        self._spill_page(page, key)

    def _tier_step(self) -> None:
        """Proactive watermark spill, run once per tick BEFORE
        admission: when the prefix LRU holds at least
        ``spill_watermark`` of the allocatable pool, back the coldest
        un-backed LRU pages (they evict first) down to the low
        watermark — budget-capped, so the decode tick's tier work is
        bounded whatever the backlog. Only rc=0 LRU pages are ever
        scanned: live slots' pages cannot spill, so lossy cold codecs
        can never touch state a decode still reads from HBM."""
        cfg = self._tier_cfg
        self._spill_budget = cfg.spill_pages_per_tick
        self._readmit_budget = cfg.readmit_pages_per_tick
        # Bridge the tier's own cold-overflow drops (demotions past
        # the host capacity with no disk dir) to the registry counter.
        over = self._tier.dropped - self._tier_drop_seen
        if over:
            global_metrics().inc("cache_tier.dropped_total", float(over))
            self._tier_drop_seen = self._tier.dropped
        alloc = self._pager.num_allocatable
        cached = self._pager.cached_pages()
        if len(cached) < cfg.spill_watermark * alloc:
            return
        # Back the coldest `need` pages: everything that would have to
        # evict to bring the LRU down to the low watermark. (Guard the
        # slice: a negative `need` must mean "nothing", not a slice
        # off the wrong end of the LRU.)
        need = len(cached) - int(cfg.spill_low_watermark * alloc)
        if need <= 0:
            return
        for page, key in cached[:need]:
            if self._spill_budget <= 0:
                break
            if self._tier.contains(key):
                continue
            self._spill_budget -= 1
            self._spill_page(page, key)

    def _maybe_readmit(self, req: "_Request") -> int:
        """The admission probe's host-tier consult: before the prefix
        probe declares a miss, readmit the request's longest run of
        host-resident prefix pages back into the pool — decoded from
        the tier, landed through the SAME ``Pager.adopt_cached`` +
        :meth:`_adopt_pages` path as a disaggregated handoff
        (epoch-carrying, tp-sharded per-shard placement), so the probe
        then shares them as ordinary prefix hits. Budgeted per tick;
        pool pressure readmits nothing (recompute is always correct).
        Returns the number of pages readmitted."""
        tier = self._tier
        if tier is None or self._readmit_budget <= 0:
            return 0
        P = self._page
        s0 = req.prompt.shape[0]
        keys: list[bytes] = []
        blocks_list: list[list] = []
        for j in range((s0 - 1) // P):
            key = Pager.prefix_key(req.prompt, (j + 1) * P)
            if self._pager.resident(key):
                continue  # probe will share it without our help
            if len(keys) >= self._readmit_budget:
                break
            blocks = tier.get(key)
            if blocks is None:
                break  # true miss — later pages can't extend the run
            keys.append(key)
            blocks_list.append(blocks)
        if not keys:
            return 0
        adopted = self._pager.adopt_cached(keys)
        if not adopted:
            return 0  # pool pressure — admission recomputes instead
        ords = [i for i, _ in adopted]
        pages = [p for _, p in adopted]
        na = len(ords)
        nb = 1
        while nb < na:
            nb *= 2

        def stack(*leaves):
            out = np.zeros((nb,) + leaves[0].shape, leaves[0].dtype)
            for t, j in enumerate(ords):
                out[t] = leaves[j]
            return out

        placed = [
            jax.tree.map(stack, *[bl[b] for bl in blocks_list])
            for b in range(len(self._blocks))
        ]
        plan = plan_kv_handoff(
            self._kv_sharding if self._mesh is not None else self._repl
        )
        placed = [plan.place_tree(pair) for pair in placed]
        self._h2d_count += sum(
            len(jax.tree.leaves(pair)) for pair in placed
        )
        pages_dev = self._h2d(
            np.asarray(pages + [0] * (nb - na), np.int32)
        )
        self._variants.setdefault("continuous.adopt_pages", set()).add(nb)
        self._caches = self._adopt_pages(
            self._caches, pages_dev, placed, epoch=self._mesh_epoch
        )
        self._readmit_budget -= na
        self._tier_readmitted += na
        reg = global_metrics()
        reg.inc("cache_tier.readmitted_total", float(na))
        global_flight_recorder().record(
            "kv_readmit",
            request=req.req_id,
            pages=na,
            staged_bytes=int(plan.staged_bytes),
        )
        return na

    def prefix_cached(self, prompt) -> int:
        """Leading FULL pages of ``prompt`` servable from the cache
        HIERARCHY without recompute: the longest run of prefix keys
        that are HBM-resident or (when a cache tier is configured)
        host-spilled. Read-only — no shares taken, no readmits, no
        probe accounting moved; the number a prefix-affinity router
        or capacity audit wants (``benchmarks/load/tier_smoke``
        measures the host tier's servable-prefix multiplier with
        it)."""
        if not self._paged:
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = 0
        for j in range((prompt.shape[0] - 1) // self._page):
            key = Pager.prefix_key(prompt, (j + 1) * self._page)
            if self._pager.resident(key) or (
                self._tier is not None and self._tier.contains(key)
            ):
                n += 1
            else:
                break
        return n

    def _insert_paged(self, caches, pages, kvs):
        """Scatter a prefilled request's per-block K/V into its pages
        (``runtime/paged.insert_prefill_pages`` per pool). tree.map
        reaches the (values, scales) members of quantized pools and the
        plain arrays of native ones alike — the scale plane scatters by
        the same page list, so the pages' scales always travel with
        their int8 values (prefix sharing included)."""
        return [
            jax.tree.map(
                lambda pool, kv: insert_prefill_pages(pool, pages, kv),
                c_pair,
                n_pair,
            )
            for c_pair, n_pair in zip(caches, kvs)
        ]

    def _first_pick(self, h_last, variables, keys, temp, top_k, top_p,
                    greedy, truncate, nucleus):
        """Shared first-token sampling tail of both prefill flavors —
        the exact knob semantics of ``submit`` (one body, cannot
        fork)."""
        logits = self._head.apply(variables["head"], h_last)[:, 0]
        pick_greedy = jnp.argmax(logits, axis=-1)
        lg = logits / jnp.maximum(temp, 1e-6)
        if truncate:
            lg = self._truncate_rows(lg, top_k[None])
        if nucleus:
            lg = nucleus_filter(lg, top_p[None])
        sampled = jax.vmap(jax.random.categorical)(keys, lg)
        first = jnp.where(greedy, pick_greedy, sampled)
        return first, chosen_logprob(logits, first)

    def _prefill_fn(self, bucket: int):
        """Jitted prefill for one prompt bucket: full causal forward over
        (1, bucket), logits at the TRUE last position, per-block K/V to
        insert into a slot."""
        if bucket in self._prefill_cache:
            return self._prefill_cache[bucket]

        # Fused scalar staging: the per-request sampling knobs ride as
        # ONE int vector + ONE float vector (ints = [true_len, top_k],
        # floats = [temp, top_p]; greedy derives from temp == 0, the
        # submit() normalization) instead of a jnp.asarray per field.
        # ``ids`` is NOT donated: int32 staging can never alias the f32
        # outputs, so donating it is only an XLA warning per compile.
        @partial(jax.jit, static_argnames=("truncate", "nucleus"))
        def prefill(variables, ids, ints, floats, keys, *, truncate,
                    nucleus):
            h = self._embed.apply(variables["embed"], ids)
            kvs = []
            for name, block in zip(self.lm.block_names, self._blocks):
                h, ck, cv = block.apply(
                    variables[name], h, bucket, None,
                    self._kv_dtype if self._kv_quant else False,
                    method="prefill",
                )
                kvs.append((ck, cv))
            h_last = lax.dynamic_index_in_dim(h, ints[0] - 1, 1)
            first, first_lp = self._first_pick(
                h_last, variables, keys, floats[0], ints[1], floats[1],
                floats[0] == 0.0, truncate, nucleus,
            )
            return first, first_lp, self._shard_kv(kvs)

        self._prefill_cache[bucket] = prefill
        return prefill

    def _prefill_suffix_fn(self, sbucket: int, n_strip: int,
                           sample: bool = True):
        """Jitted INCREMENTAL prefill pass over a paged window: positions
        [pos0, pos0 + true_len) run the forward against everything
        already cached before them, IN PLACE — each block writes the
        chunk's K/V into its own pages (one O(chunk) scatter) and
        attends the window page by page
        (``models.prefill_chunk_paged`` -> ``paged_chunk_attention``,
        per-row causal mask). No gathered strip, no scatter-back: pass
        traffic is O(window) reads + O(chunk) writes.

        Two callers, one body: the prefix-cache hit (single pass,
        ``sample=True``) and chunked prefill (every pass but the last
        uses ``sample=False`` and returns a dummy token). Specializes
        per (chunk bucket, window pages, sample) — chunked callers pad
        the page list to powers of two, so a long prompt compiles log2
        variants."""
        key = ("suffix", sbucket, n_strip, sample)
        if key in self._prefill_cache:
            return self._prefill_cache[key]

        # Fused scalar staging (same scheme as _prefill_fn): ints =
        # [pos0, true_len, top_k], floats = [temp, top_p]. The caches
        # are donated (they alias in place); ids staging is not (int32
        # can't alias the outputs — donation would only warn).
        @partial(jax.jit, static_argnames=("truncate", "nucleus"),
                 donate_argnums=(1,))
        def prefill(variables, caches, pages, ids, ints, floats, keys,
                    *, truncate, nucleus):
            caches = self._shard_kv(caches)
            pos0 = ints[0]
            pos_ids = pos0 + jnp.arange(sbucket)[None]
            h = self._embed.apply(
                variables["embed"], ids, pos_ids, method="embed_positions"
            )
            new_caches = []
            for name, block, (kp, vp) in zip(
                self.lm.block_names, self._blocks, caches
            ):
                h, kp, vp = block.apply(
                    variables[name], h, kp, vp, pages, pos0,
                    method="prefill_chunk_paged",
                )
                new_caches.append((kp, vp))
            new_caches = self._shard_kv(new_caches)
            if not sample:  # mid-prefill pass: no token yet
                return (jnp.zeros((1,), jnp.int32),
                        jnp.zeros((1,), jnp.float32), new_caches)
            h_last = lax.dynamic_index_in_dim(h, ints[1] - 1, 1)
            first, first_lp = self._first_pick(
                h_last, variables, keys, floats[0], ints[2], floats[1],
                floats[0] == 0.0, truncate, nucleus,
            )
            return first, first_lp, new_caches

        self._prefill_cache[key] = prefill
        return prefill

    def _draft_prefill_fn(self, bucket: int):
        """Jitted DRAFT prefill for one prompt bucket: full causal
        forward over (1, bucket), per-block K/V to insert into the
        draft's dense slot strips. No sampling tail — the draft never
        emits; it only seeds its cache for the per-tick draft scan.
        int8 draft weights (``draft_weight_dtype``) dequantize inside
        the jit, mirroring ``draft_chunk``."""
        key = ("draft", bucket)
        if key in self._prefill_cache:
            return self._prefill_cache[key]

        @jax.jit
        def dprefill(variables, ids):
            variables = dequantize_params(variables)
            h = self._draft_embed.apply(variables["embed"], ids)
            kvs = []
            for name, block in zip(
                self._draft_lm.block_names, self._draft_blocks
            ):
                h, ck, cv = block.apply(
                    variables[name], h, bucket, None, False,
                    method="prefill",
                )
                kvs.append((ck, cv))
            return kvs

        self._prefill_cache[key] = dprefill
        return dprefill

    def _admit_draft(self, slot_idx: int, req: _Request) -> None:
        """Prefill the DRAFT model's whole prompt into its dense slot
        row. Always the full prompt: the draft has no prefix cache and
        no chunked prefill — it is small by construction, so one
        bucketed pass per admission is the entire cost of keeping its
        cache in lockstep with the target's committed stream."""
        s0 = req.prompt.shape[0]
        bucket = next(b for b in self.prompt_buckets if b >= s0)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :s0] = req.prompt
        kvs = self._draft_prefill_fn(bucket)(
            self._draft_variables, self._h2d(ids)
        )
        # Draft K/V shapes differ from the target's, so a draft bucket
        # is its own _insert variant even at the same prompt length.
        self._variants.setdefault("continuous.insert", set()).add(
            ("draft", bucket)
        )
        self._draft_caches = self._insert(
            self._draft_caches, self._h2d(np.int32(slot_idx)), kvs
        )

    @partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _insert(self, caches, slot, kvs):
        """Write a prefilled request's K/V into slot row ``slot``
        (tree.map reaches the (values, scales) leaves of int8 caches and
        the plain arrays of native ones alike)."""
        return [
            jax.tree.map(
                lambda c, n: lax.dynamic_update_slice(
                    c, n.astype(c.dtype), (slot, 0, 0, 0)
                ),
                c_pair,
                n_pair,
            )
            for c_pair, n_pair in zip(caches, kvs)
        ]

    # -- request lifecycle -------------------------------------------------

    def validate_request(
        self,
        prompt,
        steps: int,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        rng=None,
        stop: list | None = None,
        slo: SLOSpec | None = None,
    ) -> tuple[np.ndarray, int | None]:
        """Raise exactly the errors :meth:`submit` would for these
        arguments, without queueing anything — THE one validation
        body. The disaggregated submit path (``runtime/disagg``) calls
        it up front so a bad request fails synchronously like a
        collocated one, instead of minutes later at handoff landing —
        and a future rule added here automatically covers both paths.
        Returns the normalized ``(int32, 1-D)`` prompt and the
        effective ``top_k`` (request's, or the batcher default)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        s0 = prompt.shape[0]
        if s0 < 1:
            raise ValueError("empty prompt")
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        if s0 + steps > self.lm.max_len:
            raise ValueError(
                f"prompt {s0} + steps {steps} exceeds max_len "
                f"{self.lm.max_len}"
            )
        if s0 > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt {s0} exceeds largest bucket "
                f"{self.prompt_buckets[-1]}"
            )
        if self._paged:
            bucket = next(b for b in self.prompt_buckets if b >= s0)
            need = -(
                -max(bucket, s0 + steps + self._spec_k + self._spec_w)
                // self._page
            )
            if need > self._pool_pages - 1:  # page 0 is trash
                # Would queue forever: the pool can never cover it.
                raise ValueError(
                    f"request needs {need} pages but the pool holds "
                    f"{self._pool_pages - 1} allocatable"
                )
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature > 0 requires an rng key")
        top_k_eff = top_k if top_k is not None else self.top_k
        if top_k_eff is not None and not (1 <= top_k_eff <= self.lm.vocab):
            raise ValueError(
                f"top_k {top_k_eff} outside [1, {self.lm.vocab}]"
            )
        if top_p is not None and not (0.0 < top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if stop is not None and any(len(seq) == 0 for seq in stop):
            raise ValueError("stop sequences must be non-empty")
        if slo is not None and not isinstance(slo, SLOSpec):
            raise TypeError(
                f"slo must be a config.SLOSpec, got {type(slo).__name__}"
            )
        return prompt, top_k_eff

    def submit(
        self,
        prompt,
        steps: int,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
        rng: jax.Array | None = None,
        stop: list | None = None,
        on_token: Callable[[int, int, int], None] | None = None,
        slo: SLOSpec | None = None,
        t_submit: float | None = None,
        _fanout: int = -1,
    ) -> int:
        """Queue one request; returns its id. ``slo`` (optional
        ``config.SLOSpec``) attaches a latency budget: TTFT is judged
        once at the first emitted token, ITL at every later commit,
        feeding the ``slo.*`` attainment metrics, the per-tenant
        met/missed counters and ``continuous.goodput_tokens_s``
        (evaluation rides the ``obs_timeline`` gate — host arithmetic
        on stamps already taken, nothing device-side).
        ``on_token`` (optional
        ``callable(req_id, token, index)``) streams each committed
        token as it lands — invoked on the TICKING thread at commit
        time (chunk granularity: up to ``chunk`` callbacks per tick),
        so keep it cheap and thread-safe. Exceptions poison the tick:
        synchronous drivers see them directly; under :meth:`start` the
        server stops and every ``result()`` waiter re-raises the
        callback's exception (never a silent timeout).
        ``stop`` is a list of
        token-id sequences: the stream ends at the first emitted
        occurrence of any of them, stop tokens included — host-side
        truncation, so the emitted prefix still equals solo
        ``generate()``. ``prompt`` is a 1-D token
        id sequence; ``top_k`` overrides the batcher default for this
        request. The sampling-key schedule matches ``generate`` for a
        solo batch, so outputs are reproducible against it.
        ``t_submit`` (perf-counter clock) overrides the lifecycle
        anchor for requests that entered the SERVING SYSTEM earlier
        than this call — the disaggregated submit path
        (``runtime/disagg``) passes the server-level arrival stamp so
        queue-wait/TTFT/SLO verdicts stay end-to-end honest instead of
        starting the clock after the prefill tier already ran."""
        prompt, top_k_eff = self.validate_request(
            prompt, steps, temperature=temperature, top_k=top_k,
            top_p=top_p, rng=rng, stop=stop, slo=slo,
        )
        s0 = prompt.shape[0]
        do_sample = temperature > 0.0
        if rng is None:
            rng = jax.random.PRNGKey(0)
        if do_sample:
            # generate()'s exact schedule: split -> key0 + per-step
            # keys, each folded with the row index (0 — solo
            # semantics). One vmapped dispatch + one host fetch, not
            # O(steps) of them — this runs on the serving control path.
            rng_next, key0 = jax.random.split(rng)
            if steps > 1:
                step_keys = jnp.concatenate(
                    [key0[None], jax.random.split(rng_next, steps - 1)]
                )
            else:
                step_keys = key0[None]
            folded = np.asarray(
                jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                    step_keys, 0
                )
            )
        else:
            # Greedy requests never read a sampling key (the step's
            # sampled pick is discarded by ``jnp.where(greedy, ...)``,
            # and the first-token tail does the same), so skip the
            # schedule build entirely: one zero key row stages as the
            # whole schedule (nkeys=1; the cursor clips to it). This
            # matters beyond tidiness: ``split(rng, steps-1)`` compiles
            # one variant PER DISTINCT steps VALUE, so a greedy load
            # with heavy-tailed output lengths (benchmarks/load) was
            # paying an XLA compile on the submit path for every new
            # length — a multi-second stall of the tick loop that
            # measured as fake ITL.
            folded = np.zeros((1, 2), np.uint32)
        with self._cv:
            req_id = self._next_id
            self._next_id += 1
        req = _Request(
            req_id=req_id,
            prompt=prompt,
            steps=steps,
            temperature=float(temperature) if do_sample else 0.0,
            # Greedy requests discard the sampled pick entirely —
            # normalize their knobs to the identity values so they never
            # force the truncate/nucleus sorts (or variant recompiles)
            # onto a tick.
            top_k=(
                top_k_eff
                if do_sample and top_k_eff is not None
                else self.lm.vocab
            ),
            top_p=top_p if do_sample and top_p is not None else 1.0,
            eos_id=eos_id,
            folded_keys=folded,
            stop=tuple(
                tuple(int(t) for t in seq) for seq in (stop or ())
            ),
            on_token=on_token,
            t_submit=(
                t_submit if t_submit is not None else time.perf_counter()
            ),
            slo=slo,
            fanout_group=_fanout,
        )
        if self._capacity is not None:
            # Submit-time TTFT forecast (client thread): the radix
            # probe is a read-only dict walk (same thread stance as
            # prefix_cached), and the forecaster feeds are per-field
            # scalar reads. Stored on the request; its realized TTFT
            # closes the calibration loop at first-token commit.
            hit_tokens = 0
            if self._paged:
                hit_tokens = self._pager.radix_probe(prompt)[1]
            req.ttft_forecast_s = self._capacity.forecast_ttft(
                s0, hit_tokens
            )

        def _reject(e: QueueFullError, journaled: bool) -> None:
            self._record_rejection(
                request_tenant(req), request_priority(req), e,
                request=req_id,
            )
            if journaled:
                # Done-mark so a crash recovery cannot resurrect a
                # request the client was told was rejected.
                self._journal_done(req_id)

        # Shed a flood BEFORE paying journal I/O: under sustained
        # overload (the regime rejection exists for) every rejected
        # submit would otherwise serialize its full payload record
        # plus a done mark. The bounded append below stays the
        # authoritative check — this is the same pre-check/backstop
        # split as admission_check's.
        try:
            with self._cv:
                self._queue.check(
                    request_tenant(req), request_priority(req)
                )
        except QueueFullError as e:
            _reject(e, journaled=False)
            raise
        if self._journal is not None:
            # Payload + knobs BEFORE the request becomes reachable: a
            # replay (elastic recovery) or a crash-recovering process
            # reconstructs the request from this record alone. The key
            # schedule is journaled too, so sampled replays re-emit the
            # identical stream.
            try:
                self._journal.record_submit(
                    req_id,
                    prompt,
                    meta={
                        "steps": steps,
                        "temperature": req.temperature,
                        "top_k": req.top_k,
                        "top_p": req.top_p,
                        "eos_id": eos_id,
                        "stop": [list(s) for s in req.stop],
                        "folded_keys": req.folded_keys.tolist(),
                    },
                )
            except Exception as e:  # noqa: BLE001 — serve anyway, loudly
                log.warning(
                    "journal submit failed for %d: %r", req_id, e
                )
        try:
            with self._cv:
                self._queue.append(req)  # bounded: may raise
                self._cv.notify_all()  # wake the server thread, if any
        except QueueFullError as e:
            # Synchronous rejection IS the admission-control contract:
            # the caller learns now — no id ever waits on result().
            _reject(e, journaled=True)
            raise
        global_metrics().inc("scheduler.admitted_total")
        return req.req_id

    def submit_fanout(
        self,
        prompt,
        n: int,
        steps: int,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
        rng: jax.Array | None = None,
        stop: list | None = None,
        on_token: Callable[[int, int, int], None] | None = None,
        slo: SLOSpec | None = None,
    ) -> list[int]:
        """Queue ``n`` continuations of ONE prompt as a copy-on-write
        fan-out group; returns their request ids in submission order.
        Every sibling shares every common prompt page through the
        prefix probe (rc bumps, no copies), and the group keeps the
        first admitted sibling's last prompt page rc-claimed so later
        siblings can FORK it — one device page copy, no suffix forward
        — even after that sibling retired: fan-out of width N costs
        ~1x the shared prefix's pages plus each sibling's private
        decode tail. Greedy (``temperature == 0``) siblings are
        bit-identical to ``n`` independent :meth:`submit` calls of the
        same prompt. ``temperature > 0`` requires ``rng``; each
        sibling samples under its own split of it (parallel sampling
        semantics — the streams diverge by design, so sampled
        siblings run the ordinary suffix pass for their own
        first-token logits and share only the full prefix pages).
        Dense layouts and ``n == 1`` degrade to plain serial submits.
        On a mid-group :class:`QueueFullError` the already-queued
        siblings STAY queued (their ids are lost with the raise — a
        caller that must know them should submit serially); the group
        shrinks to the survivors."""
        if n < 1:
            raise ValueError(f"fan-out width must be >= 1, got {n}")
        sib_rngs: list = [None] * n
        if temperature > 0.0:
            if rng is None:
                raise ValueError("temperature > 0 requires an rng key")
            sib_rngs = list(jax.random.split(rng, n))
        elif rng is not None:
            sib_rngs = [rng] * n
        gid = -1
        if self._paged and n > 1:
            with self._cv:
                gid = self._fanout_next
                self._fanout_next += 1
                self._fanout_groups[gid] = _FanoutGroup(
                    remaining=n, greedy=temperature == 0.0
                )
        ids: list[int] = []
        try:
            for j in range(n):
                ids.append(
                    self.submit(
                        prompt,
                        steps,
                        temperature=temperature,
                        top_k=top_k,
                        top_p=top_p,
                        eos_id=eos_id,
                        rng=sib_rngs[j],
                        stop=stop,
                        on_token=on_token,
                        slo=slo,
                        _fanout=gid,
                    )
                )
        except Exception:
            # Shrink the group by the never-submitted siblings; a
            # group emptied here dies on the CLIENT thread, so any
            # claimed page parks for the ticking thread to release
            # (the admitted-out death inside _admit releases directly).
            if gid >= 0:
                with self._cv:
                    fg = self._fanout_groups.get(gid)
                    if fg is not None:
                        fg.remaining -= n - len(ids)
                        if fg.remaining <= 0:
                            self._fanout_kill_locked(gid, fg)
            raise
        return ids

    def _fanout_kill_locked(
        self, gid: int, fg: _FanoutGroup, direct: bool = False
    ) -> None:
        """Drop an emptied fan-out group (``_cv`` held). The claimed
        page — if any — is released immediately when the caller IS the
        ticking thread (``direct=True``: the admission path, so a
        group that drains with its last sibling leaves no claim
        dangling past the tick); client-thread deaths (queued-sibling
        cancel, a failed submit_fanout) park it in ``_fanout_release``
        instead — only the ticking thread may move pager rc — and the
        next admission sweep drains the list."""
        if fg.page is not None:
            if direct:
                self._pager.release_claim(fg.page)
            else:
                self._fanout_release.append(fg.page)
            fg.page = None
        self._fanout_groups.pop(gid, None)

    def _fanout_dec_locked(self, req: "_Request") -> None:
        """Consume ``req``'s fan-out membership (``_cv`` held): clear
        the request's group id and shrink the group — admission and
        queued-cancel both land here, so a pool-pressure re-queue
        (group id already cleared) can never double-decrement."""
        gid = req.fanout_group
        if gid < 0:
            return
        req.fanout_group = -1
        fg = self._fanout_groups.get(gid)
        if fg is None:
            return
        fg.remaining -= 1
        if fg.remaining <= 0:
            self._fanout_kill_locked(gid, fg)

    def cancel(self, req_id: int) -> bool:
        """Cancel a request: queued -> dropped with an empty result;
        live (in a slot, or mid-admission on the ticking thread) ->
        retired at the next commit boundary with the partial stream as
        the result. Returns False only for ids never issued or already
        finished; True means "cancel accepted" — best-effort if the
        request finishes concurrently (the stream may complete). The
        whole decision runs under the handoff lock so it cannot race
        admission (queue-pop -> slot assignment happens on the ticking
        thread between lock holds); markers are consumed by _commit /
        the tick boundary / _finish, never leaked."""
        with self._cv:
            if req_id in self._done or not 0 <= req_id < self._next_id:
                return False
            req = self._queue.remove_id(req_id)
            if req is not None:
                # A marker from an earlier cancel of this id (e.g.
                # while it was mid-admission before being re-queued
                # on pool pressure) must not outlive it.
                self._cancelled.discard(req_id)
                # A cancelled fan-out sibling leaves its group; the
                # last leaver kills the group (claimed page released
                # on the ticking thread).
                self._fanout_dec_locked(req)
                # A freshly queued request delivered nothing, but a
                # recovery-replayed one waiting for re-admission
                # already streamed its first life's tokens: result()
                # returns that snapshot, matching what a live cancel
                # after re-admission would return.
                if req.delivered_tokens is not None:
                    self._done[req_id] = req.delivered_tokens
                    self._done_lps[req_id] = req.delivered_lps
                else:
                    self._done[req_id] = np.zeros((0,), np.int32)
                    self._done_lps[req_id] = np.zeros((0,), np.float32)
                self._cv.notify_all()
                global_flight_recorder().record(
                    "cancel", request=req_id, state="queued"
                )
            else:
                # Live = bound to a slot, or mid-admission on the
                # ticking thread (popped, not yet slot-bound). Anything
                # else with a valid id already finished and was claimed.
                live = req_id == self._admitting or any(
                    s.req is not None and s.req.req_id == req_id
                    for s in self.slots
                )
                if not live:
                    return False
                # Mark it; the ticking thread consumes the marker at
                # its next boundary.
                self._cancelled.add(req_id)
                global_flight_recorder().record(
                    "cancel", request=req_id, state="live"
                )
                return True
        # Queued cancel: the done mark's disk write (periodic fsync,
        # possible WAL compaction) must not run under the handoff lock
        # — _finish and _drop_slot keep the same discipline.
        self._journal_done(req_id)
        return True

    # -- traffic control (docs/SERVING.md "Traffic control") ---------------

    def _record_rejection(
        self,
        tenant: str,
        prio: int,
        err: Exception,
        request: int | None = None,
    ) -> None:
        """THE one rejection-bookkeeping body (books + counter +
        ``request_rejected`` flight event) — submit's bounded append,
        its pre-journal check and :meth:`admission_check` all go
        through here, so a new event field cannot silently diverge
        across the three rejection sites."""
        with self._cv:
            self._rejected += 1
        global_metrics().inc("scheduler.rejected_total")
        ev = {
            "tenant": tenant,
            "priority": prio,
            "reason": str(err)[:200],
        }
        if request is not None:
            ev["request"] = request
        global_flight_recorder().record("request_rejected", **ev)

    def admission_check(
        self, slo: SLOSpec | None = None, request: int | None = None
    ) -> None:
        """Raise :class:`~adapt_tpu.runtime.scheduler.QueueFullError`
        iff a :meth:`submit` carrying ``slo`` would be rejected by
        admission control right now, recording the rejection exactly
        like submit does. The disaggregated path
        (``runtime/disagg.DisaggServer``) calls this BEFORE routing a
        request into the prefill tier, so a doomed request fails
        synchronously instead of after its whole prefill ran (the
        landing-time rejection still backs up the race)."""
        tenant = slo.tenant if slo is not None else "default"
        prio = slo.priority if slo is not None else 0
        try:
            with self._cv:
                self._queue.check(tenant, prio)
        except QueueFullError as e:
            self._record_rejection(tenant, prio, e, request=request)
            raise

    def _maybe_preempt(self) -> None:
        """Decode-slot preemption (ticking thread, start of admission):
        when the queue's top priority class has a request whose TTFT
        budget has burned past ``preempt_ttft_fraction`` waiting and
        neither a slot nor (paged) the pages it needs can free
        otherwise, preempt the LOWEST-priority active decode slot
        through the replay path (:meth:`_replay_slot` — prompt pages
        into the prefix LRU, journal-requeue, exactly-once
        re-delivery). At most one victim per tick: admission runs
        right after, so the freed slot serves the waiting request
        before a second preemption could be justified."""
        sched = self._sched
        if sched is None or not sched.preempt:
            return
        with self._cv:
            if len(self._queue) == 0:
                return
            cand = self._queue.preempt_candidate()
        if cand is None:
            return
        req, prio = cand
        if any(s.req is None for s in self.slots):
            # A free slot exists — ordinary admission serves the head,
            # UNLESS it is PAGE-starved: paged admission is
            # all-or-nothing, and a head whose worst-case reservation
            # the pool cannot cover even after evicting every cold
            # page (can_alloc counts the LRU) waits at the free slot
            # forever while lower-priority decodes hold the pages.
            # Preempting one releases its pages into the evictable
            # set. The need bound is conservative — prefix sharing
            # only shrinks it, so can_alloc(need) true means ordinary
            # admission will succeed.
            if not self._paged:
                return
            s0 = req.prompt.shape[0]
            bucket = next(b for b in self.prompt_buckets if b >= s0)
            need = -(
                -max(bucket, s0 + req.steps + self._spec_k + self._spec_w)
                // self._page
            )
            if self._pager.can_alloc(need):
                return
        waited = time.perf_counter() - (req.t_requeued or req.t_submit)
        if waited < sched.preempt_ttft_fraction * req.slo.ttft_budget_s:
            return
        with self._cv:
            # Re-validate: a client cancel() since the candidate
            # snapshot removed it from the queue — preempting a live
            # decode (discarded tokens, full replay) to serve a
            # request that no longer exists would be pure waste.
            if not any(
                r.req_id == req.req_id for r in self._queue
            ):
                return
        victims = [
            s for s in self.slots
            if s.req is not None
            and s.pf_done < 0  # decode slots only; mid-prefill slots
            # finish their admission (they have emitted nothing yet)
            and request_priority(s.req) < prio
        ]
        if not victims:
            return  # never preempt an equal-or-higher class
        # Lowest class first; ties broken by FEWEST emitted tokens —
        # the cheapest regeneration when the victim re-admits.
        victim = min(
            victims,
            key=lambda s: (request_priority(s.req), len(s.tokens)),
        )
        vid = victim.req.req_id
        vprio = request_priority(victim.req)
        delivered = len(victim.tokens)
        self._replay_slot(
            victim, event="preempted", extra={"for_request": req.req_id}
        )
        with self._cv:
            self._preempted += 1
        global_metrics().inc("scheduler.preempted_total")
        log.info(
            "preempted request %d (priority %d, %d tokens delivered) "
            "for request %d (priority %d, waited %.3fs of %.3fs TTFT)",
            vid, vprio, delivered, req.req_id, prio, waited,
            req.slo.ttft_budget_s,
        )

    def set_draft_k(self, k: int) -> None:
        """Shrink (or restore) the EFFECTIVE speculation depth at
        runtime — the degradation ladder's cheapest rung
        (``runtime/scheduler.DegradationController``). Cache slack,
        page reservations and the idle sentinel all sized for the
        CONFIGURED ``draft_k`` at construction, so any ``k`` in
        ``[1, draft_k]`` keeps every write inside reserved space; the
        next tick's draft scan and verify chunk simply narrow to
        ``k + 1`` rows. Each DISTINCT ``k`` lowers one fresh variant
        of the two spec programs (granted as an expected-compile
        allowance, like recovery's re-lowers — not a phantom-variant
        alarm); toggling back to a seen value reuses its cached
        executables."""
        if self._spec is None:
            raise ValueError(
                "set_draft_k requires speculative mode (draft_lm=)"
            )
        if not 1 <= k <= self._spec.draft_k:
            raise ValueError(
                f"draft_k must be in [1, {self._spec.draft_k}], got {k}"
            )
        if k == self._spec_k_eff:
            return
        if k not in self._spec_k_granted:
            # One fresh draft variant per distinct k, plus one verify
            # variant per sampling-flag combination already in service
            # at this depth (greedy-only traffic has exactly one;
            # sampled traffic adds its (sample, truncate, nucleus)
            # combos — narrowing must stay lossless for them too).
            combos = len({
                v[1:] for v in self._variants.get(
                    "continuous.spec_verify", set()
                )
            }) or 1
            self._sentinel.rearm("continuous.spec_verify", expect=combos)
            self._granted["continuous.spec_verify"] = (
                self._granted.get("continuous.spec_verify", 0) + combos
            )
            self._sentinel.rearm("speculative.draft_chunk", expect=1)
            self._granted["speculative.draft_chunk"] = (
                self._granted.get("speculative.draft_chunk", 0) + 1
            )
            self._spec_k_granted.add(k)
        self._spec_k_eff = k
        log.info("effective draft_k -> %d (configured %d)",
                 k, self._spec.draft_k)

    # -- elastic mesh recovery ---------------------------------------------

    def _on_device_event(self, event: str, key: str) -> None:
        """Membership watch callback (fires on the killer's / reaper's
        thread): a ``leave`` for a device of OUR current mesh is queued
        for the ticking thread to consume — detection is event-driven,
        recovery runs only where the compiled state is owned."""
        if event != "leave" or not key.startswith("device:"):
            return
        try:
            did = int(key.split(":", 1)[1])
        except ValueError:
            return
        with self._cv:
            if did not in self._mesh_device_ids or key in self._lost_pending:
                return
            self._lost_pending.append(key)
            self._cv.notify_all()  # wake an idle server thread
        global_flight_recorder().record(
            "device_lost", device=key, tp=self._tp
        )
        log.warning("mesh device lost: %s (tp=%d)", key, self._tp)

    def device_lost_pending(self) -> bool:
        """True when a mesh device loss awaits recovery (the next tick
        re-shards, or raises under ``auto_reshard=False``)."""
        with self._cv:
            return bool(self._lost_pending)

    def recover(self) -> dict:
        """Re-shard the batcher onto its surviving devices after a
        device loss — the elastic recovery path, end to end:

        1. **shrink the mesh** — new tp is the largest divisor of the
           old tp the survivors can host (divisors keep every
           head-range split aligned, so the model re-validates by
           construction — ``validate_tp`` + per-block
           ``check_head_parity`` run anyway, by name);
        2. **re-place weights** by the megatron rules on the shrunk
           mesh (the checkpoint tier owns weight durability — under
           the simulated kill the still-resident shards re-place
           directly; a real deployment re-streams from checkpoint);
        3. **migrate live state** via an explicit
           ``parallel.sharding.KVReshardPlan``: head-sharded KV
           (dense strips, paged (values, scales) pools) moves
           per-shard — device-to-device where the shard survives,
           host-staged for the lost shard's heads — and replicated
           state (sampling ``_dstate``, draft weights/caches) re-places
           from a surviving replica. Migrated requests continue
           **bit-identically**;
        4. **replay** requests whose state does not migrate
           (``policy="replay"``, or mid-chunked-prefill slots) from the
           journal — re-entering through the paged prefix cache when
           the prompt pages are still resident — to identical tokens;
        5. **re-arm** the compile sentinel for every program family:
           the re-lowered variants (new shardings) are expected
           compiles, not phantom-variant alarms.

        Runs on the ticking thread (``tick`` calls it under
        ``auto_reshard``); call it directly only with the batcher
        stopped or between synchronous ticks. Returns the recovery
        summary (also recorded as the ``mesh_reshard`` flight event).
        Raises :class:`DeviceLostError` when no recovery exists (all
        devices lost, or survivors below ``min_tp``)."""
        # Pipeline boundary (RuntimeConfig.pipeline_depth >= 2): a
        # dispatched-but-uncommitted tick drains BEFORE the mesh
        # surgery below. Its results were computed on the old layout —
        # under the simulated kill they are still readable, exactly
        # like the last completed tick the synchronous loop commits
        # before detecting the loss — and its commits move
        # slot.tokens/emitted, which the migrate-vs-replay decisions
        # and ``_replay_slot``'s delivered-token arithmetic read. This
        # is where ``_lost_pending`` is consumed relative to the
        # pipeline: at the tick boundary, never mid-flight.
        fl, self._inflight = self._inflight, None
        if fl is not None:
            self._tick_commit(fl)
        t0 = time.perf_counter()
        # NOTE: _lost_pending is cleared only on success (or when there
        # is genuinely nothing to recover from) — a recovery that
        # RAISES (min_tp floor, all devices lost) must leave the loss
        # pending so every subsequent dispatch keeps raising instead of
        # running on the broken layout.
        old_devices = self._mesh_devices
        if not old_devices:
            # Never mesh-native: the monitor never targeted this
            # batcher, so there is nothing to recover from. (A tp=1
            # REMNANT keeps its one-entry device list — losing that
            # device too must fall through to the every-device-lost
            # raise below, not report healthy here.)
            with self._cv:
                self._lost_pending.clear()
            return {"old_tp": self._tp, "new_tp": self._tp, "lost": []}
        dead = (
            self._health.dead_ids() if self._health is not None else set()
        )
        lost_here = sorted(
            int(d.id) for d in old_devices if int(d.id) in dead
        )
        if not lost_here:
            with self._cv:
                self._lost_pending.clear()
            return {"old_tp": self._tp, "new_tp": self._tp, "lost": []}
        survivors = [d for d in old_devices if int(d.id) not in dead]
        if not survivors:
            raise DeviceLostError(
                f"every device of the tp={self._tp} mesh is lost"
            )
        old_tp = self._tp
        new_tp = old_tp
        while new_tp > len(survivors) or old_tp % new_tp:
            new_tp -= 1
        if new_tp < self._recovery.min_tp:
            raise DeviceLostError(
                f"{len(survivors)} survivors support tp={new_tp}, below "
                f"RecoveryConfig.min_tp={self._recovery.min_tp}"
            )
        validate_tp(self.lm, new_tp)
        axis = self._axis
        new_devices = survivors[:new_tp]
        plan = plan_kv_reshard(old_devices, new_devices, lost_here, axis)
        if new_tp > 1:
            new_mesh = Mesh(np.asarray(new_devices), (axis,))
            repl = NamedSharding(new_mesh, P())
            kv_sh = kv_head_sharding(new_mesh, axis)
            self.variables = jax.device_put(
                self.variables,
                tree_shardings(
                    self.variables, new_mesh,
                    rules=partial(lm_tp_rules, axis=axis),
                ),
            )
        else:
            # Single-device remnant: the degenerate-mesh discipline
            # from construction — no GSPMD, everything committed to the
            # one survivor via SingleDeviceSharding (consistent
            # placement, no phantom variants).
            new_mesh = None
            repl = SingleDeviceSharding(new_devices[0])
            kv_sh = repl
            self.variables = jax.device_put(self.variables, repl)
        # Live-state migration: KV on the head axis per the plan;
        # replicated members from a surviving replica.
        self._caches = plan.migrate_tree(self._caches, kv_sh)
        for name, block, (ck, _) in zip(
            self.lm.block_names, self._blocks, self._caches
        ):
            # The partial-TP-migration check, by name, on per-SHARD
            # geometry: migrate() rebuilds at the logical shape, so
            # leaf.shape[1] can never disagree — what a plan bug
            # produces is a shard holding the wrong head span. Each of
            # the new_tp shards must carry exactly heads/new_tp rows.
            leaf = ck[0] if isinstance(ck, tuple) else ck
            shard_heads = leaf.addressable_shards[0].data.shape[1]
            check_head_parity(block.cache_heads, shard_heads * new_tp)
        self._dstate = plan.migrate_replicated(self._dstate, repl)
        if self._spec:
            self._draft_variables = plan.migrate_replicated(
                self._draft_variables, repl
            )
            self._draft_caches = plan.migrate_replicated(
                self._draft_caches, repl
            )
        # Install the shrunk layout; the page table re-uploads on the
        # first post-recovery paged tick (placement changed even where
        # the host table did not).
        self._mesh = new_mesh
        self._tp = new_tp
        self._repl = repl
        self._kv_sharding = kv_sh if new_mesh is not None else None
        self._table_dev = None
        self._table_snapshot = None
        # Force a re-TRACE of every program whose jaxpr bakes concrete
        # sharding constraints (jit caches traces on avals + statics —
        # see _shard_kv), and drop the per-instance prefill closures so
        # each bucket re-traces against the new layout on first use.
        self._mesh_epoch += 1
        prefill_dropped = sum(
            f._cache_size() for f in self._prefill_cache.values()
        )
        self._prefill_cache.clear()
        with self._cv:
            # Consume only the losses THIS recovery handled: a device
            # killed on another thread after the dead_ids() snapshot
            # (its leave already queued against the old membership)
            # must stay pending so the next tick recovers again —
            # clear() would erase the event and leave a dead chip in
            # the just-installed mesh.
            consumed = {f"device:{i}" for i in lost_here}
            self._lost_pending = [
                k for k in self._lost_pending if k not in consumed
            ]
            self._mesh_device_ids = {int(d.id) for d in new_devices}
            self._mesh_devices = list(new_devices)
        # Re-lowering against the shrunk mesh is EXPECTED compilation,
        # but LAZY — stage_slot pays on the next admission, a prefill
        # bucket on its next use, possibly long after recovery — so
        # each family gets an explicit expected-compile ALLOWANCE (not
        # a warmup window that would re-close first): one re-lowered
        # variant per STATIC-VARIANT KEY this batcher dispatched under
        # the old epoch (every variant in use re-traces after the epoch
        # bump — a mixed-traffic batcher holds several: step_chunk's
        # (truncate, nucleus) combos, stage_slot's key buckets,
        # _insert's prompt buckets), plus one per dropped prefill
        # executable. Variants never re-used leave allowance slack on
        # the shared watch (the cost of not knowing future traffic, as
        # with prefill); anything beyond the allowance is still the
        # phantom-variant alarm. Granted BEFORE the replay loop below:
        # _replay_slot/_drop_slot dispatch the epoch-bumped _clear_slot
        # inside it, and a concurrent exporter scrape sampling between
        # that compile and a later rearm would fire a false alarm.
        def nvar(prog: str) -> int:
            # No floor: a family never dispatched under the old epoch
            # had no executable to re-lower, and a banked allowance
            # would mask one future REAL phantom variant (the same rule
            # plain-paged _insert follows below).
            return len(self._variants.get(prog, ()))

        # _clear_slot re-lowers if it compiled under the old epoch, or
        # compiles fresh on ANY occupied slot's account — the replay
        # loop below dispatches it directly, a migrated slot's eventual
        # _finish does too. Empty batcher + never compiled: NO banked
        # allowance (the nvar rule — slack on a family recovery gives
        # no reason to compile masks a real phantom).
        will_clear = any(s.req is not None for s in self.slots)
        expected = {
            "continuous.stage_slot": nvar("continuous.stage_slot"),
            "continuous.clear_slot": int(
                bool(nvar("continuous.clear_slot")) or will_clear
            ),
            "continuous.prefill": prefill_dropped,
        }
        if not self._paged or self._spec:
            # _insert dispatches only for dense admissions and the
            # (always-dense) draft admission — a plain paged batcher
            # inserts via _insert_paged and must not bank an allowance
            # that would mask a later real phantom variant.
            expected["continuous.insert"] = nvar("continuous.insert")
        if self._paged:
            # Handoff-adoption variants re-lower like every other
            # sharding-constrained program (nvar rule: only buckets
            # actually dispatched under the old epoch).
            expected["continuous.adopt_pages"] = nvar(
                "continuous.adopt_pages"
            )
            expected["continuous.fork_page"] = nvar(
                "continuous.fork_page"
            )
        if self._spec:
            # One re-lower per speculation DEPTH dispatched under the
            # old epoch (the degradation ladder's set_draft_k makes
            # several possible); a spec batcher that never ticked
            # still re-lowers its first tick's variant.
            expected["continuous.spec_verify"] = (
                nvar("continuous.spec_verify") or 1
            )
            expected["speculative.draft_chunk"] = (
                nvar("speculative.draft_chunk") or 1
            )
        else:
            expected["continuous.step_chunk"] = nvar(
                "continuous.step_chunk"
            )
        for prog, n in expected.items():
            if n:
                self._sentinel.rearm(prog, expect=n)
                self._granted[prog] = self._granted.get(prog, 0) + n
        # Sequence-parallel prefiller: its OWN mesh may have included
        # the dead chip, and its tp must track the batcher's — rebuild
        # the ring from survivors (width shrinks by powers of two),
        # or degrade to the ordinary prefill path when no ring fits.
        # The rebuilt instance's program variants are expected
        # compiles: one allowance per bucket dispatched under the old
        # epoch (the nvar rule — a prefiller that never ran banks
        # nothing).
        if self._sp_cfg is not None and self._sp_cfg.enabled:
            cfg = self._sp_cfg
            if self._sp is not None:
                sp_variants = len(self._sp.variants)
                sp_alive = [
                    d for d in self._sp._mesh.devices.flat
                    if int(d.id) not in dead
                ]
                self._sp.close()
                self._sp = None
            else:
                # Breaker-retired earlier (consecutive dispatch
                # failures — plausibly this very loss): rebuild from
                # the platform pool minus the dead set.
                sp_variants = 0
                sp_alive = [
                    d for d in jax.devices() if int(d.id) not in dead
                ]
            self._sp_failures = 0
            w = cfg.sp_width
            while w > 1 and w * new_tp > len(sp_alive):
                w //= 2
            if w > 1:
                try:
                    mesh_sp = build_sp_mesh(
                        w, new_tp, cfg.sp_axis, axis, devices=sp_alive
                    )
                    self._sp = SPPrefiller(
                        self.lm, self.variables, mesh_sp, self._page,
                        kv_cache_dtype=self._kv_dtype,
                        sp_axis=cfg.sp_axis,
                        tp_axis=(axis if new_tp > 1 else None),
                        name="batcher-sp",
                    )
                    if sp_variants:
                        self._sentinel.rearm(
                            "sp.prefill", expect=sp_variants
                        )
                        self._granted["sp.prefill"] = (
                            self._granted.get("sp.prefill", 0)
                            + sp_variants
                        )
                except Exception:  # noqa: BLE001 — degrade, don't wedge
                    log.exception(
                        "sp prefiller rebuild failed; sp prefill "
                        "disabled until the next recovery"
                    )
            else:
                log.warning(
                    "sp prefill disabled: %d surviving ring devices "
                    "support no sp >= 2 at tp=%d",
                    len(sp_alive), new_tp,
                )
            global_metrics().set_gauge(
                "prefill.sp_width",
                float(self._sp.sp if self._sp is not None else 1),
            )
        # Post-recovery dispatches repopulate against the new epoch —
        # a second recovery must size from its own epoch's variants
        # (the replay loop's _clear_slot dispatch is already one).
        self._variants.clear()
        self._roofline_costs = None  # stale: the program re-lowers
        # Per-request policy: decoding slots migrate (their state just
        # did, bit-exactly); mid-chunked-prefill slots — and everything
        # under policy="replay" — replay from the journal instead.
        migrated = replayed = dropped = 0
        replay_ids: list[int] = []
        replay_all = self._recovery.policy == "replay"
        for slot in self.slots:
            if slot.req is None:
                continue
            if replay_all or slot.pf_done >= 0:
                rid = slot.req.req_id
                try:
                    self._replay_slot(slot)
                    replayed += 1
                    replay_ids.append(rid)
                except Exception:  # noqa: BLE001 — drop, don't wedge
                    if slot.req is None:
                        # _replay_slot released the slot and re-queued
                        # the request before failing (e.g. the final
                        # slot-park dispatch): the replay IS in flight
                        # — dropping here would deref a freed slot and
                        # double-handle the queued request.
                        log.exception(
                            "replay of request %d raised after "
                            "re-queue; replay proceeds", rid,
                        )
                        replayed += 1
                        replay_ids.append(rid)
                    else:
                        log.exception(
                            "replay failed for request %d; dropping",
                            rid,
                        )
                        self._drop_slot(slot)
                        dropped += 1
            else:
                migrated += 1
                global_flight_recorder().record(
                    "kv_migrated",
                    request=slot.req.req_id,
                    slot=slot.idx,
                    tokens_kept=len(slot.tokens),
                )
        if len(replay_ids) > 1:
            # Each _replay_slot appendleft'ed in slot order, inverting
            # arrival order among the replays; restore FIFO (req_id is
            # monotone in submit order) so the oldest in-flight request
            # is not re-admitted last onto the shrunk — possibly
            # halved-capacity — mesh. Rebuild by MEMBERSHIP, not by
            # popping `replayed` entries: a client cancel() landing
            # between a replay's re-queue and this reorder deletes its
            # entry, and a blind popleft would then underflow or steal
            # a non-replay request.
            ids = set(replay_ids)
            with self._cv:
                head = sorted(
                    (r for r in self._queue if r.req_id in ids),
                    key=lambda r: r.req_id,
                )
                if head:
                    rest = [
                        r for r in self._queue if r.req_id not in ids
                    ]
                    self._queue.clear()
                    self._queue.extend(head + rest)
        wall = time.perf_counter() - t0
        with self._cv:
            self._recoveries += 1
            self._recovery_migrated += migrated
            self._recovery_replayed += replayed
            self._recovery_dropped += dropped
            self._last_recovery_wall_s = wall
        reg = global_metrics()
        reg.observe("recovery.wall_s", wall)
        if migrated:
            reg.inc("recovery.migrated_total", float(migrated))
        if replayed:
            reg.inc("recovery.replayed_total", float(replayed))
        if dropped:
            reg.inc("recovery.dropped_total", float(dropped))
        summary = plan.summary()
        summary.update(
            migrated=migrated, replayed=replayed, dropped=dropped,
            wall_s=wall,
        )
        global_flight_recorder().record(
            "mesh_reshard",
            old_tp=old_tp,
            new_tp=new_tp,
            lost=lost_here,
            migrated=migrated,
            replayed=replayed,
            dropped=dropped,
            moved_bytes=plan.moved_bytes,
            host_staged_bytes=plan.host_staged_bytes,
            wall_s=round(wall, 6),
        )
        log.warning(
            "mesh reshard: tp %d -> %d (lost %s): %d migrated, "
            "%d replayed, %d dropped in %.3fs",
            old_tp, new_tp, lost_here, migrated, replayed, dropped, wall,
        )
        return summary

    def _replay_slot(
        self,
        slot: _Slot,
        event: str = "replayed_from_journal",
        extra: dict | None = None,
    ) -> None:
        """Replay one slot's request instead of migrating it: free the
        slot (paged: its registered prompt pages drop into the prefix
        LRU, so the re-admission re-enters through the prefix cache —
        a suffix-only prefill instead of a full one), discard the
        partial stream, and re-queue the request reconstructed from
        the JOURNAL when one is configured (payload + sampling-knob
        meta; the in-memory record is the fallback). Greedy replays
        re-emit the identical stream; sampled ones re-use the
        journaled key schedule — identical too.

        Decode-slot PREEMPTION (``runtime/scheduler``) rides this
        exact path with ``event="preempted"``: cancel the slot,
        prompt pages into the prefix LRU, journal-requeue, re-admit
        later as a prefix-cache hit with ``stream_skip`` suppressing
        re-delivery — preemption reuses recovery's exactly-once and
        SLO-carry-across-lives discipline instead of inventing a
        second one."""
        req = slot.req
        # Per-life timing stamps for the INTERRUPTED life, riding the
        # replay/preemption flight edge (its finish event belongs to a
        # later life whose clock starts mid-stream): TTFT only when
        # this life emitted the request's true first token — the
        # forensics bundle (utils.telemetry.assemble_request) reads
        # each life's story straight off these edges.
        life_stamps: dict = {}
        if slot.t_first != 0.0:
            if req.stream_skip == 0:
                life_stamps["ttft_s"] = round(
                    slot.t_first - req.t_submit, 6
                )
            if len(slot.tokens) > 1 and slot.t_last > slot.t_first:
                life_stamps["life_itl_mean_s"] = round(
                    (slot.t_last - slot.t_first)
                    / (len(slot.tokens) - 1),
                    6,
                )
        # Tokens already DELIVERED to the client across this request's
        # lives (a double-kill chain replays a replay: slot.tokens
        # restarts at 0 each life, so the high-water mark carries).
        delivered = max(req.stream_skip, len(slot.tokens))
        # Snapshot the delivered stream so a cancel that lands before
        # the re-run regenerates it can still resolve result() with
        # what the client saw. Mid-regeneration (this life shorter than
        # the last), the previous life's snapshot stays the truth.
        if req.delivered_tokens is None or len(slot.tokens) >= len(
            req.delivered_tokens
        ):
            req.delivered_tokens = np.asarray(slot.tokens, np.int32)
            req.delivered_lps = np.asarray(slot.lps, np.float32)
            if len(slot.tokens) > req.stream_skip:
                # This life delivered NEW tokens, so its last commit is
                # the client's latest delivery: the next new token's
                # ITL measures from it. A life that only regenerated
                # (double kill mid-catch-up) keeps the older stamp —
                # the client received nothing since.
                req.t_last_delivered = slot.t_last
        source = "memory"
        if self._journal is not None:
            try:
                payload = self._journal.read_payload(req.req_id)
                meta = self._journal.submit_meta(req.req_id)
                if meta is not None:
                    req = _Request(
                        req_id=req.req_id,
                        prompt=np.asarray(payload, np.int32).reshape(-1),
                        steps=int(meta["steps"]),
                        temperature=float(meta["temperature"]),
                        top_k=int(meta["top_k"]),
                        top_p=float(meta["top_p"]),
                        eos_id=meta["eos_id"],
                        folded_keys=np.asarray(
                            meta["folded_keys"], np.uint32
                        ).reshape(-1, 2),
                        stop=tuple(
                            tuple(int(t) for t in s)
                            for s in meta.get("stop", [])
                        ),
                        # Host-side attachments are not journalable;
                        # they carry over from the live record.
                        on_token=req.on_token,
                        t_submit=req.t_submit,
                        slo=req.slo,
                        stream_skip=delivered,
                        slo_violated=req.slo_violated,
                        delivered_tokens=req.delivered_tokens,
                        delivered_lps=req.delivered_lps,
                        t_last_delivered=req.t_last_delivered,
                    )
                    source = "journal"
            except Exception as e:  # noqa: BLE001 — fallback, loudly
                log.warning(
                    "journal replay of request %d fell back to the "
                    "in-memory record: %r",
                    req.req_id, e,
                )
        req.stream_skip = delivered  # memory-fallback path (no-op for
        # the journal reconstruction, which was built with it)
        req.t_requeued = time.perf_counter()
        global_flight_recorder().record(
            event,
            request=req.req_id,
            slot=slot.idx,
            source=source,
            tokens_discarded=len(slot.tokens),
            **life_stamps,
            **(extra or {}),
        )
        with self._cv:
            self._release_slot(slot)
            self._queue.appendleft(req)
            self._cv.notify_all()
        self._park_slot_row(slot.idx)

    def _drop_slot(self, slot: _Slot) -> None:
        """Last resort when a replay cannot be constructed: the request
        finishes with an empty result (a result() waiter unblocks with
        the loss visible, never a timeout) and counts as dropped."""
        req = slot.req
        global_flight_recorder().record(
            "request_dropped", request=req.req_id, slot=slot.idx
        )
        if self.obs_timeline:
            # The same per-finish observations _finish records, so the
            # latency histogram count and per-tenant verdict totals keep
            # summing to the finish count. A drop delivered nothing —
            # its verdict is missed regardless of budgets met so far.
            global_metrics().observe(
                "continuous.request_latency_s",
                time.perf_counter() - req.t_submit,
            )
            if req.slo is not None:
                global_metrics().inc(
                    f"slo.missed_total.{req.slo.tenant}"
                )
        # A dropped request still FINISHES (once, reason="dropped"):
        # the admit==finish lifecycle books and the
        # stats()/continuous.completed mirrors must agree with _finish.
        global_flight_recorder().record(
            "finish", request=req.req_id, reason="dropped", tokens=0
        )
        with self._cv:
            self._done[req.req_id] = np.zeros((0,), np.int32)
            self._done_lps[req.req_id] = np.zeros((0,), np.float32)
            self._cancelled.discard(req.req_id)
            self._completed += 1
            self._release_slot(slot)
            self._cv.notify_all()
        self._journal_done(req.req_id)
        global_metrics().inc("continuous.completed")
        self._park_slot_row(slot.idx)

    def _journal_done(self, req_id: int) -> None:
        """Done-mark a request in the journal (no-op without one; a
        journal write failure must not poison the serving path)."""
        if self._journal is None:
            return
        try:
            self._journal.record_done(req_id)
        except Exception as e:  # noqa: BLE001 — serving outlives the WAL
            log.warning("journal done mark failed for %d: %r", req_id, e)

    def _slo_violation(
        self, slot: _Slot, budget: str, budget_s: float, measured_s: float
    ) -> None:
        """First budget violation flips the request OUT of goodput and
        records ONE ``slo_missed`` flight event (per-request-lifecycle
        grade, like admit/finish — later violations of an
        already-missed request only move the attainment counters)."""
        if slot.slo_ok:
            slot.slo_ok = False
            slot.req.slo_violated = True  # survives a recovery replay
            global_flight_recorder().record(
                "slo_missed",
                request=slot.req.req_id,
                tenant=slot.req.slo.tenant,
                budget=budget,
                budget_s=budget_s,
                measured_s=round(measured_s, 6),
            )

    def _obs_flush(self) -> None:
        """Per-tick registry flush of the timeline/SLO bookkeeping the
        commit path accumulated as plain attributes: the batched ITL
        samples, the SLO attainment counters + gauges, the goodput
        token counters and the windowed ``continuous.goodput_tokens_s``
        rate. ONE call per tick (idle ticks included, so goodput decays
        to zero instead of scraping the last busy rate forever); costs
        a handful of registry-lock holds, inside the obs budget
        (benchmarks/micro/obs_overhead.py)."""
        reg = global_metrics()
        if self._ttft_pending:
            reg.observe_many("continuous.ttft_s", self._ttft_pending)
            self._ttft_pending = []
        if self._itl_pending:
            reg.observe_many("continuous.itl_s", self._itl_pending)
            self._itl_pending = []
        pend = self._slo_pending
        if any(pend.values()):
            tot = self._slo_totals
            for key, n in pend.items():
                if n:
                    tot[key] += n
                    reg.inc(f"slo.{key}_total", float(n))
                    pend[key] = 0
            den = tot["ttft_met"] + tot["ttft_missed"]
            if den:
                reg.set_gauge(
                    "slo.ttft_attainment", tot["ttft_met"] / den
                )
            den = tot["itl_met"] + tot["itl_missed"]
            if den:
                reg.set_gauge(
                    "slo.itl_attainment", tot["itl_met"] / den
                )
        if self._tick_tokens:
            reg.inc("continuous.tokens_total", float(self._tick_tokens))
        if self._tick_good_tokens:
            reg.inc(
                "continuous.good_tokens_total",
                float(self._tick_good_tokens),
            )
        # Windowed goodput rate: per-tick (t, good) samples spanning
        # goodput_window_s. The gauge is tokens-inside-budget per
        # second over that window — the "graceful degradation under
        # overload" number the load harness sweeps.
        now = time.perf_counter()
        gs = self._goodput_samples
        gs.append((now, self._tick_good_tokens))
        self._tick_tokens = 0
        self._tick_good_tokens = 0
        cutoff = now - self.goodput_window_s
        while len(gs) > 1 and gs[0][0] < cutoff:
            gs.popleft()
        span = now - gs[0][0]
        if span > 0:
            # gs[0] anchors the window start; its tokens were counted
            # by the PREVIOUS span, so the rate sums the later samples.
            good = sum(g for _, g in list(gs)[1:])
            reg.set_gauge("continuous.goodput_tokens_s", good / span)
        if self._capacity is not None:
            # Capacity plane: tick-gap feed + (rate-limited inside
            # update) book rebuild, sketch refresh, health scoring and
            # the capacity.* gauges. Same seam, same obs budget.
            if self._cap_last_flush:
                self._capacity.on_tick_gap(now - self._cap_last_flush)
            self._cap_last_flush = now
            self._capacity.update(self, now)

    def _release_slot(self, slot: _Slot) -> None:
        """Reset one slot's host-side lifecycle state and return its
        pages to the pool — caller holds ``_cv``. The SINGLE definition
        ``_finish`` / ``_replay_slot`` / ``_drop_slot`` share, so a new
        ``_Slot`` lifecycle field cannot silently diverge across the
        three release paths."""
        slot.req = None
        slot.tokens = []
        slot.lps = []
        slot.pf_done = -1
        slot.slo_ok = True
        slot.t_first = 0.0
        slot.obs_count = 0
        if self._paged:
            self._pager.free_slot(slot.idx)

    def _park_slot_row(self, idx: int) -> None:
        """Park a retired slot's device row (one donated setter
        dispatch, outside the lock): active mask off + idle-sentinel
        position, so the next chunk's garbage writes route to the
        trash strip / trash page again. The SINGLE ``_clear_slot``
        dispatch site ``_finish`` / ``_replay_slot`` / ``_drop_slot``
        share — it also books the family into ``_variants`` so
        ``recover()`` knows an old-epoch executable exists to
        re-lower."""
        self._variants.setdefault("continuous.clear_slot", set()).add(0)
        self._dstate = self._clear_slot(
            self._dstate, self._h2d(np.int32(idx)),
            epoch=self._mesh_epoch,
        )

    def _finish(self, slot: _Slot, reason: str = "completed") -> None:
        req = slot.req
        if self.obs_timeline:
            global_metrics().observe(
                "continuous.request_latency_s",
                time.perf_counter() - req.t_submit,
            )
            if req.slo is not None:
                # Request-level verdict for the tenant books: met =
                # finished with every evaluated budget inside limits.
                kind = "met" if slot.slo_ok else "missed"
                global_metrics().inc(
                    f"slo.{kind}_total.{req.slo.tenant}"
                )
        toks = np.asarray(slot.tokens, np.int32)
        lps = np.asarray(slot.lps, np.float32)
        if req.delivered_tokens is not None and len(toks) < len(
            req.delivered_tokens
        ):
            # A replay cancelled mid-regeneration holds fewer tokens in
            # THIS life than the client received in the last; result()
            # must never contradict the delivered stream.
            toks, lps = req.delivered_tokens, req.delivered_lps
        # Flight events stay UNGATED like cancel's: the recorder's
        # contract is always-on per-lifecycle — a post-mortem must not
        # show cancels for requests with no admit/finish.
        # Per-life timing stamps ride the finish edge when the timeline
        # stamped them (obs_timeline): the per-request forensics bundle
        # (utils.telemetry.assemble_request, GET /debug/request/<id>)
        # reads TTFT and this life's mean inter-token gap straight off
        # the flight stream instead of reverse-engineering them from
        # process-wide histograms.
        stamps: dict = {}
        if slot.t_first != 0.0:
            if req.stream_skip == 0:
                stamps["ttft_s"] = round(
                    slot.t_first - req.t_submit, 6
                )
            if len(slot.tokens) > 1 and slot.t_last > slot.t_first:
                stamps["life_itl_mean_s"] = round(
                    (slot.t_last - slot.t_first)
                    / (len(slot.tokens) - 1),
                    6,
                )
        if req.t_requeued:
            stamps["replayed_life"] = True
        global_flight_recorder().record(
            "finish",
            request=req.req_id,
            reason=reason,
            tokens=len(toks),
            **stamps,
        )
        with self._cv:
            self._done[req.req_id] = toks
            self._done_lps[req.req_id] = lps
            while len(self._done_lps) > self._LPS_CAP:
                evicted = next(iter(self._done_lps))
                self._done_lps.pop(evicted)
                global_flight_recorder().record(
                    "lps_evicted", request=evicted
                )
            # Consume any cancel marker that raced a natural finish —
            # markers must never outlive their request.
            self._cancelled.discard(req.req_id)
            self._cv.notify_all()  # result() waiters
            # Slot retirement + lifetime counters stay inside the lock so
            # stats() can't observe "finished but still counted active"
            # (the torn triple an unlocked _completed/slot.req allowed).
            self._completed += 1
            # Pages return to the pool the moment the request retires —
            # the capacity win continuous paging exists for.
            self._release_slot(slot)
        self._journal_done(req.req_id)
        self._park_slot_row(slot.idx)
        global_metrics().inc("continuous.completed")

    def _commit(self, slot: _Slot, token: int, lp: float) -> None:
        """Append one emitted token; EOS, a stop sequence, or a pending
        cancel latches and finishes the request."""
        req = slot.req
        with self._cv:
            cancelled = req.req_id in self._cancelled
            self._cancelled.discard(req.req_id)
        if cancelled:
            # Partial stream becomes the result; the chunk's remaining
            # tokens for this slot are garbage nobody reads.
            self._finish(slot, reason="cancelled")
            return
        if self.obs_timeline:
            # One perf_counter stamp per committed token. TTFT observes
            # inline (once per request); inter-token gaps batch into
            # _itl_pending and flush under ONE registry-lock hold per
            # tick (observe_many) — the hot-path contention stays O(1)
            # per tick, not O(tokens). Contiguity guards make a
            # mid-request obs_timeline toggle drop samples instead of
            # corrupting them: TTFT only for the request's TRUE first
            # token, ITL only when the previous commit also stamped.
            now = time.perf_counter()
            emitted_before = len(slot.tokens)
            # A replay's regenerated prefix (indices < stream_skip) was
            # already delivered, stamped and counted in the request's
            # first life: it re-runs for state only — no second TTFT,
            # no ITL samples, no goodput/attainment movement.
            regen = emitted_before < req.stream_skip
            if slot.t_first == 0.0:
                slot.t_first = now
                if emitted_before == 0 and req.stream_skip == 0:
                    # TTFT samples batch like ITL: one observe_many per
                    # tick in _obs_flush. The budget COMPARISON stays
                    # inline (plain float compare) — slo_ok must flip
                    # before this tick's later goodput increments read
                    # it.
                    ttft = now - req.t_submit
                    self._ttft_pending.append(ttft)
                    if (
                        self._capacity is not None
                        and req.ttft_forecast_s > 0.0
                    ):
                        # Close the forecast loop: realized-vs-forecast
                        # pairs drain in _obs_flush (calibration gauge,
                        # abs-error histogram, bias update).
                        self._capacity.on_ttft(req.ttft_forecast_s, ttft)
                    if req.slo is not None and (
                        req.slo.ttft_budget_s is not None
                    ):
                        if ttft <= req.slo.ttft_budget_s:
                            self._slo_pending["ttft_met"] += 1
                        else:
                            self._slo_pending["ttft_missed"] += 1
                            self._slo_violation(
                                slot, "ttft", req.slo.ttft_budget_s, ttft
                            )
            elif slot.obs_count == emitted_before and not regen:
                if (
                    emitted_before == req.stream_skip
                    and req.t_last_delivered != 0.0
                ):
                    # First NEW token after a replay: the client's
                    # previous token landed before the kill, so the gap
                    # spans kill + recovery + re-prefill + regeneration
                    # — the stall the client actually saw, judged like
                    # a migrated request's recovery wall is.
                    gap = now - req.t_last_delivered
                else:
                    gap = now - slot.t_last
                self._itl_pending.append(gap)
                if req.slo is not None and (
                    req.slo.itl_budget_s is not None
                ):
                    if gap <= req.slo.itl_budget_s:
                        self._slo_pending["itl_met"] += 1
                    else:
                        self._slo_pending["itl_missed"] += 1
                        self._slo_violation(
                            slot, "itl", req.slo.itl_budget_s, gap
                        )
            slot.t_last = now
            slot.obs_count = emitted_before + 1
            # Goodput accounting: every committed token, split by
            # whether its request is still inside budget (no-SLO
            # requests have nothing to violate and stay good). Plain
            # int incs here; the registry sees one flush per tick.
            if not regen:
                self._tick_tokens += 1
                if slot.slo_ok:
                    self._tick_good_tokens += 1
        slot.tokens.append(token)
        slot.lps.append(lp)
        if req.on_token is not None and len(slot.tokens) > req.stream_skip:
            # stream_skip suppresses re-delivery of the indices a
            # replayed request already streamed pre-kill (the re-run
            # regenerates them identically) — on_token stays
            # exactly-once even across a recovery replay.
            req.on_token(req.req_id, token, len(slot.tokens) - 1)
        if req.eos_id is not None and token == req.eos_id:
            # generate() pads with EOS forever after; a server frees the
            # slot instead — the emitted stream up to EOS is identical.
            self._finish(slot, reason="eos")
            return
        slot.emitted += 1
        slot.last_token = token
        # Host-side stop sequences: purely a stream-tail check — the
        # emitted stream equals solo generate() truncated at the first
        # occurrence (inclusive), whatever the stop tokens are.
        for seq in req.stop:
            n = len(seq)
            if n and len(slot.tokens) >= n and tuple(
                slot.tokens[-n:]
            ) == seq:
                self._finish(slot, reason="stop")
                return
        if slot.emitted >= req.steps:
            self._finish(slot)

    def _admit(self) -> None:
        # Traffic control: a high-priority request past its TTFT
        # headroom may free a slot here (replay-path preemption); the
        # loop below then admits it first (popleft is priority-first).
        self._maybe_preempt()
        if self._paged:
            # Drain page claims parked by client-thread fan-out group
            # deaths (cancel / mid-group rejection): only this thread
            # may move pager rc.
            with self._cv:
                rel, self._fanout_release = self._fanout_release, []
            for pg in rel:
                self._pager.release_claim(pg)
        for i, slot in enumerate(self.slots):
            if slot.req is not None:
                continue
            with self._cv:
                if not self._queue:
                    continue
                req = self._queue.popleft()
                self._admitting = req.req_id  # cancel() sees it as live
                fg = self._fanout_groups.get(req.fanout_group)
            s0 = req.prompt.shape[0]
            bucket = next(b for b in self.prompt_buckets if b >= s0)
            if self._sp is not None and s0 >= self._sp_cfg.sp_threshold:
                # Long admission: sp-shard the prefill wall across the
                # ring BEFORE the prefix probe — the probe then shares
                # the landed pages as ordinary hits and the suffix
                # pass is all that runs on the decode mesh.
                self._sp_admit(req)
            m = 0
            if self._paged:
                # Prefix probe: acquire (rc+1) every already-cached FULL
                # prompt page, longest run first-miss-stops. Cap at the
                # page before the last prompt token so the suffix
                # forward is never empty (the first sampled token needs
                # a live last-position hidden state).
                P = self._page
                if self._tier is not None:
                    # Consult the host tier BEFORE the probe declares
                    # any miss: host-resident prefix pages readmit
                    # (budgeted) through the adopt_cached landing path
                    # and then share below as ordinary hits.
                    self._maybe_readmit(req)
                for j in range((s0 - 1) // P):
                    key = Pager.prefix_key(req.prompt, (j + 1) * P)
                    if self._pager.lookup_share(i, key) is None:
                        break
                    m += 1
                # All-or-nothing reservation for the REST of the window
                # (prefill writes `bucket` positions; decode reaches
                # s0 + steps - 1). FIFO head-of-line: if the pool can't
                # cover the next request, admission stops — later
                # (smaller) requests do not jump it.
                # Speculative mode reserves draft_k SLACK pages: the
                # verify chunk's rejected overshoot writes land there,
                # masked, instead of off the end of the window.
                span = max(
                    bucket, s0 + req.steps + self._spec_k + self._spec_w
                )
                n_pages = -(-span // P) - m
                if not self._pager.alloc(i, n_pages):
                    self._pager.free_slot(i)  # releases the shares too
                    with self._cv:
                        self._queue.appendleft(req)
                        self._admitting = None
                    return
                # Radix books: token-weighted hit accounting for this
                # admission (partial-hit counting when the match stops
                # short of the last full prompt page).
                self._pager.record_prefix_match(m, s0)
            # Copy-on-write fork eligibility: a greedy fan-out sibling
            # whose probe matched EVERY page before the last prompt
            # token, with the group's source page claimed and its first
            # commit cached — the suffix forward is skipped entirely
            # (the source page already holds the K/V of every prompt
            # position, the last one included).
            cow = (
                self._paged
                and fg is not None
                and fg.greedy
                and fg.page is not None
                and fg.first is not None
                and req.temperature == 0.0
                and m == (s0 - 1) // self._page
            )
            chunked = (
                self._paged
                and not cow
                and self._prefill_chunk is not None
                and s0 - m * self._page > self._prefill_chunk
            )
            tracer = global_tracer()
            t0 = tracer.now() if tracer.enabled else 0.0
            # Capacity forecaster feed: the admission prefill's wall is
            # measured through the first-token host sync below (the
            # tracer stamp above may be disabled; this one is gated on
            # the capacity plane instead). cow (zero positions) and
            # chunked (spread over ticks) admissions skip the feed —
            # the forecaster's calibration bias absorbs them.
            cap_t0 = (
                time.perf_counter() if self._capacity is not None else 0.0
            )
            cap_tokens = 0
            first = None
            if chunked:
                # Chunked prefill: park the slot in the prefilling state
                # — tick() runs one chunk pass per tick alongside the
                # decode batch, so this long admission never stalls the
                # requests already decoding. The first token samples on
                # the final chunk (no _commit here).
                pass
            elif cow:
                # Copy-on-write fork: one device page copy (data-
                # dependent on the source sibling's prefill through
                # the donated cache buffers, so device-side ordering
                # is free) plus the group's cached first commit below
                # — zero prompt positions recomputed. Junk the source
                # page may carry past the prompt (its owner's decode
                # writes, when s0 is not page-aligned) is overwritten
                # by this sibling's own first decode write or causally
                # masked before any read, so the forked stream stays
                # bit-identical to an independent submit's.
                dst = self._pager.owned(i)[m]
                self._variants.setdefault(
                    "continuous.fork_page", set()
                ).add(0)
                self._caches = self._fork_page(
                    self._caches,
                    self._h2d(np.array([fg.page, dst], np.int32)),
                    epoch=self._mesh_epoch,
                )
                self._pager.note_cow_fork()
                global_flight_recorder().record(
                    "cow_fork",
                    request=req.req_id,
                    src_page=int(fg.page),
                    dst_page=int(dst),
                    prefix_pages=m,
                    saved_positions=s0 - m * self._page,
                )
            elif m:
                # Suffix-only prefill against the shared prefix pages.
                # The suffix pads to whole PAGES, not prompt buckets —
                # page rounding keeps the strip inside the reserved
                # window by construction (ceil(s0/P) <= ceil(span/P)),
                # where bucket rounding could round past it.
                slen = s0 - m * self._page
                sbucket = -(-slen // self._page) * self._page
                n_strip = m + sbucket // self._page
                owned = self._pager.owned(i)
                assert n_strip <= len(owned)
                # Pad the window to a power-of-two page count (pad
                # entries point at the trash page, masked past the
                # causal window) — the SAME discipline as
                # _prefill_step, so a long-context prompt's suffix
                # pass compiles log2 window variants instead of one
                # per prefix page count. Byte-equal by the pinned
                # padding invariance (masked columns contribute exact
                # zeros).
                n_pad = 1
                while n_pad < n_strip:
                    n_pad *= 2
                pages = owned[:n_strip] + [0] * (n_pad - n_strip)
                ids = np.zeros((1, sbucket), np.int32)
                ids[0, :slen] = req.prompt[m * self._page:]
                first, first_lp, self._caches = self._prefill_suffix_fn(
                    sbucket, n_pad
                )(
                    self.variables,
                    self._caches,
                    self._h2d(np.asarray(pages, np.int32)),
                    self._h2d(ids),
                    self._h2d(np.array(
                        [m * self._page, slen, req.top_k], np.int32
                    )),
                    self._h2d(np.array(
                        [req.temperature, req.top_p], np.float32
                    )),
                    self._h2d(req.folded_keys[0][None]),
                    truncate=req.top_k < self.lm.vocab,
                    nucleus=req.top_p < 1.0,
                )
                self._count_prefill(slen)
                cap_tokens = slen
            else:
                ids = np.zeros((1, bucket), np.int32)
                ids[0, :s0] = req.prompt
                first, first_lp, kvs = self._prefill_fn(bucket)(
                    self.variables,
                    self._h2d(ids),
                    self._h2d(np.array([s0, req.top_k], np.int32)),
                    self._h2d(np.array(
                        [req.temperature, req.top_p], np.float32
                    )),
                    self._h2d(req.folded_keys[0][None]),
                    truncate=req.top_k < self.lm.vocab,
                    nucleus=req.top_p < 1.0,
                )
                if self._paged:
                    self._caches = self._insert_paged(
                        self._caches,
                        self._h2d(np.asarray(self._pager.owned(i), np.int32)),
                        kvs,
                    )
                else:
                    # Pad each block's (1, h, bucket, hd) K/V to the
                    # cache length happens inside _insert via
                    # dynamic_update_slice bounds.
                    self._variants.setdefault(
                        "continuous.insert", set()
                    ).add(bucket)
                    self._caches = self._insert(
                        self._caches, self._h2d(np.int32(i)), kvs
                    )
                self._count_prefill(s0)
                cap_tokens = s0
            if self._paged and not chunked:
                # Publish this request's full prompt pages for future
                # sharing (first writer wins; the shared ones are
                # already registered). Chunked admissions register on
                # their final pass instead.
                owned = self._pager.owned(i)
                for j in range(m, s0 // self._page):
                    self._pager.register(
                        owned[j], Pager.prefix_key(req.prompt, (j + 1) * self._page)
                    )
            if tracer.enabled and not chunked:
                tracer.add_span(
                    "batcher.prefill",
                    start=t0,
                    end=tracer.now(),
                    request=req.req_id,
                    bucket=bucket,
                    prefix_pages=m,
                )
            slot.req = req
            slot.s0 = s0
            slot.pos = s0
            slot.emitted = 0
            slot.tokens = []
            slot.lps = []
            slot.t_first = 0.0  # timeline: no token emitted yet
            slot.obs_count = 0
            # A replayed request that already missed its budget stays
            # missed — its client experienced the violation.
            slot.slo_ok = not req.slo_violated
            slot.pf_done = m * self._page if chunked else -1
            tok0 = lp0 = None
            if not chunked:
                # One host sync per admission either way; the fork path
                # reuses the group's cached first commit (greedy: the
                # first token is a pure function of the prompt).
                if cow:
                    tok0, lp0 = fg.first, fg.first_lp
                else:
                    tok0, lp0 = int(first[0]), float(first_lp[0])
                    if self._capacity is not None and cap_tokens:
                        # The int() above is the host sync, so this
                        # wall covers dispatch AND compute.
                        self._capacity.on_prefill(
                            cap_tokens, time.perf_counter() - cap_t0
                        )
            with self._cv:
                self._admitting = None  # slot-bound: visible to cancel()
                self._admitted += 1
                gid = req.fanout_group
                if fg is not None and gid >= 0:
                    req.fanout_group = -1
                    fg.remaining -= 1
                    if (
                        fg.greedy
                        and fg.page is None
                        and fg.remaining > 0
                        and tok0 is not None
                    ):
                        # First admitted greedy sibling: claim its
                        # last prompt page (rc+1 — outlives the
                        # sibling's retirement) and cache its first
                        # commit for the siblings' forks. Chunked
                        # admissions leave the group fork-less
                        # (tok0 is None): later siblings run the
                        # ordinary suffix path.
                        fg.page = self._pager.owned(i)[
                            (s0 - 1) // self._page
                        ]
                        self._pager.retain(fg.page)
                        fg.first, fg.first_lp = tok0, lp0
                    if fg.remaining <= 0:
                        self._fanout_kill_locked(gid, fg, direct=True)
            global_metrics().inc("continuous.admitted")
            if self._paged:
                # Prefix-cache effectiveness per admission: prompt pages
                # REUSED from the content-addressed cache instead of
                # recomputed (0 on a cold admission). Per-admission, not
                # per-token — always on, like the flight events.
                global_metrics().observe(
                    "paged.pages_reused_per_admission", float(m)
                )
            # A replay's wait measures from its re-queue, not from the
            # original submit (that span is first-life decode plus the
            # recovery wall, not time spent queued).
            queue_wait = time.perf_counter() - (
                req.t_requeued or req.t_submit
            )
            if self._capacity is not None:
                self._capacity.on_queue_wait(queue_wait)
            if self.obs_timeline:
                global_metrics().observe(
                    "continuous.queue_wait_s", queue_wait
                )
            global_flight_recorder().record(
                "admit",
                request=req.req_id,
                slot=slot.idx,
                prompt_len=s0,
                chunked=chunked,
                queue_wait_s=round(queue_wait, 6),
            )
            if not chunked:
                self._commit(slot, tok0, lp0)
                if slot.req is req:
                    # Survived the first commit: stage its whole device
                    # row in one fused setter call (and, speculating,
                    # seed the draft's cache with the prompt).
                    if self._spec:
                        self._admit_draft(slot.idx, req)
                    self._stage_decode_row(slot)

    def _stage_decode_row(self, slot: _Slot) -> None:
        """Stage one freshly admitted slot's sampling row into the
        device state: THREE fused transfers (int vector, float vector,
        key block) + one donated setter dispatch, however many sampling
        fields a request carries. The key block pads to a power-of-two
        bucket so _stage_slot compiles log2(max_steps) variants."""
        req = slot.req
        nk = req.folded_keys.shape[0]
        nkb = 1
        while nkb < nk:
            nkb *= 2
        # The bucket must still fit the (slots, max_len, 2) key buffer
        # (nk <= max_len - 1 by submit()'s length check, so the cap
        # never truncates real keys).
        nkb = min(nkb, self.lm.max_len)
        kbuf = np.zeros((nkb, 2), np.uint32)
        kbuf[:nk] = req.folded_keys
        ints = np.array(
            [
                slot.idx,
                slot.last_token,
                # tick-entry invariant: the next step consumes
                # last_token (stream index emitted-1) at s0 + emitted - 1
                slot.s0 + slot.emitted - 1,
                req.top_k,
                nk,
                slot.emitted,
            ],
            np.int32,
        )
        floats = np.array([req.temperature, req.top_p], np.float32)
        self._variants.setdefault("continuous.stage_slot", set()).add(nkb)
        self._dstate = self._stage_slot(
            self._dstate,
            self._h2d(ints),
            self._h2d(floats),
            self._h2d(kbuf),
            epoch=self._mesh_epoch,
        )

    def _ensure_mesh(self) -> None:
        """The device-lost gate, shared by every dispatch ENTRY POINT
        running on the ticking thread (``tick``,
        :meth:`adopt_prefill_pages`): a mesh device died since the
        last pass — recover BEFORE dispatching anything onto the
        broken layout. Under ``auto_reshard`` this re-shards inline
        and proceeds on the shrunk mesh; otherwise every dispatch
        raises until :meth:`recover` is called."""
        if self._lost_pending:
            if self._recovery.auto_reshard:
                self.recover()
            else:
                with self._cv:
                    lost = list(self._lost_pending)
                raise DeviceLostError(
                    f"mesh device(s) lost: {lost} — auto_reshard is "
                    "off; call recover()"
                )

    def _count_prefill(self, n: int) -> None:
        """Book ``n`` prompt positions computed by an in-tick prefill
        pass (instance counter always; the registry counter rides the
        ``obs_timeline`` gate like every other timeline counter — one
        inc per pass, admission-rate, not token-rate)."""
        self._prefill_tokens += n
        if self.obs_timeline:
            global_metrics().inc(
                "continuous.prefill_tokens_total", float(n)
            )

    def _current_table(self):
        """Device copy of the pager's page table, re-uploaded only when
        the host table changed (admissions, retirements, window
        recycling, prefix shares) — a steady-state paged tick performs
        zero table transfers. Snapshot-compare rather than dirty flags:
        self-healing against any new pager mutation site."""
        t = np.asarray(self._pager.table())
        if self._table_dev is None or not np.array_equal(
            t, self._table_snapshot
        ):
            self._table_snapshot = np.array(t, copy=True)
            self._table_dev = self._h2d(self._table_snapshot)
        return self._table_dev

    def _prefill_step(self, slot: _Slot) -> None:
        """One chunked-prefill pass for ``slot``: write positions
        [pf_done, pf_done + clen) through the incremental-prefill body.
        The final pass samples the first token and flips the slot into
        the decode batch."""
        req, s0, P = slot.req, slot.s0, self._page
        tracer = global_tracer()
        t0 = tracer.now() if tracer.enabled else 0.0
        pos0 = slot.pf_done  # page-aligned (chunks are page multiples)
        clen = min(self._prefill_chunk, s0 - pos0)
        final = pos0 + clen >= s0
        cbucket = -(-clen // P) * P
        n_strip = (pos0 + cbucket) // P
        owned = self._pager.owned(slot.idx)
        assert n_strip <= len(owned)
        # Pad the window to a power-of-two page count so a long prompt
        # compiles log2 variants instead of one per chunk ordinal (pad
        # entries point at the trash page; their positions sit past the
        # chunk's causal window, masked and compute-skipped).
        n_pad = 1
        while n_pad < n_strip:
            n_pad *= 2
        pages = owned[:n_strip] + [0] * (n_pad - n_strip)
        ids = np.zeros((1, cbucket), np.int32)
        ids[0, :clen] = req.prompt[pos0:pos0 + clen]
        first, first_lp, self._caches = self._prefill_suffix_fn(
            cbucket, n_pad, sample=final
        )(
            self.variables,
            self._caches,
            self._h2d(np.asarray(pages, np.int32)),
            self._h2d(ids),
            self._h2d(np.array([pos0, clen, req.top_k], np.int32)),
            self._h2d(np.array(
                [req.temperature, req.top_p], np.float32
            )),
            self._h2d(req.folded_keys[0][None]),
            # Only the final pass samples; mid-prefill passes must not
            # fork compile variants over sampling flags they never use.
            truncate=final and req.top_k < self.lm.vocab,
            nucleus=final and req.top_p < 1.0,
        )
        slot.pf_done = pos0 + clen
        self._count_prefill(clen)
        if tracer.enabled:
            tracer.add_span(
                "batcher.prefill_chunk",
                start=t0,
                end=tracer.now(),
                request=req.req_id,
                pos0=int(pos0),
                chunk_len=int(clen),
                final=final,
            )
        if final:
            for j in range(s0 // P):  # register() skips known keys
                self._pager.register(
                    owned[j], Pager.prefix_key(req.prompt, (j + 1) * P)
                )
            slot.pf_done = -1
            self._commit(slot, int(first[0]), float(first_lp[0]))
            if slot.req is req:
                if self._spec:
                    self._admit_draft(slot.idx, req)
                self._stage_decode_row(slot)

    def _spec_decode(self, active, tracer):
        """Dispatch one SPECULATIVE decode round for the whole slot
        batch: the fixed-shape draft scan
        (``models/speculative.draft_chunk`` over the device-resident
        per-slot state), then the fused verify-and-accept program
        (``_spec_verify``). Exactly two compiled programs however rows
        desynchronize — guarded by the compile-count test. Stages zero
        host arrays steady-state; the round's (tokens, logprobs,
        accepted) D2H starts here as ONE async fetch and lands in
        ``_tick_commit`` (same call at depth 1, next tick at depth 2).
        Returns the round's :class:`_InFlight` (binding identity is
        filled in by ``_tick_dispatch``)."""
        d = self._spec_k_eff
        w = self._spec_w
        # Static sampling flags, computed host-side exactly like the
        # lockstep path's: an all-greedy batch keeps dispatching the
        # PR-12 program text (bit-identity + compile footprint pinned);
        # any sampled row switches the verify to its speculative-
        # sampling variant, with the truncate/nucleus sorts elided
        # unless some active request needs them.
        sample = any(s.req.temperature > 0.0 for s in active)
        truncate = sample and any(
            s.req.top_k < self.lm.vocab for s in active
        )
        nucleus = sample and any(s.req.top_p < 1.0 for s in active)
        self._variants.setdefault("speculative.draft_chunk", set()).add(d)
        self._variants.setdefault("continuous.spec_verify", set()).add(
            (d, sample, truncate, nucleus)
        )
        eo = self._eobs
        # Snapshot the gate ONCE per call: flipping obs_engine while a
        # tick is in flight must never pair a 0.0 open with an enabled
        # close (a perf-counter-sized garbage histogram sample).
        eo_on = eo.enabled
        t_ph = eo.now() if eo_on else 0.0
        # Only the span tags consume the id tuple — don't build it on
        # the untraced hot path.
        req_ids = (
            tuple(s.req.req_id for s in active) if tracer.enabled else ()
        )
        t_draft = tracer.now() if tracer.enabled else 0.0
        if w:
            # Tree drafts: d chain steps + the argmax-leaf step + one
            # leaf-coverage step (the leaf token's own draft-cache
            # write), with the top-w leaf candidates harvested from
            # logits the scan computes anyway (equal draft FLOPs per
            # committed token). cands = the top-w ids of the step that
            # predicts the post-chain position (scan index d).
            dtoks, dtops, self._draft_caches = draft_chunk(
                self._draft_lm,
                self._draft_variables,
                self._dstate["tok"],
                self._dstate["pos"],
                self._draft_caches,
                n=d + 2,
                tail_w=w,
            )
            cands = dtops[d]  # (B, w); cands[:, 0] == dtoks[d]
        else:
            cands = None
            dtoks, self._draft_caches = draft_chunk(
                self._draft_lm,
                self._draft_variables,
                self._dstate["tok"],
                self._dstate["pos"],
                self._draft_caches,
                n=d + 1,
            )
        if tracer.enabled:
            # Dispatch-side cost of the draft scan; the verify span
            # below carries the host sync. Tagged with the same request
            # ids the framing headers use, so Perfetto correlates these
            # rows with dispatcher/worker spans.
            tracer.add_span(
                "decode.draft",
                start=t_draft,
                end=tracer.now(),
                slots=len(active),
                draft_k=d,
                requests=req_ids,
            )
        if eo_on:
            # span=False: decode.draft above is the tracer row.
            t_ph = eo.phase("draft", t_ph, span=False)
        t_verify = tracer.now() if tracer.enabled else 0.0
        toks, lps, acc, self._caches, self._dstate = self._spec_verify(
            self.variables,
            self._caches,
            self._dstate,
            dtoks,
            self._current_table() if self._paged else None,
            cands,
            sample=sample,
            truncate=truncate,
            nucleus=nucleus,
            epoch=self._mesh_epoch,
        )
        with self._cv:
            self._ticks += 1
        global_metrics().inc("continuous.ticks")
        # The round's ONE host fetch covers all three arrays — started
        # here (async), landed at commit.
        return _InFlight(
            fetch=_AsyncFetch((toks, lps, acc)),
            reqs=[],
            lives=[],
            spec=(d, w, tuple(s.idx for s in active)),
            t_span=t_verify,
            t_eo=t_ph,
            req_ids=req_ids,
        )

    def tick(self) -> int:
        """Admit waiting requests into free slots, run ONE prefill chunk
        for each slot mid-chunked-prefill, then decode: one chunk of
        lockstep steps (a single compiled scan) — or, in speculative
        mode, one draft-scan + fused-verify round that commits
        1..draft_k+1 tokens per slot (``_spec_decode``). Returns the
        number of active slots whose decode pass was COMMITTED by this
        call (0 = nothing committed).

        The call is split into a host **dispatch** half
        (``_tick_dispatch``: scheduler/admission/prefill + the decode
        dispatch, with the D2H fetch started asynchronously) and a
        **commit** half (``_tick_commit``: land the fetch, apply
        per-slot commits, flush telemetry). At
        ``RuntimeConfig.pipeline_depth=1`` the halves run back to back
        — the historical synchronous loop, except the fetch now
        overlaps the tracer/phase bookkeeping between them. At
        ``depth=2`` this call dispatches tick *t* and then commits
        tick *t−1* while *t* runs on device: the host's scheduler pass
        overlaps the device wall, and every result is delivered with a
        one-tick lag (drained at :meth:`drain` / :meth:`run` exit /
        :meth:`recover`).

        Engine-tier phase timing (``utils.profiling.EngineObs``,
        ``obs_engine``): admit / prefill / draft / verify / decode /
        dispatch / commit_lag / commit / update each record one
        ``engine.phase.<name>_s`` histogram sample per tick when
        enabled; disabled, each site costs one branch. decode/verify
        span dispatch→results-landed, so under the pipelined loop they
        OVERLAP the other phases — that overlap is the win, gauged as
        ``runtime.overlap_ratio``. The compile sentinel samples once
        at the end of every commit half, so an unexpected recompile is
        flagged next to the tick that paid for it."""
        if self._depth <= 1:
            fl = self._tick_dispatch()
            return self._tick_commit(fl) if fl is not None else 0
        # Pipelined: dispatch t FIRST (its programs enqueue behind
        # t-1's on the device stream), then commit t-1 on the host
        # while t runs. _ensure_mesh inside the dispatch half drains
        # the in-flight tick through recover() on a device loss.
        fl = self._tick_dispatch()
        prev, self._inflight = self._inflight, fl
        if prev is not None:
            return self._tick_commit(prev)
        return 0

    def drain(self) -> int:
        """Commit the in-flight tick, if any (pipelined runtime) —
        the explicit pipeline boundary. Call before reading results
        outside :meth:`run` / :meth:`result`, before handing the
        device to another dispatcher (DisaggServer does), or before
        tearing down. Idempotent; returns the committed tick's active
        count (0 = pipeline was empty)."""
        fl, self._inflight = self._inflight, None
        if fl is not None:
            return self._tick_commit(fl)
        return 0

    def _tick_dispatch(self) -> "_InFlight | None":
        """Host half of one tick: degradation/tier steps, admission,
        cancel sweep, chunked-prefill passes, gauge refresh, then ONE
        decode dispatch with its async D2H fetch started. Returns the
        tick's :class:`_InFlight` record, or None for an idle tick
        (nothing dispatched)."""
        self._ensure_mesh()
        t0 = time.perf_counter()  # dispatch wall for overlap_ratio
        if self._controller is not None:
            # Closed-loop degradation BEFORE admission: this tick's
            # admits see the ladder's current shed level.
            self._controller.step(self)
        if self._tier is not None:
            # Host-tier step BEFORE admission: reset the per-tick
            # spill/readmit budgets and pre-spill the coldest LRU
            # pages past the watermark, so admission-pressure
            # evictions this tick find their content host-backed.
            self._tier_step()
        eo = self._eobs
        # Snapshot the gate ONCE per tick (see _spec_decode).
        eo_on = eo.enabled
        t_ph = eo.now() if eo_on else 0.0
        # Prefill-stall accounting (continuous.prefill_stall_s): when
        # requests were already DECODING at tick entry, every second
        # this tick spends on in-tick prefill work (admission prefill
        # passes, chunked-prefill passes) is decode delay they eat as
        # inter-token latency — the pathology the disaggregated path
        # (runtime/disagg) exists to remove. Two stamps + one counter
        # delta per tick; observed only when prefill actually ran.
        obs_on = self.obs_timeline
        decode_waiting = obs_on and any(
            s.req is not None and s.pf_done < 0 for s in self.slots
        )
        t_stall0 = time.perf_counter() if decode_waiting else 0.0
        pf_tokens0 = self._prefill_tokens
        self._admit()
        if eo_on:
            t_ph = eo.phase("admit", t_ph)
        for slot in self.slots:
            if slot.req is None:
                continue
            with self._cv:
                cancelled = slot.req.req_id in self._cancelled
                self._cancelled.discard(slot.req.req_id)
            if cancelled:  # mid-prefill or between chunks
                self._finish(slot, reason="cancelled")
        for slot in self.slots:
            if slot.req is not None and slot.pf_done >= 0:
                self._prefill_step(slot)  # interleaves with decode below
        if decode_waiting and self._prefill_tokens > pf_tokens0:
            global_metrics().observe(
                "continuous.prefill_stall_s",
                time.perf_counter() - t_stall0,
            )
        if eo_on:
            eo.phase("prefill", t_ph)
        active = [
            s for s in self.slots
            if s.req is not None and s.pf_done < 0
        ]
        # Gauges refresh BEFORE the idle early-return, or an empty
        # batcher would scrape its last busy tick's values forever.
        # active_slots means OCCUPANCY (request held), matching
        # stats()["active"]; the prefilling subset gets its own gauge —
        # a device busy with chunk passes must not scrape as idle.
        global_metrics().set_gauge(
            "continuous.active_slots",
            sum(1 for s in self.slots if s.req is not None),
        )
        global_metrics().set_gauge(
            "continuous.prefilling_slots",
            sum(1 for s in self.slots
                if s.req is not None and s.pf_done >= 0),
        )
        global_metrics().set_gauge("continuous.queue_depth", len(self._queue))
        if self._sched is not None:
            # Per-tenant queue-depth gauges — bounded cardinality: the
            # queue retains at most _MAX_TENANTS drained tenants (so
            # recent ones read 0 instead of going stale), and gauges
            # for tenants it evicted are removed here in step.
            with self._cv:
                depths = self._queue.depths()
            for tenant in self._gauged_tenants - depths.keys():
                global_metrics().remove_gauge(
                    f"scheduler.queue_depth.{tenant}"
                )
            for tenant, depth in depths.items():
                global_metrics().set_gauge(
                    f"scheduler.queue_depth.{tenant}", float(depth)
                )
            self._gauged_tenants = set(depths)
        # Bridge PR-1's fused-staging counter to /metrics: transfers are
        # cumulative, so dashboards derive the steady-state rate (the
        # contract: flat between admissions).
        global_metrics().set_gauge(
            "continuous.h2d_transfers", float(self._h2d_count)
        )
        if not active:
            if self.obs_timeline:
                # Idle ticks still flush (first-token commits from an
                # admission whose request finished in one step, goodput
                # decay toward zero).
                self._obs_flush()
            self._sentinel.sample(write_gauges=False)
            return None
        tracer = global_tracer()
        if self._spec is not None:
            fl = self._spec_decode(active, tracer)
        else:
            t_ph = eo.now() if eo_on else 0.0
            # The whole per-slot staging block the old path rebuilt and
            # transferred here every tick (tokens/pos/keys/temps/top_ks/
            # top_ps/greedy — O(slots x fields) jnp.asarray calls) is
            # GONE: the state already lives on device (_dstate, staged
            # once per admission), so a steady-state tick stages zero
            # host scalars and the paged table re-uploads only when it
            # changed.
            truncate = any(s.req.top_k < self.lm.vocab for s in active)
            nucleus = any(s.req.top_p < 1.0 for s in active)
            self._variants.setdefault("continuous.step_chunk", set()).add(
                (truncate, nucleus)
            )
            t_chunk = tracer.now() if tracer.enabled else 0.0
            toks, lps, self._caches, self._dstate = self._step_chunk(
                self.variables,
                self._caches,
                self._dstate,
                self._current_table() if self._paged else None,
                truncate=truncate,
                nucleus=nucleus,
                epoch=self._mesh_epoch,
            )
            with self._cv:
                self._ticks += 1
            global_metrics().inc("continuous.ticks")
            # The chunk's ONE host fetch covers both arrays — started
            # here (async), landed at commit.
            fl = _InFlight(
                fetch=_AsyncFetch((toks, lps)),
                reqs=[],
                lives=[],
                t_span=t_chunk,
                t_eo=t_ph,
            )
        # Binding identity for every slot in the decode batch: commit
        # applies a slot's column only while it still holds the same
        # request object AND the same life (slot.tokens list identity —
        # see _InFlight). Captured AFTER the dispatch so a prefill-
        # finishing slot that joined `active` this tick is included.
        fl.reqs = [
            s.req if (s.req is not None and s.pf_done < 0) else None
            for s in self.slots
        ]
        fl.lives = [
            s.tokens if fl.reqs[i] is not None else None
            for i, s in enumerate(self.slots)
        ]
        fl.n_active = len(active)
        fl.t0 = t0
        if eo_on:
            # Total host-side cost of this dispatch half — what the
            # pipelined loop overlaps with the device wall.
            eo.phase("dispatch", t0, span=False)
        fl.t_dispatched = time.perf_counter()
        return fl

    def _tick_commit(self, fl: "_InFlight") -> int:
        """Commit half of one tick: land ``fl``'s async fetch, close
        the decode/verify spans it opened, apply per-slot token
        commits (skipping slots whose binding changed since dispatch —
        their columns are a bounded garbage tail nobody reads), then
        window recycling, the telemetry flush, and the compile-
        sentinel sample. Runs in the same :meth:`tick` call at depth
        1; one tick later at depth 2."""
        eo = self._eobs
        eo_on = eo.enabled
        if eo_on and fl.t_dispatched:
            # Dispatch-end -> commit-entry: ~0 at depth 1; the NEXT
            # tick's dispatch wall at depth 2 (the lag the stream
            # timing docs describe).
            eo.phase("commit_lag", fl.t_dispatched, span=False)
        host = fl.fetch.commit()
        tracer = global_tracer()
        if fl.spec is None:
            toks, lps = host
            limits = np.full((toks.shape[1],), self.chunk, np.int64)
            if tracer.enabled and fl.t_span:
                # Dispatch -> results-landed of one compiled decode
                # chunk — the Perfetto row that shows tick cadence and
                # chunk cost (overlaps other rows under the pipelined
                # loop).
                tracer.add_span(
                    "batcher.decode_chunk",
                    start=fl.t_span,
                    end=tracer.now(),
                    slots=fl.n_active,
                    chunk=self.chunk,
                )
            if eo_on and fl.t_eo:
                # span=False: batcher.decode_chunk above is already the
                # tracer row for this window.
                eo.phase("decode", fl.t_eo, span=False)
        else:
            toks, lps, acc = host
            d, w, active_idx = fl.spec
            if tracer.enabled and fl.t_span:
                tracer.add_span(
                    "decode.verify",
                    start=fl.t_span,
                    end=tracer.now(),
                    slots=len(active_idx),
                    draft_k=d,
                    requests=fl.req_ids,
                )
            if eo_on and fl.t_eo:
                # Ends when the round's ONE fused fetch lands
                # (decode.verify is the tracer row for the same
                # window).
                eo.phase("verify", fl.t_eo, span=False)
            # Acceptance accounting: drafted/accepted proposals for
            # the rows ACTIVE at dispatch only (idle rows verify
            # garbage nobody commits). Both counters move under _cv so
            # a concurrent stats() snapshot cannot tear across them
            # (the ADVICE-r4 rule the other lifetime counters follow).
            # (d, w) come from the dispatch snapshot — set_draft_k
            # mid-lag must not misattribute the round.
            acc_counts = [int(acc[i]) for i in active_idx]
            with self._cv:
                # Tree rounds draft d chain proposals + w leaf
                # candidates per slot (acc counts a leaf hit as one
                # more accepted).
                self._spec_drafted += (d + w) * len(active_idx)
                self._spec_accepted += sum(acc_counts)
                ratio = (
                    self._spec_accepted / self._spec_drafted
                    if self._spec_drafted
                    else 0.0
                )
            global_metrics().set_gauge("continuous.spec_acceptance", ratio)
            if self.obs_timeline:
                # One histogram sample per active slot per tick (one
                # registry-lock hold, like the ITL flush).
                global_metrics().observe_many(
                    "continuous.spec_accepted_per_tick",
                    [float(a) for a in acc_counts],
                )
            limits = np.asarray(acc, np.int64) + 1
        if fl.t0:
            # Overlap gauge: the fraction of the dispatch->results
            # wall the host did NOT spend blocked on the fetch. ~0 for
            # a device-bound synchronous loop; -> 1 when the pipelined
            # loop hides the device wall behind the next dispatch.
            wall = time.perf_counter() - fl.t0
            if wall > 0:
                global_metrics().set_gauge(
                    "runtime.overlap_ratio",
                    max(0.0, 1.0 - fl.fetch.wait_s / wall),
                )
        t_ph = eo.now() if eo_on else 0.0
        for i, slot in enumerate(self.slots):
            req = fl.reqs[i]
            if req is None:
                continue
            if (
                slot.req is not req
                or slot.tokens is not fl.lives[i]
                or slot.pf_done >= 0
            ):
                # The binding moved since dispatch (retire + re-admit,
                # preempt + replay — possible only under the one-tick
                # lag): this column belongs to a dead life. Drop it.
                continue
            # limits[i] is the slot's committable token count this tick:
            # the full chunk in lockstep mode, the accepted prefix + 1
            # correction token in speculative mode (rows desynchronize).
            for j in range(int(limits[i])):
                self._commit(slot, int(toks[j, i]), float(lps[j, i]))
                if slot.req is not req:  # finished (steps or EOS)
                    break
            if slot.req is req:
                # pos invariant at tick entry: the next step consumes
                # last_token (stream index emitted-1) at s0 + emitted - 1.
                slot.pos = slot.s0 + slot.emitted - 1
        if eo_on:
            t_ph = eo.phase("commit", t_ph)
        if self._paged and self._window is not None:
            # Rolling-window recycling: pages wholly behind every future
            # read ((o+1)*P <= pos - window + 1 — reads from here on
            # mask positions < index - window + 1 and writes land at
            # >= pos) go back to the pool MID-REQUEST, so pool pressure
            # bounds by the window, not the sequence.
            for slot in self.slots:
                if slot.req is None or slot.pf_done >= 0:
                    continue
                dead = max(
                    0, slot.pos - self._window + 1
                ) // self._page - self._pager.base(slot.idx)
                if dead > 0:
                    self._pager.release_prefix(slot.idx, dead)
        # Flush the tick's timeline/SLO bookkeeping in O(1) registry
        # lock acquisitions (not one per committed token): batched ITL
        # samples, SLO attainment counters/gauges, goodput counters +
        # windowed rate gauge.
        if self.obs_timeline:
            self._obs_flush()
        # Post-commit occupancy: slots retired by this chunk are gone.
        global_metrics().set_gauge(
            "continuous.active_slots",
            sum(1 for sl in self.slots if sl.req is not None),
        )
        if eo_on:
            # "update" = post-commit bookkeeping: window recycling, the
            # batched ITL flush, occupancy gauges.
            eo.phase("update", t_ph)
        self._sentinel.sample(write_gauges=False)
        return fl.n_active

    def capacity_book(self) -> dict | None:
        """The capacity plane's last rebuilt book (None when the plane
        is disabled). JSON-safe — the exact object telemetry providers
        and lease meta advertise; its ``wall`` stamp lets any consumer
        age it. Before the first ``_obs_flush`` rebuild this is the
        constructor's empty-headroom book, still well-formed."""
        if self._capacity is None:
            return None
        return self._capacity.book()

    def stats(self) -> dict:
        """Serving observability snapshot: slot occupancy, queue depth,
        and THIS batcher's lifetime admit/complete/tick counts
        (instance-scoped — mirror counters also land in
        ``utils.metrics.global_metrics`` for process-level scraping)."""
        # Snapshot under _cv so the counts are mutually consistent even
        # when the server thread is mid-tick (ADVICE r4 — unlocked reads
        # were benign under the GIL but could tear across fields).
        with self._cv:
            out = {
                "slots": len(self.slots),
                "active": sum(1 for s in self.slots if s.req is not None),
                "queued": len(self._queue),
                "finished_unclaimed": len(self._done),
                "admitted": self._admitted,
                "completed": self._completed,
                "ticks": self._ticks,
                # Tick-runtime shape (config.RuntimeConfig): depth 1 =
                # synchronous dispatch+commit; depth 2 = one tick in
                # flight between calls (inflight reports whether one is
                # pending right now).
                "pipeline_depth": self._depth,
                "inflight": self._inflight is not None,
                # Prompt positions prefilled IN-TICK by this batcher
                # (full/suffix/chunk passes; prefix-cache hits and
                # disaggregated handoffs excluded) — pair with the
                # committed-token counters for a prefill/decode
                # tokens-per-second split.
                "prefill_tokens": self._prefill_tokens,
                # Host->device staging transfers this batcher issued
                # (every jnp.asarray in this module funnels through
                # _h2d): the fused-staging contract is ZERO per
                # steady-state tick, O(1) per admission/retirement.
                "h2d_transfers": self._h2d_count,
                # Resident KV bytes across layouts (slot strips, int8
                # value+scale pairs, or page pools) — the capacity number
                # benches and dashboards report. cache_bytes is the
                # LOGICAL size; under tensor parallelism each device
                # holds cache_bytes_per_device == cache_bytes / tp (the
                # head axis shards), which is the number HBM planning
                # must use.
                "cache_bytes": sum(
                    x.nbytes for x in jax.tree.leaves(self._caches)
                ),
                "cache_bytes_per_device": sum(
                    device_local_nbytes(x)
                    for x in jax.tree.leaves(self._caches)
                ),
                # Quantized ÷ native-equivalent cache bytes (scale
                # planes counted): the honest capacity multiplier —
                # 1.0 for native caches, (hd + 4) / (hd * itemsize)
                # for int8 + f32-scale ones.
                "cache_bytes_ratio": sum(
                    x.nbytes for x in jax.tree.leaves(self._caches)
                ) / float(self._native_cache_bytes),
                "tp": self._tp,
                # Elastic-recovery books (instance-lifetime mirrors of
                # the recovery.* registry counters; wall_s is the most
                # recent recovery's detection->migrated span).
                "recoveries": self._recoveries,
                "recovery_migrated": self._recovery_migrated,
                "recovery_replayed": self._recovery_replayed,
                "recovery_dropped": self._recovery_dropped,
                "last_recovery_wall_s": self._last_recovery_wall_s,
                # SLO attainment books (instance-lifetime, flushed
                # per tick — mirrors of the slo.* registry counters).
                "slo_ttft_met": self._slo_totals["ttft_met"],
                "slo_ttft_missed": self._slo_totals["ttft_missed"],
                "slo_itl_met": self._slo_totals["itl_met"],
                "slo_itl_missed": self._slo_totals["itl_missed"],
                # Traffic-control books (mirrors of the scheduler.*
                # registry counters). "queued" above is the BOUNDED
                # admission-queue depth — it can never exceed the
                # scheduler's max_queue_depth.
                "rejected": self._rejected,
                "preempted": self._preempted,
            }
            if self._controller is not None:
                out["degradation_level"] = self._controller.level
            if self._spec is not None:
                out["spec_drafted"] = self._spec_drafted
                out["spec_accepted"] = self._spec_accepted
                out["spec_acceptance"] = (
                    self._spec_accepted / self._spec_drafted
                    if self._spec_drafted
                    else 0.0
                )
                out["draft_cache_bytes"] = sum(
                    x.nbytes
                    for x in jax.tree.leaves(self._draft_caches)
                )
            if self._paged:
                ps = self._pager.stats()
                out["pool_pages"] = ps.num_pages
                out["pages_in_use"] = ps.in_use
                out["pages_free"] = ps.free
                out["pages_cached"] = ps.cached
                out["prefix_hits"] = ps.prefix_hits
                out["prefix_misses"] = ps.prefix_misses
                out["prefix_capacity_skips"] = ps.prefix_capacity_skips
                # Radix prefix-cache books: resident token-block tree
                # size, partial-hit admissions (match stopped short of
                # the last full prompt page), token-weighted hit mass,
                # and radix-node evictions.
                out["radix_nodes"] = ps.radix_nodes
                out["radix_partial_hits"] = ps.radix_partial_hits
                out["radix_hit_tokens"] = ps.radix_hit_tokens
                out["radix_evictions"] = self._pager.radix_evictions
                # Copy-on-write fan-out books.
                out["cow_forks"] = ps.cow_forks
                out["fanout_groups"] = len(self._fanout_groups)
            if self._sp_cfg is not None:
                # Sequence-parallel prefill books: the live ring width
                # (1 = degraded to the ordinary path) and how many
                # admissions took the sp program.
                out["sp_width"] = (
                    self._sp.sp if self._sp is not None else 1
                )
                out["sp_prefills"] = self._sp_prefills
            if self._tier is not None:
                ts = self._tier.stats()
                out["host_pages"] = ts.pages
                out["host_bytes"] = ts.host_bytes
                out["tier_spilled"] = self._tier_spilled
                out["tier_readmitted"] = self._tier_readmitted
                out["tier_dropped"] = self._tier_dropped + ts.dropped
                out["tier_codec_bytes_saved"] = ts.codec_bytes_saved
        return out

    def _memory_stats(self) -> dict[str, float]:
        """Pull-style memory source for ``utils.profiling``'s engine
        collector (runs on exporter scrape threads — reads only, no
        locks, tolerant of racing a live tick). Keys are final metric
        names; the collector SUMS across live batchers:

        - dense layout: ``memory.kv_bytes`` (LOGICAL slot strip bytes,
          int8 value+scale pairs included) and
          ``memory.kv_bytes_per_device`` (the per-chip resident bytes —
          == kv_bytes / tp under a head-sharded mesh; equal otherwise);
        - paged layout: ``memory.pool_bytes`` /
          ``memory.pool_bytes_per_device`` (same logical-vs-per-chip
          split) plus page occupancy —
          ``memory.pages_used + pages_free + pages_cached ==
          memory.pool_pages`` (allocatable pool, trash page excluded) —
          and the pager's prefix-cache effectiveness counters
          (``paged.prefix_{hits,misses,capacity_skips}``);
        - speculative mode: ``memory.draft_cache_bytes`` (the draft
          replicates under TP, so its per-device bytes ARE its logical
          bytes);
        - both layouts: ``memory.kv_bytes_ratio`` — actual cache bytes
          (scale planes INCLUDED) over what the same geometry would
          cost in the native dtype. 1.0 native; ~(hd + 4)/(hd *
          itemsize) quantized — the 2-4x capacity win as a dashboard
          number.
        """
        cache_bytes = float(
            sum(x.nbytes for x in jax.tree.leaves(self._caches))
        )
        per_device = float(
            sum(
                device_local_nbytes(x)
                for x in jax.tree.leaves(self._caches)
            )
        )
        out: dict[str, float] = {}
        if self._paged:
            ps = self._pager.stats()
            out["memory.pool_bytes"] = cache_bytes
            out["memory.pool_bytes_per_device"] = per_device
            out["memory.pool_pages"] = float(self._pager.num_allocatable)
            out["memory.pages_used"] = float(ps.in_use)
            out["memory.pages_cached"] = float(ps.cached)
            # PagerStats.free counts evictable cached pages as free
            # (allocator view); the gauges partition instead.
            out["memory.pages_free"] = float(ps.free - ps.cached)
            out["paged.prefix_hits"] = float(ps.prefix_hits)
            out["paged.prefix_misses"] = float(ps.prefix_misses)
            out["paged.prefix_capacity_skips"] = float(
                ps.prefix_capacity_skips
            )
            # Radix prefix cache + copy-on-write fan-out gauges
            # (docs/OBSERVABILITY.md "Paged KV"): resident radix-tree
            # size, partial-hit admissions, token-weighted hit mass,
            # and the cumulative fork count (also an inc'd counter at
            # the fork site — the gauge makes it scrape-visible even
            # between exporter windows).
            out["paged.radix_nodes"] = float(ps.radix_nodes)
            out["paged.radix_partial_hits"] = float(ps.radix_partial_hits)
            out["paged.radix_hit_tokens"] = float(ps.radix_hit_tokens)
            out["paged.cow_forks_total"] = float(ps.cow_forks)
            if self._tier is not None:
                # Host-tier occupancy: pages_spilled counts pages
                # RESIDENT in host memory (warm + cold), host_bytes
                # their post-codec footprint. The HBM partition above
                # (used + free + cached == pool_pages) is untouched —
                # the tier is a copy below it, never double-counted.
                ts = self._tier.stats()
                out["memory.host_bytes"] = float(ts.host_bytes)
                out["memory.pages_spilled"] = float(ts.pages)
        else:
            out["memory.kv_bytes"] = cache_bytes
            out["memory.kv_bytes_per_device"] = per_device
        out["memory.kv_bytes_ratio"] = cache_bytes / float(
            self._native_cache_bytes
        )
        if self._draft_caches is not None:
            out["memory.draft_cache_bytes"] = float(
                sum(x.nbytes for x in jax.tree.leaves(self._draft_caches))
            )
        return out

    def _program_costs(self) -> dict[str, dict[str, float]]:
        """Per-execution XLA ``cost_analysis`` (flops, bytes accessed)
        of this batcher's decode-path program — ``_step_chunk`` in
        lockstep mode, ``_spec_verify`` in speculative mode — computed
        ONCE, lazily, at the first roofline scrape. Lowering uses
        ``ShapeDtypeStruct`` stand-ins (never touches live buffers —
        a scrape can race a ticking thread's donation) and never
        compiles, so the watched jit caches do not grow: pulling
        roofline numbers must not itself read as a recompile
        (sentinel-checked in tests). Failures (exotic backend, no
        analysis support) cache as empty — a scrape degrades to no
        roofline gauges, never to an error."""
        if self._roofline_costs is not None:
            return self._roofline_costs
        av = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            (self.variables, self._caches, self._dstate),
        )
        a_vars, a_caches, a_dstate = av
        a_table = (
            jax.ShapeDtypeStruct(
                (len(self.slots), self._pager.pages_per_slot), jnp.int32
            )
            if self._paged
            else None
        )
        costs: dict[str, dict[str, float]] = {}
        try:
            if self._spec is not None:
                a_dtoks = jax.ShapeDtypeStruct(
                    (self._spec_k + (2 if self._spec_w else 1),
                     len(self.slots)),
                    jnp.int32,
                )
                a_cands = (
                    jax.ShapeDtypeStruct(
                        (len(self.slots), self._spec_w), jnp.int32
                    )
                    if self._spec_w
                    else None
                )
                costs["verify"] = program_cost_analysis(
                    type(self)._spec_verify,
                    self, a_vars, a_caches, a_dstate, a_dtoks, a_table,
                    a_cands,
                    epoch=self._mesh_epoch,
                )
            else:
                costs["decode"] = program_cost_analysis(
                    type(self)._step_chunk,
                    self, a_vars, a_caches, a_dstate, a_table,
                    truncate=False, nucleus=False,
                    epoch=self._mesh_epoch,
                )
        except Exception as e:  # noqa: BLE001 — degrade, don't break scrape
            log.info("roofline cost analysis unavailable: %r", e)
        self._roofline_costs = costs
        return costs

    def _roofline_stats(self) -> dict[str, dict[str, float]]:
        """Pull-style roofline source (``utils.profiling``): static
        flops/bytes per program execution joined with the live phase
        wall times (``EngineObs.last_s`` — populated when
        ``obs_engine`` is enabled; without it the gauges carry
        flops/bytes but no utilization, same contract as an unknown
        peak)."""
        out: dict[str, dict[str, float]] = {}
        last = self._eobs.last_s
        for prog in self._program_costs():
            st = dict(self._roofline_costs[prog])
            # Program names deliberately equal their tick-phase names
            # ("decode" / "verify") — the join is a dict lookup.
            st["wall_s"] = last.get(prog)
            out[prog] = st
        return out

    def logprobs(self, req_id: int) -> np.ndarray:
        """Per-token model logprobs of a FINISHED request's stream —
        the same raw-log-softmax convention as
        ``generate(return_logprobs=True)``, recorded for every request
        (the reduction is one cheap (B, V) take per step). Claims them;
        fetch after :meth:`run` / :meth:`result`."""
        with self._cv:
            if req_id not in self._done_lps:
                raise KeyError(
                    f"no logprobs for request {req_id} "
                    "(not finished, or already claimed)"
                )
            return self._done_lps.pop(req_id)

    def run(self, max_ticks: int = 100_000) -> dict[int, np.ndarray]:
        """Tick until every submitted request completed; returns
        {req_id: (tokens,) int32} and clears the finished set. The
        synchronous driver — do not mix with :meth:`start`."""
        ticks = 0
        while self._queue or any(s.req is not None for s in self.slots):
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(f"run() exceeded {max_ticks} ticks")
        # Pipeline boundary: the loop exits when every slot RETIRED,
        # which the pipelined runtime only does at commit — so any
        # remaining in-flight tick is pure garbage tail. Drain it so
        # the next caller (or a disagg handoff) sees an empty pipeline.
        self.drain()
        done, self._done = self._done, {}
        return done

    # -- threaded serving --------------------------------------------------

    def start(self) -> "ContinuousBatcher":
        """Serve on a background thread: callers :meth:`submit` from any
        thread and block on :meth:`result`. All compiled work runs on
        the server thread; the condition variable only guards the
        queue/done handoff."""
        with self._cv:
            if self._server is not None:
                raise RuntimeError("batcher already started")
            self._stopping = False
            # Reserve the slot under the lock so a concurrent start()
            # cannot also pass the guard; the thread object replaces
            # the placeholder below.
            self._server = threading.current_thread()  # placeholder

        def loop():
            while True:
                with self._cv:
                    while (
                        not self._stopping
                        and not self._queue
                        and all(s.req is None for s in self.slots)
                    ):
                        self._cv.wait(timeout=0.1)
                    if self._stopping:
                        break
                try:
                    self.tick()
                except BaseException as e:  # noqa: BLE001 — re-raised
                    # A tick exception (e.g. from a user's on_token
                    # callback) must not strand result() waiters in a
                    # silent 300s timeout: stash it, stop, wake them —
                    # they re-raise it with provenance.
                    with self._cv:
                        self._server_error = e
                        self._stopping = True
                        self._cv.notify_all()
                    log.error("server tick failed: %r", e)
                    return
                with self._cv:
                    self._cv.notify_all()  # results may have landed
            # Stopping: drain the pipelined runtime's in-flight tick ON
            # THE TICKING THREAD — stop() runs on the caller's thread
            # and must not touch device state — so the last dispatched
            # results commit before the thread exits and result()
            # waiters wake to them.
            try:
                self.drain()
            except BaseException as e:  # noqa: BLE001 — re-raised
                with self._cv:
                    self._server_error = e
                log.error("drain on stop failed: %r", e)
            with self._cv:
                self._cv.notify_all()

        server = threading.Thread(
            target=loop, name="continuous-batcher", daemon=True
        )
        with self._cv:
            self._server = server
        server.start()
        return self

    def stop(self) -> None:
        with self._cv:
            server = self._server
            if server is None:
                return
            self._stopping = True
            self._cv.notify_all()
        server.join(timeout=30.0)
        if server.is_alive():
            # A tick stuck in a long compile/stall: forgetting the
            # thread here would let a later start() run TWO tickers over
            # the same donated caches. Keep it registered and fail loud.
            raise RuntimeError(
                "batcher server thread did not stop within 30s "
                "(stuck tick?); retry stop()"
            )
        with self._cv:
            self._server = None

    def __enter__(self) -> "ContinuousBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def close(self) -> None:
        """Retire this batcher from the engine telemetry: drop it from
        the ``memory.*`` gauge sums and the shared prefill compile
        watch. Needed because the jit caches pin ``self`` (static
        argnum), so GC alone never removes a replaced batcher — without
        close(), an operator swapping in a new batcher sees both
        instances' bytes summed (a phantom leak). Idempotent; call
        after :meth:`stop` when the batcher is permanently done."""
        unregister_memory_source("continuous", self)
        unregister_roofline_source("continuous", self)
        _LIVE_BATCHERS.discard(self)
        self._inflight = None  # drop any undrained device references
        if self._sp is not None:
            self._sp.close()
            self._sp = None
        self._retired = True  # stop consuming membership events
        # Revoke this batcher's unconsumed recovery allowances: the
        # class-level watches outlive it, and leftover slack (a family
        # recovery expected to re-lower but traffic never exercised)
        # would silently absorb ANOTHER live batcher's real phantom
        # variant. Consumed units are already gone, so disarming the
        # full grant strips exactly the leftovers.
        for prog, n in self._granted.items():
            self._sentinel.disarm(prog, n)
        self._granted.clear()

    def result(self, req_id: int, timeout: float = 300.0) -> np.ndarray:
        """Block until ``req_id`` finishes (requires :meth:`start`);
        returns and claims its tokens."""
        with self._cv:
            if not self._cv.wait_for(
                lambda: req_id in self._done or self._stopping,
                timeout=timeout,
            ):
                raise TimeoutError(
                    f"request {req_id} not done within {timeout}s"
                )
            if req_id not in self._done:
                if self._server_error is not None:
                    raise RuntimeError(
                        "batcher server thread died mid-tick"
                    ) from self._server_error
                raise RuntimeError("batcher stopped before completion")
            return self._done.pop(req_id)
