"""Replica capacity, TTFT-forecast, and prefix-affinity signal plane.

ROADMAP item 2's router tier places requests across N decode replicas
by prefix-cache affinity and closes the loop with autoscaling — but
placement needs SIGNALS: today the only affinity probe is
``prefix_cached(prompt)`` (a full-prompt round-trip to every replica),
there is no headroom or TTFT forecast a placement/shed decision can
read, and replica health is implicit in a dozen scattered gauges. This
module is the observability half of that item — the paper's
etcd-membership DNA (PAPER.md §0) promoted from "is the worker alive"
to "what can this replica serve, how fast, and how hot is my prefix
there":

- **Headroom book** — free slots, free + cached (evictable) pages,
  admission-queue depth vs bound, per-tenant queue pressure, and the
  current degradation rung, in one JSON-safe dict a router reads at
  placement time.
- **TTFT forecaster** — :meth:`CapacityModel.forecast_ttft` combines
  an EWMA of measured queue wait, per-pow2-bucket prefill walls
  (learned from the suffix tokens each admission actually computes —
  a prefix-cache hit shrinks the bucket, exactly as it shrinks the
  wall), and the windowed decode-tick gap, under a multiplicative
  bias corrector. **Self-calibration**: every admission's realized
  TTFT is compared against the forecast made at submit; the absolute
  error feeds the ``capacity.ttft_forecast_abs_err_s`` histogram, the
  within-2x fraction the ``capacity.forecast_calibration`` gauge, and
  the realized/forecast ratio nudges the bias corrector — a
  systematically wrong forecaster converges instead of staying wrong.
- **Prefix-affinity sketch** — the top-K radix nodes by token-weighted
  heat (``Pager.radix_sketch``), shipped as HASHED content keys
  (blake2b digests: bounded bytes, and raw prompt tokens never ride
  the control plane). :func:`affinity_score` is static — a router
  scores "replica A holds 900 of my 1000 tokens" from the sketch
  alone, no prompt round-trip to any replica.
- **Health score** — ``ok | degraded | critical`` with dwell
  hysteresis (worsening applies immediately; an improvement must hold
  ``health_dwell_s`` before the score follows), derived from existing
  signals: degradation-ladder rung, recovery-in-progress, unexpected
  recompiles, windowed TTFT attainment, admission-queue saturation.
  Emitted as the ``capacity.health`` gauge plus ``health_transition``
  flight events.

Books ride two existing paths: the ``TelemetryReporter`` →
``FederatedStore`` wire (reports carry a ``capacity`` section; the
exporter serves the merged view at ``GET /fleet/capacity``) and the
``WorkerRegistry`` lease meta (``meta["capacity"]``, rate-limited
refresh — the disaggregated prefill tier's path). Everything here is
host-side Python fed through the batcher's ``_obs_flush`` seam: the
0-h2d steady tick and the frozen two-program compile footprint are
untouched (sentinel-pinned; the capacity arm of
``benchmarks/micro/obs_overhead.py`` measures the enabled cost against
the <5% budget).
"""

from __future__ import annotations

import collections
import hashlib
import time

import numpy as np

from adapt_tpu.config import CapacityConfig
from adapt_tpu.runtime.scheduler import DegradationController
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.tracing import global_flight_recorder

#: Book schema version (a router must reject books from a newer peer
#: loudly, not half-parse them — same stance as telemetry.REPORT_V).
BOOK_V = 1

#: Health levels, gauge encoding and wire names. Order IS severity.
HEALTH_NAMES = ("ok", "degraded", "critical")

#: Sketch-entry hash: blake2b-8 of the radix node's content key. 8
#: bytes keeps a book small at sketch_k entries while a cross-replica
#: collision stays ~2^-64 per pair — a wrong AFFINITY score on
#: collision costs one suboptimal placement, never correctness.
_DIGEST_SIZE = 8


def _key_hash(key: bytes) -> str:
    return hashlib.blake2b(key, digest_size=_DIGEST_SIZE).hexdigest()


def _pow2_bucket(n: int) -> int:
    """Smallest power of two >= max(1, n) — the forecaster's prefill
    wall buckets, mirroring the batcher's pow2 prompt buckets (walls
    are a property of the padded bucket a prefill actually runs at,
    not the raw token count)."""
    b = 1
    n = max(1, int(n))
    while b < n:
        b *= 2
    return b


class TTFTForecaster:
    """EWMA-learned TTFT estimate with online self-calibration.

    ``forecast = bias * (queue_wait + prefill_wall(bucket) + tick_gap)``

    where every term is an EWMA of measured walls and ``bias`` is a
    multiplicative corrector updated from realized/forecast ratios
    (log-free power update, clamped), so structural costs the additive
    model misses — chunked prefill spreading over ticks, pipelined
    commit lag, queue depth the wait EWMA lags — are absorbed instead
    of becoming permanent error."""

    def __init__(self, alpha: float = 0.2, window: int = 256):
        self._a = float(alpha)
        self._queue_wait: float | None = None
        #: pow2 suffix bucket -> EWMA prefill wall seconds.
        self._walls: dict[int, float] = {}
        #: EWMA seconds per prefilled position (the cold-bucket
        #: fallback before any wall lands in a bucket).
        self._per_token: float | None = None
        #: EWMA gap between an admission's prefill end and its first
        #: committed token (decode dispatch + commit latency).
        self._tick_gap: float | None = None
        self._bias = 1.0
        #: Rolling within-2x verdicts (the calibration fraction).
        self._within: collections.deque[bool] = collections.deque(
            maxlen=max(1, int(window))
        )
        self._samples = 0

    # -- feeds (O(1); admission / commit sites) -------------------------

    def _ewma(self, old: float | None, v: float) -> float:
        """Fast-down, slow-up: a sample 4x UNDER the EWMA snaps the
        estimate to it instead of decaying there over dozens of
        admissions. Queue waits and prefill walls are floor-like —
        their outliers are structural one-offs that only inflate
        (warmup admissions measure jit compiles through the same host
        sync as real walls) — so the steady-state value is the floor
        and an inflated estimate should not take 1/alpha admissions
        to forget."""
        if old is None:
            return v
        if v < old / 4:
            return v
        return old + self._a * (v - old)

    def observe_queue_wait(self, s: float) -> None:
        self._queue_wait = self._ewma(self._queue_wait, max(0.0, s))

    def observe_prefill(self, tokens: int, wall_s: float) -> None:
        """One admission's in-tick prefill: ``tokens`` positions
        actually computed (the suffix past any prefix-cache hit) took
        ``wall_s``."""
        if tokens <= 0 or wall_s < 0:
            return
        b = _pow2_bucket(tokens)
        self._walls[b] = self._ewma(self._walls.get(b), wall_s)
        self._per_token = self._ewma(self._per_token, wall_s / tokens)

    def observe_tick_gap(self, s: float) -> None:
        self._tick_gap = self._ewma(self._tick_gap, max(0.0, s))

    # -- forecast --------------------------------------------------------

    def _wall_for(self, suffix_tokens: int) -> float:
        if suffix_tokens <= 0:
            return 0.0
        b = _pow2_bucket(suffix_tokens)
        w = self._walls.get(b)
        if w is not None:
            return w
        if self._walls:
            # Nearest learned bucket, scaled by the token ratio — a
            # coarse interpolation beats pretending an unseen bucket
            # costs nothing.
            near = min(self._walls, key=lambda k: abs(k - b))
            return self._walls[near] * (b / near)
        if self._per_token is not None:
            return self._per_token * suffix_tokens
        return 0.0

    def forecast(
        self, prompt_len: int, prefix_hit_tokens: int = 0
    ) -> float:
        """Seconds from submit to first committed token. Returns 0.0
        when NOTHING has been learned yet (a cold replica honestly has
        no estimate; callers treat 0 as "no forecast" and such
        admissions never enter the calibration books)."""
        suffix = max(0, int(prompt_len) - int(prefix_hit_tokens))
        raw = (
            (self._queue_wait or 0.0)
            + self._wall_for(suffix)
            + (self._tick_gap or 0.0)
        )
        return self._bias * raw if raw > 0 else 0.0

    # -- self-calibration ------------------------------------------------

    def record_realized(self, forecast_s: float, realized_s: float) -> bool:
        """Fold one (submit-time forecast, realized TTFT) pair in;
        returns the within-2x verdict. The bias corrector moves
        toward the realized/forecast ratio (clamped: one outlier tick
        must not swing every later forecast 10x)."""
        if forecast_s <= 0 or realized_s <= 0:
            return False
        ratio = realized_s / forecast_s
        within = 0.5 <= ratio <= 2.0
        self._within.append(within)
        self._samples += 1
        step = min(4.0, max(0.25, ratio)) ** self._a
        self._bias = min(8.0, max(0.125, self._bias * step))
        return within

    def calibration(self) -> float:
        """Fraction of the rolling window's forecasts within 2x of
        realized (1.0 when no samples yet — an unmeasured forecaster
        is unproven, not failing; the gauge only becomes meaningful
        with samples, which the book reports alongside)."""
        if not self._within:
            return 1.0
        return sum(self._within) / len(self._within)

    def reset_calibration(self) -> None:
        """Drop the rolling verdict window (learned walls and bias
        survive) — the train-then-measure seam load drivers use."""
        self._within.clear()

    def snapshot(self) -> dict:
        return {
            "queue_wait_s": round(self._queue_wait or 0.0, 6),
            "tick_gap_s": round(self._tick_gap or 0.0, 6),
            "bias": round(self._bias, 4),
            "calibration": round(self.calibration(), 4),
            "samples": self._samples,
            "walls": {
                str(b): round(w, 6)
                for b, w in sorted(self._walls.items())
            },
        }


def sketch_from_pager(pager, k: int) -> dict:
    """The bounded prefix-affinity sketch: ``pager``'s top-``k`` radix
    nodes by token-weighted heat, content keys hashed. Entries carry
    the node's page depth, covered tokens, and lifetime hit heat —
    everything :func:`affinity_score` needs, nothing else leaves the
    replica."""
    page_tokens = int(getattr(pager, "page_tokens", 0) or 0)
    entries = []
    if page_tokens:
        for key, depth, hits in pager.radix_sketch(k):
            entries.append(
                {
                    "h": _key_hash(key),
                    "d": int(depth),
                    "t": int(depth) * page_tokens,
                    "heat": int(hits),
                }
            )
    return {"v": BOOK_V, "page_tokens": page_tokens, "entries": entries}


def affinity_score(sketch: dict, prompt) -> float:
    """Score ``prompt``'s affinity for the replica that shipped
    ``sketch`` — STATIC: hashes the prompt's page prefixes locally and
    intersects with the sketch's hashed keys, no replica round-trip.

    Returns the deepest matched prefix in TOKENS plus a sub-token heat
    tiebreak (two replicas holding the same depth rank by how hot the
    matched path runs there). 0.0 = cold. The walk mirrors the
    admission probe: the page holding the last prompt token is never
    shareable, so the scan caps at ``(len - 1) // page_tokens``."""
    if not isinstance(sketch, dict) or int(sketch.get("v", -1)) != BOOK_V:
        return 0.0
    page_tokens = int(sketch.get("page_tokens", 0) or 0)
    entries = sketch.get("entries") or ()
    if not page_tokens or not entries:
        return 0.0
    by_hash = {e["h"]: e for e in entries if "h" in e}
    tokens = np.ascontiguousarray(np.asarray(prompt, np.int32).reshape(-1))
    raw = tokens.tobytes()
    step = 4 * page_tokens
    best_tokens, heat = 0, 0
    # No break on a miss: the sketch is top-K, so a hot deep node can
    # survive while its (resident) ancestor was squeezed out — the
    # deepest HASH PRESENT is still evidence of that resident path.
    for j in range((tokens.shape[0] - 1) // page_tokens):
        e = by_hash.get(_key_hash(raw[: (j + 1) * step]))
        if e is not None:
            best_tokens = (j + 1) * page_tokens
            heat += int(e.get("heat", 0))
    if not best_tokens:
        return 0.0
    return float(best_tokens) + min(float(heat), 999.0) * 1e-3


def forecast_from_snapshot(
    snap: dict, prompt_len: int, prefix_hit_tokens: int = 0
) -> float:
    """Router-side TTFT forecast from a SHIPPED book's ``forecast``
    section (:meth:`TTFTForecaster.snapshot`) — the static sibling of
    :func:`affinity_score`: the same bucket-walk
    :meth:`TTFTForecaster.forecast` runs replica-side, replayed from
    the wire snapshot with no replica round-trip. Returns seconds;
    0.0 = the replica has learned nothing yet (callers fall back to
    headroom — least-loaded — exactly as a cold replica deserves)."""
    if not isinstance(snap, dict) or not snap:
        return 0.0
    suffix = max(0, int(prompt_len) - int(prefix_hit_tokens))
    wall = 0.0
    walls = snap.get("walls") or {}
    if suffix > 0 and walls:
        by_bucket = {int(k): float(v) for k, v in walls.items()}
        b = _pow2_bucket(suffix)
        w = by_bucket.get(b)
        if w is None:
            # Nearest learned bucket scaled by the token ratio — the
            # forecaster's own coarse interpolation, mirrored.
            near = min(by_bucket, key=lambda k: abs(k - b))
            w = by_bucket[near] * (b / near)
        wall = w
    raw = (
        float(snap.get("queue_wait_s") or 0.0)
        + wall
        + float(snap.get("tick_gap_s") or 0.0)
    )
    bias = float(snap.get("bias") or 1.0)
    return bias * raw if raw > 0 else 0.0


class HealthScore:
    """``ok | degraded | critical`` with dwell hysteresis.

    Worsening applies IMMEDIATELY (a router must back off fast);
    improvement must hold ``dwell_s`` before the published level
    follows (flapping signals — a degradation controller oscillating
    around its threshold — must not make placement oscillate with
    them). Every published change records a ``health_transition``
    flight event."""

    def __init__(self, dwell_s: float = 1.0):
        self._dwell = float(dwell_s)
        self.level = 0
        #: (candidate better level, since-monotonic) — pending
        #: improvement being dwelled on.
        self._pending: tuple[int, float] | None = None

    def update(self, target: int, now: float | None = None) -> int:
        now = time.monotonic() if now is None else now
        target = max(0, min(len(HEALTH_NAMES) - 1, int(target)))
        if target >= self.level:
            self._pending = None
            if target > self.level:
                self._transition(target)
            return self.level
        if self._pending is None or self._pending[0] != target:
            self._pending = (target, now)
        if now - self._pending[1] >= self._dwell:
            self._pending = None
            self._transition(target)
        return self.level

    def _transition(self, to: int) -> None:
        global_flight_recorder().record(
            "health_transition",
            from_level=HEALTH_NAMES[self.level],
            to_level=HEALTH_NAMES[to],
        )
        self.level = to

    @property
    def name(self) -> str:
        return HEALTH_NAMES[self.level]


class CapacityModel:
    """The self-describing replica: one per ``ContinuousBatcher``.

    Hot-path feeds are O(1) attribute work (submit-time forecast,
    admission EWMA observes, commit-time realized compare appending to
    a pending list); everything else — headroom/sketch/health rebuild,
    gauge + histogram flush — happens in :meth:`update`, called from
    the batcher's ``_obs_flush`` seam and rate-limited by
    ``CapacityConfig.refresh_s``. ``update`` runs on the ticking
    thread; ``forecast_ttft`` may run on client threads (submit), so
    the forecaster's feeds touch only per-field scalars (GIL-atomic
    swaps, same stance as the batcher's _slo_pending ints)."""

    def __init__(
        self,
        cfg: CapacityConfig | None = None,
        *,
        kind: str = "decode",
        window_s: float = 2.0,
    ):
        self.cfg = cfg or CapacityConfig()
        self.kind = kind
        self.window_s = float(window_s)
        self.forecaster = TTFTForecaster(
            alpha=self.cfg.ewma_alpha,
            window=self.cfg.calibration_window,
        )
        self.health = HealthScore(dwell_s=self.cfg.health_dwell_s)
        #: (forecast_s, realized_s) pairs committed since the last
        #: update() — folded into the calibration books and the
        #: abs-err histogram there (ticking thread only: appended at
        #: commit, drained at flush).
        self._pending_ttft: list[tuple[float, float]] = []
        self._book: dict = {
            "v": BOOK_V,
            "kind": kind,
            "wall": time.time(),
            "health": self.health.name,
            "health_level": 0,
            "headroom": {},
            "forecast": self.forecaster.snapshot(),
            "sketch": {"v": BOOK_V, "page_tokens": 0, "entries": []},
        }
        self._last_refresh = 0.0
        #: Compile-sentinel event count at the last refresh (health
        #: reads the DELTA: a recompile long ago is not a reason to
        #: stay degraded forever).
        self._compile_seen: int | None = None
        self._recent_recompile = False
        #: SLO totals at the last refresh (windowed attainment reads
        #: the delta, same stance as DegradationController).
        self._slo_seen = {"ttft_met": 0, "ttft_missed": 0}

    # -- hot-path feeds --------------------------------------------------

    def forecast_ttft(
        self, prompt_len: int, prefix_hit_tokens: int = 0
    ) -> float:
        """Submit-time TTFT forecast (seconds; 0.0 = nothing learned
        yet). Stored on the request and compared against its realized
        TTFT at first-token commit."""
        return self.forecaster.forecast(prompt_len, prefix_hit_tokens)

    def on_queue_wait(self, s: float) -> None:
        self.forecaster.observe_queue_wait(s)

    def on_prefill(self, tokens: int, wall_s: float) -> None:
        self.forecaster.observe_prefill(tokens, wall_s)

    def on_tick_gap(self, s: float) -> None:
        self.forecaster.observe_tick_gap(s)

    def on_ttft(self, forecast_s: float, realized_s: float) -> None:
        """One admission's realized TTFT against its submit-time
        forecast (commit site; cheap append — the verdict and
        histogram work happen at flush)."""
        if forecast_s > 0 and realized_s > 0:
            self._pending_ttft.append((forecast_s, realized_s))

    def reset_calibration(self) -> None:
        self._pending_ttft.clear()
        self.forecaster.reset_calibration()

    def calibration(self) -> float:
        return self.forecaster.calibration()

    # -- refresh (off the critical path) ---------------------------------

    def update(self, bat, now: float | None = None) -> bool:
        """Drain pending calibration pairs, then (rate-limited)
        rebuild the book and publish the capacity gauges. ``bat`` is
        the owning ``ContinuousBatcher``; returns True when a rebuild
        ran."""
        now = time.monotonic() if now is None else now
        reg = global_metrics()
        if self._pending_ttft:
            errs = []
            for f, r in self._pending_ttft:
                self.forecaster.record_realized(f, r)
                errs.append(abs(r - f))
            self._pending_ttft.clear()
            reg.observe_many("capacity.ttft_forecast_abs_err_s", errs)
        if now - self._last_refresh < self.cfg.refresh_s:
            return False
        self._last_refresh = now
        self.refresh_book(bat, now=now)
        book = self._book
        hr = book["headroom"]
        reg.set_gauge("capacity.health", float(self.health.level))
        reg.set_gauge(
            "capacity.forecast_calibration",
            self.forecaster.calibration(),
        )
        reg.set_gauge(
            "capacity.slots_free", float(hr.get("slots_free", 0))
        )
        reg.set_gauge(
            "capacity.pages_free", float(hr.get("pages_free", 0))
        )
        reg.set_gauge(
            "capacity.queue_frac", float(hr.get("queue_frac", 0.0))
        )
        reg.set_gauge(
            "capacity.sketch_entries",
            float(len(book["sketch"]["entries"])),
        )
        return True

    def refresh_book(self, bat, now: float | None = None) -> dict:
        """Rebuild the book from the batcher's live books (ticking
        thread; every read here is a host-side attribute or dict
        snapshot — no device work, no locks beyond the pager's
        C-speed list() snapshots)."""
        now = time.monotonic() if now is None else now
        free_slots = sum(1 for s in bat.slots if s.req is None)
        queue_len, bound, tenant_depths = bat._queue.pressure()
        queue_frac = queue_len / bound if bound > 0 else 0.0
        level = int(bat._controller.level) if bat._controller else 0
        rung = bat._controller.rung if bat._controller else ""
        headroom: dict = {
            "slots_free": free_slots,
            "slots_total": len(bat.slots),
            "queue_depth": queue_len,
            "queue_bound": bound,
            "queue_frac": round(queue_frac, 4),
            "tenants": {
                str(t): int(d) for t, d in tenant_depths.items()
            },
            "degradation_level": level,
            "degradation_rung": rung,
        }
        sketch = {"v": BOOK_V, "page_tokens": 0, "entries": []}
        if bat._pager is not None:
            ps = bat._pager.stats()
            headroom["pages_free"] = ps.free
            headroom["pages_in_use"] = ps.in_use
            headroom["pages_cached"] = ps.cached
            headroom["pages_total"] = ps.num_pages
            sketch = sketch_from_pager(bat._pager, self.cfg.sketch_k)
        # -- health target from existing signals -------------------------
        recovering = bool(bat._lost_pending)
        sentinel_events = int(bat._sentinel.events)
        if self._compile_seen is None:
            self._compile_seen = sentinel_events
        self._recent_recompile = sentinel_events > self._compile_seen
        self._compile_seen = sentinel_events
        totals = bat._slo_totals
        met = totals["ttft_met"] - self._slo_seen["ttft_met"]
        missed = totals["ttft_missed"] - self._slo_seen["ttft_missed"]
        self._slo_seen = {
            "ttft_met": totals["ttft_met"],
            "ttft_missed": totals["ttft_missed"],
        }
        attainment_low = (met + missed) >= 4 and (
            met / (met + missed) < 0.5
        )
        target = 0
        if (
            level > 0
            or self._recent_recompile
            or attainment_low
            or queue_frac >= 0.9
        ):
            target = 1
        if recovering or level >= len(DegradationController.LADDER):
            target = 2
        self.health.update(target, now=now)
        self._book = {
            "v": BOOK_V,
            "kind": self.kind,
            "wall": time.time(),
            "health": self.health.name,
            "health_level": self.health.level,
            "headroom": headroom,
            "forecast": self.forecaster.snapshot(),
            "sketch": sketch,
        }
        return self._book

    def book(self) -> dict:
        """The last rebuilt book (JSON-safe; ``wall`` is the rebuild's
        wall clock, so any consumer can age it)."""
        return self._book


def prefill_tier_book(prefill) -> dict:
    """Capacity book for a disaggregated prefill tier
    (``runtime/disagg.PrefillWorker``): queue/pool headroom from the
    tier's own stats, plus its pager's affinity sketch — the pages a
    handoff would find already resident. Rides the tier's registry
    lease (``meta["capacity"]``)."""
    st = prefill.stats()
    pool = int(st.get("pool_pages", 0))
    in_use = int(st.get("pages_in_use", 0))
    book = {
        "v": BOOK_V,
        "kind": "prefill",
        "wall": time.time(),
        "health": "ok",
        "health_level": 0,
        "headroom": {
            "queue_depth": int(st.get("queued", 0)),
            "active": int(st.get("active", 0)),
            "pages_total": pool,
            "pages_in_use": in_use,
            "pages_free": max(0, pool - in_use),
        },
        "forecast": {},
        "sketch": {"v": BOOK_V, "page_tokens": 0, "entries": []},
    }
    pager = getattr(prefill, "_pager", None)
    if pager is not None and getattr(pager, "page_tokens", None):
        book["sketch"] = sketch_from_pager(pager, CapacityConfig().sketch_k)
    return book


def stage_book(n_stages: int, backlog: int = 0) -> dict:
    """Minimal capacity book for a remote pipeline-stage worker
    (``comm/remote.RemoteStageServer``): which stages it holds and how
    deep its work backlog runs — enough for the fleet view to show the
    worker as a capacity source with first-class staleness."""
    return {
        "v": BOOK_V,
        "kind": "stage",
        "wall": time.time(),
        "health": "ok",
        "health_level": 0,
        "headroom": {"stages": int(n_stages), "backlog": int(backlog)},
        "forecast": {},
        "sketch": {"v": BOOK_V, "page_tokens": 0, "entries": []},
    }
