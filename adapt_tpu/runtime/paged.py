"""Host-side page bookkeeping for the paged KV cache.

The device side is ``ops/paged_attention`` (pools + the scalar-prefetch
kernel); this module owns the ALLOCATOR: a free list of physical pages,
per-slot page ownership, and the (slots, pages_per_slot) page table the
compiled step consumes. All of it is plain numpy/python on the serving
control path — page churn is a few integers per request, never worth a
device round trip.

Prefix caching rides on the same bookkeeping: a FULL page of prompt
K/V is immutable once written (position p's K/V depend only on tokens
[0..p], so page i is determined by tokens[0..(i+1)*page_size)), which
makes the page the natural sharing unit. Pages carry REFCOUNTS; a
finished request's registered pages drop to rc=0 but stay resident in
an LRU of evictables, and a later request whose prompt hashes to the
same content keys shares them (rc+1) instead of recomputing —
``lookup_share`` / ``register``. Allocation evicts rc=0 cached pages
only under pool pressure, oldest first.

Conventions (shared with ``ops/paged_attention``):
- page 0 is the shared TRASH page: never allocated, the target of every
  unallocated table entry and of idle slots' garbage writes. Reads of it
  are always masked; concurrent garbage writes to it are unordered and
  unread.
- a slot's table row holds its pages in logical order; entries past its
  allocation point at the trash page.

Tensor parallelism is invisible here by design: the POOLS shard on
their head axis over the mesh (``runtime/continuous``), but a page is a
page — the table, the free list, refcounts and prefix keys are logical
bookkeeping, identical on every shard, so the allocator never changes
with the mesh (``table()`` is uploaded replicated).

Quantization is equally invisible: an int8 batcher keeps TWO pools per
K/V (``(int8 values, f32 scales)`` — ``ops/paged_attention``'s
quantized layout) addressed by ONE page id space, so every allocator
decision (alloc/free/recycle/prefix-share) applies to a page's values
and its scale plane atomically — a prefix-shared page always carries
the scales its int8 payload was written with. ``insert_prefill_pages``
scatters either member (``kv`` trailing dim is head_dim for values, 1
for scale planes).

No reference analog (SURVEY.md §2.2) — serving-memory frontier.
"""

from __future__ import annotations

import collections
import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class PagerStats:
    num_pages: int  # total pool pages incl. trash
    free: int  # immediately allocatable (free list + evictable cache)
    in_use: int  # rc > 0, excl. trash
    cached: int  # rc == 0 but resident for prefix reuse
    prefix_hits: int
    prefix_misses: int
    prefix_capacity_skips: int  # resident page, but the table row was full


class Pager:
    """Free-list page allocator with refcounted prefix sharing over a
    pool of ``num_pages`` physical pages (page 0 reserved as trash) for
    ``slots`` lockstep slots whose table rows are ``pages_per_slot``
    wide."""

    def __init__(self, num_pages: int, slots: int, pages_per_slot: int):
        if num_pages < 2:
            raise ValueError(f"num_pages must be >= 2, got {num_pages}")
        if pages_per_slot < 1:
            raise ValueError(
                f"pages_per_slot must be >= 1, got {pages_per_slot}"
            )
        self.num_pages = num_pages
        self.pages_per_slot = pages_per_slot
        # Pop from the end -> low page ids hand out first (determinism
        # helps test reproducibility; no perf meaning).
        self._free = list(range(num_pages - 1, 0, -1))
        self._owned: list[list[int]] = [[] for _ in range(slots)]
        #: Rolling-window offset: how many LEADING logical ordinals of
        #: each slot have been released mid-request (sliding-window
        #: recycling). owned[0] then sits at table ordinal base[slot].
        self._base: list[int] = [0 for _ in range(slots)]
        self._rc: dict[int, int] = {}
        # Content-addressed prefix registry: key -> page, both ways.
        self._by_key: dict[bytes, int] = {}
        self._key_of: dict[int, bytes] = {}
        # rc==0 registered pages, oldest-first (eviction order).
        self._lru: collections.OrderedDict[int, None] = (
            collections.OrderedDict()
        )
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_capacity_skips = 0

    @property
    def num_allocatable(self) -> int:
        """Pages the allocator can ever hand out: the pool minus the
        reserved trash page — the denominator occupancy gauges and
        capacity planning should use (``num_pages`` counts the trash
        page too)."""
        return self.num_pages - 1

    # -- raw pages ---------------------------------------------------------

    def _take_one(self) -> int | None:
        if self._free:
            return self._free.pop()
        if self._lru:  # evict the coldest cached prefix page
            page, _ = self._lru.popitem(last=False)
            key = self._key_of.pop(page)
            del self._by_key[key]
            return page
        return None

    def can_alloc(self, n: int) -> bool:
        return len(self._free) + len(self._lru) >= n

    def alloc(self, slot: int, n: int) -> bool:
        """Grant ``n`` MORE pages to ``slot``; all-or-nothing. False if
        the pool cannot cover it even after evicting every rc=0 cached
        page (caller leaves the request queued)."""
        owned = self._owned[slot]
        if self._base[slot] + len(owned) + n > self.pages_per_slot:
            raise ValueError(
                f"slot {slot}: {self._base[slot]}+{len(owned)}+{n} pages "
                f"exceeds table width {self.pages_per_slot}"
            )
        if not self.can_alloc(n):
            return False
        for _ in range(n):
            page = self._take_one()
            self._rc[page] = 1
            owned.append(page)
        return True

    def _release_one(self, page: int) -> None:
        """Drop one claim on ``page``; at rc=0 it returns to the free
        list — unless registered as prefix cache, in which case it
        stays resident and evictable (LRU)."""
        self._rc[page] -= 1
        if self._rc[page] == 0:
            del self._rc[page]
            if page in self._key_of:
                self._lru[page] = None  # newest = last evicted
            else:
                self._free.append(page)

    def free_slot(self, slot: int) -> None:
        """Drop ``slot``'s claim on all its pages."""
        for page in reversed(self._owned[slot]):
            self._release_one(page)
        self._owned[slot] = []
        self._base[slot] = 0

    def release_prefix(self, slot: int, n: int) -> None:
        """Sliding-window recycling: release ``slot``'s first ``n``
        logical pages MID-REQUEST (they fell wholly behind the
        attention window — masked forever, written never again). Their
        table ordinals point at the trash page from here on; shared /
        registered pages follow the usual rc / LRU rules, so a released
        prompt page can still serve future prefix hits."""
        if n <= 0:
            return
        if n > len(self._owned[slot]):
            raise ValueError(
                f"slot {slot}: releasing {n} of "
                f"{len(self._owned[slot])} owned pages"
            )
        for page in self._owned[slot][:n]:
            self._release_one(page)
        self._owned[slot] = self._owned[slot][n:]
        self._base[slot] += n

    def base(self, slot: int) -> int:
        return self._base[slot]

    def owned(self, slot: int) -> list[int]:
        return list(self._owned[slot])

    def table(self) -> np.ndarray:
        """(slots, pages_per_slot) int32; unallocated (and released)
        entries -> trash page 0."""
        t = np.zeros((len(self._owned), self.pages_per_slot), np.int32)
        for i, pages in enumerate(self._owned):
            b = self._base[i]
            t[i, b: b + len(pages)] = pages
        return t

    # -- prefix sharing ----------------------------------------------------

    @staticmethod
    def prefix_key(tokens: np.ndarray, upto: int) -> bytes:
        """Content key for the page covering positions [upto-P, upto):
        the whole prompt prefix [0, upto) (K/V at position p depend on
        every earlier token, so the key must cover them all)."""
        return np.ascontiguousarray(tokens[:upto], np.int32).tobytes()

    def lookup_share(self, slot: int, key: bytes) -> int | None:
        """If ``key``'s page is resident, acquire it for ``slot``
        (rc+1, out of the eviction LRU) and return it."""
        page = self._by_key.get(key)
        if page is None:
            self.prefix_misses += 1
            return None
        # Row-capacity check mirrors alloc()'s accounting: the recycled
        # window base occupies leading ordinals even though the pages are
        # gone (ADVICE r4 — len(owned) alone silently overflowed the row
        # for any future caller sharing into a partially-recycled slot).
        if self._base[slot] + len(self._owned[slot]) + 1 > self.pages_per_slot:
            # A miss for accounting (hits+misses == probes) with its own
            # counter: the page WAS resident, the row was just full.
            self.prefix_misses += 1
            self.prefix_capacity_skips += 1
            return None
        self._lru.pop(page, None)
        self._rc[page] = self._rc.get(page, 0) + 1
        self._owned[slot].append(page)
        self.prefix_hits += 1
        return page

    def adopt_cached(self, keys: list[bytes]) -> list[tuple[int, int]]:
        """Adopt EXTERNALLY prefilled prefix pages into the cache — the
        disaggregated-serving landing path (``runtime/disagg``): for
        every key not already resident, take a pool page, register it
        under its content key and park it rc=0 in the LRU (newest), so
        the next admission whose prompt hashes to these keys shares
        them exactly like locally computed prefix pages (evictable
        under pressure by the usual rules until then). Returns
        ``[(ordinal, page)]`` for the keys actually adopted — the
        caller scatters ONLY those ordinals' K/V (already-resident keys
        dedupe against the cache; first writer won). Returns ``[]``
        with nothing taken when the pool cannot cover the new pages
        all-or-nothing (the caller falls back to a collocated
        prefill — adoption is an optimization, never a correctness
        gate)."""
        fresh = [
            (i, k) for i, k in enumerate(keys) if k not in self._by_key
        ]
        if not fresh or not self.can_alloc(len(fresh)):
            return []
        out = []
        for i, key in fresh:
            page = self._take_one()
            self._by_key[key] = page
            self._key_of[page] = key
            self._lru[page] = None  # rc=0, resident, newest
            out.append((i, page))
        return out

    def evict_cached(self, n: int | None = None) -> int:
        """Evict up to ``n`` (default: all) COLD prefix-cache pages —
        rc=0 LRU residents, oldest first — back to the free list,
        dropping their content keys. The degradation ladder's sweep
        rung (``runtime/scheduler``): capacity-NEUTRAL by construction
        (``can_alloc`` already counts the LRU and ``alloc`` evicts on
        demand), it trades the cache's speculative prefix-hit value
        for the allocator's free-list fast path under overload. Live
        (rc>0) pages are untouched; the pool partition (used + free +
        cached) is conserved. Returns the count evicted."""
        evicted = 0
        while self._lru and (n is None or evicted < n):
            page, _ = self._lru.popitem(last=False)
            key = self._key_of.pop(page)
            del self._by_key[key]
            self._free.append(page)
            evicted += 1
        return evicted

    def register(self, page: int, key: bytes) -> None:
        """Publish ``page`` (currently owned, rc>=1) as the cache entry
        for ``key``. First writer wins; a page may carry one key."""
        if key in self._by_key or page in self._key_of:
            return
        self._by_key[key] = page
        self._key_of[page] = key

    def stats(self) -> PagerStats:
        # list(...) snapshots the dict at C speed: stats() is now also
        # read from exporter scrape threads (the memory collector in
        # utils.profiling) while the ticking thread mutates _rc, and a
        # generator over live .values() could raise "dict changed size
        # during iteration" mid-scrape.
        return PagerStats(
            num_pages=self.num_pages,
            free=len(self._free) + len(self._lru),
            in_use=sum(1 for r in list(self._rc.values()) if r > 0),
            cached=len(self._lru),
            prefix_hits=self.prefix_hits,
            prefix_misses=self.prefix_misses,
            prefix_capacity_skips=self.prefix_capacity_skips,
        )


@partial(jax.jit, donate_argnums=(0,))
def insert_prefill_pages(pool, pages, kv):
    """Scatter a prefilled request's contiguous (1, kv_h, S, hd) K or V
    into its physical ``pages`` ((n,) int32, logical order). S pads up
    to n*page positions — pad columns hold zeros that sit beyond the
    prompt (masked until decode overwrites them). One scatter on the
    page axis; jit specializes per (n, S), both bucket-bounded."""
    n = pages.shape[0]
    _, kvh, page, hd = pool.shape
    s = kv.shape[2]
    kvp = jnp.pad(kv[0], ((0, 0), (0, n * page - s), (0, 0)))
    kvp = jnp.swapaxes(kvp.reshape(kvh, n, page, hd), 0, 1)
    return pool.at[pages].set(kvp.astype(pool.dtype))
