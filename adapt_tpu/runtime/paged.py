"""Host-side page bookkeeping for the paged KV cache.

The device side is ``ops/paged_attention`` (pools + the scalar-prefetch
kernel); this module owns the ALLOCATOR: a free list of physical pages,
per-slot page ownership, and the (slots, pages_per_slot) page table the
compiled step consumes. All of it is plain numpy/python on the serving
control path — page churn is a few integers per request, never worth a
device round trip.

Prefix caching rides on the same bookkeeping: a FULL page of prompt
K/V is immutable once written (position p's K/V depend only on tokens
[0..p], so page i is determined by tokens[0..(i+1)*page_size)), which
makes the page the natural sharing unit. Pages carry REFCOUNTS; a
finished request's registered pages drop to rc=0 but stay resident in
an LRU of evictables, and a later request whose prompt hashes to the
same content keys shares them (rc+1) instead of recomputing —
``lookup_share`` / ``register``. Allocation evicts rc=0 cached pages
only under pool pressure, oldest first.

The registry doubles as a RADIX TREE over token blocks: every content
key IS a root-to-node path (the key for page j is the byte string of
tokens [0, (j+1)*P), so a key's parent is itself minus one page of
tokens), which means the flat ``key -> page`` dict already encodes the
trie — what ``_radix`` adds is the per-node metadata (block depth, hit
heat) and the token-level accounting that makes PARTIAL matches
first-class: an admission walks the deepest resident path and prefills
only the suffix past it (a 900-token match on a 1000-token prompt
recomputes 100 tokens), ``radix_probe`` scores a queued prompt's
resident prefix without touching the books (the scheduler's
cache-aware admission ordering reads it), and the
``radix_partial_hits`` / ``radix_hit_tokens`` books say how much
prefill the tree actually absorbed. Copy-on-write fan-out leans on the
same refcounts: ``retain``/``release_claim`` let a fan-out group hold
a raw claim on a shared page so N sibling continuations admit against
it (rc bumps, no copies), and a sibling forks a private copy only for
the one partial page it must write into (``cow_forks`` counts them).

Conventions (shared with ``ops/paged_attention``):
- page 0 is the shared TRASH page: never allocated, the target of every
  unallocated table entry and of idle slots' garbage writes. Reads of it
  are always masked; concurrent garbage writes to it are unordered and
  unread.
- a slot's table row holds its pages in logical order; entries past its
  allocation point at the trash page.

Tensor parallelism is invisible here by design: the POOLS shard on
their head axis over the mesh (``runtime/continuous``), but a page is a
page — the table, the free list, refcounts and prefix keys are logical
bookkeeping, identical on every shard, so the allocator never changes
with the mesh (``table()`` is uploaded replicated).

Quantization is equally invisible: an int8 batcher keeps TWO pools per
K/V (``(int8 values, f32 scales)`` — ``ops/paged_attention``'s
quantized layout) addressed by ONE page id space, so every allocator
decision (alloc/free/recycle/prefix-share) applies to a page's values
and its scale plane atomically — a prefix-shared page always carries
the scales its int8 payload was written with. ``insert_prefill_pages``
scatters either member (``kv`` trailing dim is head_dim for values, 1
for scale planes).

No reference analog (SURVEY.md §2.2) — serving-memory frontier.
"""

from __future__ import annotations

import collections
import dataclasses
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class PagerStats:
    num_pages: int  # total pool pages incl. trash
    free: int  # immediately allocatable (free list + evictable cache)
    in_use: int  # rc > 0, excl. trash
    cached: int  # rc == 0 but resident for prefix reuse
    prefix_hits: int
    prefix_misses: int
    prefix_capacity_skips: int  # resident page, but the table row was full
    radix_nodes: int  # resident token-block nodes (== registered keys)
    radix_partial_hits: int  # admissions whose match ended mid-path
    radix_hit_tokens: int  # prompt tokens answered from resident nodes
    cow_forks: int  # fan-out page forks (private copy of a shared page)


@dataclasses.dataclass
class _RadixNode:
    """Metadata for one resident token-block node. The tree STRUCTURE
    lives in the content keys themselves (a node's key is its full
    root path; the parent key is the same bytes minus one page of
    tokens), so nodes need no child pointers — only what a flat key
    can't carry: the block depth and how hot the node runs."""

    depth: int  # 1-based page depth (covers depth * page_tokens tokens)
    hits: int = 0  # lookup_share acquisitions through this node


class Pager:
    """Free-list page allocator with refcounted prefix sharing over a
    pool of ``num_pages`` physical pages (page 0 reserved as trash) for
    ``slots`` lockstep slots whose table rows are ``pages_per_slot``
    wide."""

    def __init__(
        self,
        num_pages: int,
        slots: int,
        pages_per_slot: int,
        page_tokens: int | None = None,
    ):
        if num_pages < 2:
            raise ValueError(f"num_pages must be >= 2, got {num_pages}")
        if pages_per_slot < 1:
            raise ValueError(
                f"pages_per_slot must be >= 1, got {pages_per_slot}"
            )
        self.num_pages = num_pages
        self.pages_per_slot = pages_per_slot
        #: Tokens per page — lets the radix books convert page depths
        #: to token counts and ``radix_probe`` walk a raw prompt. None
        #: (a caller that never probes by tokens) degrades the radix
        #: view to depth-0 nodes with the byte registry untouched.
        self.page_tokens = page_tokens
        # Pop from the end -> low page ids hand out first (determinism
        # helps test reproducibility; no perf meaning).
        self._free = list(range(num_pages - 1, 0, -1))
        self._owned: list[list[int]] = [[] for _ in range(slots)]
        #: Rolling-window offset: how many LEADING logical ordinals of
        #: each slot have been released mid-request (sliding-window
        #: recycling). owned[0] then sits at table ordinal base[slot].
        self._base: list[int] = [0 for _ in range(slots)]
        self._rc: dict[int, int] = {}
        # Content-addressed prefix registry: key -> page, both ways.
        self._by_key: dict[bytes, int] = {}
        self._key_of: dict[int, bytes] = {}
        # rc==0 registered pages, oldest-first (eviction order).
        self._lru: collections.OrderedDict[int, None] = (
            collections.OrderedDict()
        )
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_capacity_skips = 0
        #: Radix metadata, keyed by the SAME content keys as _by_key
        #: (kept in lockstep: inserted by register/adopt_cached, dropped
        #: by the two eviction paths) — the byte registry stays the one
        #: source of residency truth, radix coherence with the host
        #: tier's spill/readmit keys is free.
        self._radix: dict[bytes, _RadixNode] = {}
        self.radix_partial_hits = 0
        self.radix_hit_tokens = 0
        self.radix_evictions = 0
        self.cow_forks = 0
        #: Optional eviction callback ``(page, key) -> None``, invoked
        #: just BEFORE a registered rc=0 page leaves the pool (LRU
        #: eviction under allocation pressure, or an ``evict_cached``
        #: sweep) — the hierarchical-cache seam: a host tier
        #: (``HostKVTier`` via ``runtime/continuous``) captures the
        #: page's bytes here so eviction spills instead of killing the
        #: content. The page's HBM bytes are still readable when the
        #: hook runs (pools are functional arrays; the new owner's
        #: write dispatches later), and the hook must not reenter the
        #: pager.
        self.evict_hook = None

    @property
    def num_allocatable(self) -> int:
        """Pages the allocator can ever hand out: the pool minus the
        reserved trash page — the denominator occupancy gauges and
        capacity planning should use (``num_pages`` counts the trash
        page too)."""
        return self.num_pages - 1

    # -- raw pages ---------------------------------------------------------

    def _take_one(self) -> int | None:
        if self._free:
            return self._free.pop()
        if self._lru:  # evict the coldest cached prefix page
            page, _ = self._lru.popitem(last=False)
            key = self._key_of.pop(page)
            del self._by_key[key]
            self._radix_drop(key)
            if self.evict_hook is not None:
                self.evict_hook(page, key)
            return page
        return None

    # -- radix metadata (keys double as root-to-node paths) ----------------

    def _radix_add(self, key: bytes) -> None:
        if key not in self._radix:
            depth = (
                len(key) // (4 * self.page_tokens)
                if self.page_tokens
                else 0
            )
            self._radix[key] = _RadixNode(depth=depth)

    def _radix_drop(self, key: bytes) -> None:
        if self._radix.pop(key, None) is not None:
            self.radix_evictions += 1

    def can_alloc(self, n: int) -> bool:
        return len(self._free) + len(self._lru) >= n

    def alloc(self, slot: int, n: int) -> bool:
        """Grant ``n`` MORE pages to ``slot``; all-or-nothing. False if
        the pool cannot cover it even after evicting every rc=0 cached
        page (caller leaves the request queued)."""
        owned = self._owned[slot]
        if self._base[slot] + len(owned) + n > self.pages_per_slot:
            raise ValueError(
                f"slot {slot}: {self._base[slot]}+{len(owned)}+{n} pages "
                f"exceeds table width {self.pages_per_slot}"
            )
        if not self.can_alloc(n):
            return False
        for _ in range(n):
            page = self._take_one()
            self._rc[page] = 1
            owned.append(page)
        return True

    def _release_one(self, page: int) -> None:
        """Drop one claim on ``page``; at rc=0 it returns to the free
        list — unless registered as prefix cache, in which case it
        stays resident and evictable (LRU)."""
        self._rc[page] -= 1
        if self._rc[page] == 0:
            del self._rc[page]
            if page in self._key_of:
                self._lru[page] = None  # newest = last evicted
            else:
                self._free.append(page)

    def free_slot(self, slot: int) -> None:
        """Drop ``slot``'s claim on all its pages."""
        for page in reversed(self._owned[slot]):
            self._release_one(page)
        self._owned[slot] = []
        self._base[slot] = 0

    def release_prefix(self, slot: int, n: int) -> None:
        """Sliding-window recycling: release ``slot``'s first ``n``
        logical pages MID-REQUEST (they fell wholly behind the
        attention window — masked forever, written never again). Their
        table ordinals point at the trash page from here on; shared /
        registered pages follow the usual rc / LRU rules, so a released
        prompt page can still serve future prefix hits."""
        if n <= 0:
            return
        if n > len(self._owned[slot]):
            raise ValueError(
                f"slot {slot}: releasing {n} of "
                f"{len(self._owned[slot])} owned pages"
            )
        for page in self._owned[slot][:n]:
            self._release_one(page)
        self._owned[slot] = self._owned[slot][n:]
        self._base[slot] += n

    def base(self, slot: int) -> int:
        return self._base[slot]

    def owned(self, slot: int) -> list[int]:
        return list(self._owned[slot])

    def table(self) -> np.ndarray:
        """(slots, pages_per_slot) int32; unallocated (and released)
        entries -> trash page 0."""
        t = np.zeros((len(self._owned), self.pages_per_slot), np.int32)
        for i, pages in enumerate(self._owned):
            b = self._base[i]
            t[i, b: b + len(pages)] = pages
        return t

    # -- prefix sharing ----------------------------------------------------

    @staticmethod
    def prefix_key(tokens: np.ndarray, upto: int) -> bytes:
        """Content key for the page covering positions [upto-P, upto):
        the whole prompt prefix [0, upto) (K/V at position p depend on
        every earlier token, so the key must cover them all)."""
        return np.ascontiguousarray(tokens[:upto], np.int32).tobytes()

    def lookup_share(self, slot: int, key: bytes) -> int | None:
        """If ``key``'s page is resident, acquire it for ``slot``
        (rc+1, out of the eviction LRU) and return it."""
        page = self._by_key.get(key)
        if page is None:
            self.prefix_misses += 1
            return None
        # Row-capacity check mirrors alloc()'s accounting: the recycled
        # window base occupies leading ordinals even though the pages are
        # gone (ADVICE r4 — len(owned) alone silently overflowed the row
        # for any future caller sharing into a partially-recycled slot).
        if self._base[slot] + len(self._owned[slot]) + 1 > self.pages_per_slot:
            # A miss for accounting (hits+misses == probes) with its own
            # counter: the page WAS resident, the row was just full.
            self.prefix_misses += 1
            self.prefix_capacity_skips += 1
            return None
        self._lru.pop(page, None)
        self._rc[page] = self._rc.get(page, 0) + 1
        self._owned[slot].append(page)
        self.prefix_hits += 1
        node = self._radix.get(key)
        if node is not None:
            node.hits += 1
        return page

    def retain(self, page: int) -> None:
        """Take one RAW claim on ``page`` (rc+1, out of the eviction
        LRU) without binding it to a slot — how a fan-out group pins
        its shared last-prompt page so it cannot recycle before every
        queued sibling has forked off it. Balance with
        :meth:`release_claim`."""
        self._lru.pop(page, None)
        self._rc[page] = self._rc.get(page, 0) + 1

    def release_claim(self, page: int) -> None:
        """Drop a :meth:`retain` claim; the usual rc=0 rules apply
        (registered pages park in the LRU, others return free)."""
        self._release_one(page)

    def record_prefix_match(self, matched_pages: int, prompt_len: int) -> None:
        """Token-weighted admission accounting for one radix walk:
        ``matched_pages`` leading pages of a ``prompt_len``-token
        prompt were answered from resident nodes. A match that ends
        strictly inside the prompt's shareable page run is a PARTIAL
        hit — the case whole-run keying would have scored as a total
        miss."""
        if matched_pages <= 0 or not self.page_tokens:
            return
        self.radix_hit_tokens += matched_pages * self.page_tokens
        if matched_pages < (prompt_len - 1) // self.page_tokens:
            self.radix_partial_hits += 1

    def note_cow_fork(self) -> None:
        """One fan-out sibling forked a private copy of a shared page
        (the copy-on-write write point)."""
        self.cow_forks += 1

    def radix_probe(self, tokens) -> tuple[int, int, int]:
        """Read-only radix walk for a prompt: ``(matched_pages,
        matched_tokens, heat)`` of the deepest resident token-block
        path, where ``heat`` sums the path nodes' lifetime hit counts.
        No counters move and nothing is acquired — safe to call per
        queued candidate (the scheduler's cache-aware ordering and
        `prefix_cached` both score with it). The walk caps at
        ``(len(tokens)-1)//page_tokens`` pages, mirroring the admission
        probe: the page holding the last prompt token is never shared
        because its tail positions get written."""
        if not self.page_tokens:
            return (0, 0, 0)
        tokens = np.ascontiguousarray(
            np.asarray(tokens, np.int32).reshape(-1)
        )
        raw = tokens.tobytes()
        step = 4 * self.page_tokens
        pages = heat = 0
        for j in range((tokens.shape[0] - 1) // self.page_tokens):
            node = self._radix.get(raw[: (j + 1) * step])
            if node is None:
                break
            pages += 1
            heat += node.hits
        return (pages, pages * self.page_tokens, heat)

    def radix_sketch(self, k: int) -> list[tuple[bytes, int, int]]:
        """Top-``k`` resident radix nodes by token-weighted heat:
        ``[(content_key, depth, hits)]``, hottest first. Weight is
        ``depth * (1 + hits)`` — depth counts the tokens a match at
        this node saves, the ``1 +`` keeps never-hit (freshly
        registered) deep prefixes rankable at all. Read-only snapshot
        (``list()`` at C speed, same stats()-era discipline: exporter
        threads may call while the ticking thread mutates) — the
        capacity plane's affinity-sketch export
        (``runtime/capacity.sketch_from_pager``)."""
        if not self.page_tokens or k <= 0:
            return []
        items = list(self._radix.items())
        items.sort(
            key=lambda kv: (
                kv[1].depth * (1 + kv[1].hits), kv[1].depth,
            ),
            reverse=True,
        )
        return [(key, n.depth, n.hits) for key, n in items[:k]]

    def adopt_cached(self, keys: list[bytes]) -> list[tuple[int, int]]:
        """Adopt EXTERNALLY prefilled prefix pages into the cache — the
        disaggregated-serving landing path (``runtime/disagg``): for
        every key not already resident, take a pool page, register it
        under its content key and park it rc=0 in the LRU (newest), so
        the next admission whose prompt hashes to these keys shares
        them exactly like locally computed prefix pages (evictable
        under pressure by the usual rules until then). Returns
        ``[(ordinal, page)]`` for the keys actually adopted — the
        caller scatters ONLY those ordinals' K/V (already-resident keys
        dedupe against the cache; first writer won). Returns ``[]``
        with nothing taken when the pool cannot cover the new pages
        all-or-nothing (the caller falls back to a collocated
        prefill — adoption is an optimization, never a correctness
        gate)."""
        fresh = [
            (i, k) for i, k in enumerate(keys) if k not in self._by_key
        ]
        if not fresh or not self.can_alloc(len(fresh)):
            return []
        out = []
        for i, key in fresh:
            page = self._take_one()
            self._by_key[key] = page
            self._key_of[page] = key
            self._radix_add(key)
            self._lru[page] = None  # rc=0, resident, newest
            out.append((i, page))
        return out

    def evict_cached(self, n: int | None = None) -> int:
        """Evict up to ``n`` (default: all) COLD prefix-cache pages —
        rc=0 LRU residents, oldest first — back to the free list,
        dropping their content keys. The degradation ladder's sweep
        rung (``runtime/scheduler``): capacity-NEUTRAL by construction
        (``can_alloc`` already counts the LRU and ``alloc`` evicts on
        demand), it trades the cache's speculative prefix-hit value
        for the allocator's free-list fast path under overload. Live
        (rc>0) pages are untouched; the pool partition (used + free +
        cached) is conserved. Returns the count evicted."""
        evicted = 0
        while self._lru and (n is None or evicted < n):
            page, _ = self._lru.popitem(last=False)
            key = self._key_of.pop(page)
            del self._by_key[key]
            self._radix_drop(key)
            if self.evict_hook is not None:
                self.evict_hook(page, key)
            self._free.append(page)
            evicted += 1
        return evicted

    def resident(self, key: bytes) -> bool:
        """True when ``key``'s page is in the pool (owned or cached) —
        the no-accounting residency probe the host-tier readmit path
        uses BEFORE ``lookup_share`` (which counts a hit or miss)."""
        return key in self._by_key

    def cached_pages(self) -> list[tuple[int, bytes]]:
        """The rc=0 prefix-cache residents with their content keys,
        oldest (next-evicted) first — the proactive spill sweep's
        working set. Spill candidates come ONLY from here: a page
        referenced by a live slot (rc > 0) never appears, which is
        what keeps lossy host-tier codecs away from live decode
        state."""
        return [(p, self._key_of[p]) for p in self._lru]

    def register(self, page: int, key: bytes) -> None:
        """Publish ``page`` (currently owned, rc>=1) as the cache entry
        for ``key``. First writer wins; a page may carry one key."""
        if key in self._by_key or page in self._key_of:
            return
        self._by_key[key] = page
        self._key_of[page] = key
        self._radix_add(key)

    def stats(self) -> PagerStats:
        # list(...) snapshots the dict at C speed: stats() is now also
        # read from exporter scrape threads (the memory collector in
        # utils.profiling) while the ticking thread mutates _rc, and a
        # generator over live .values() could raise "dict changed size
        # during iteration" mid-scrape.
        return PagerStats(
            num_pages=self.num_pages,
            free=len(self._free) + len(self._lru),
            in_use=sum(1 for r in list(self._rc.values()) if r > 0),
            cached=len(self._lru),
            prefix_hits=self.prefix_hits,
            prefix_misses=self.prefix_misses,
            prefix_capacity_skips=self.prefix_capacity_skips,
            radix_nodes=len(self._radix),
            radix_partial_hits=self.radix_partial_hits,
            radix_hit_tokens=self.radix_hit_tokens,
            cow_forks=self.cow_forks,
        )


@dataclasses.dataclass
class HostTierStats:
    pages: int  # host-resident pages (warm + cold; disk excluded)
    warm: int
    cold: int
    disk: int  # pages persisted to the optional disk tier
    host_bytes: int  # encoded bytes resident in host memory
    spilled: int  # lifetime pages accepted by put()
    dropped: int  # lifetime pages that fell off the cold end
    codec_bytes_saved: int  # lifetime raw - encoded bytes


@dataclasses.dataclass
class _HostPage:
    """One spilled page: per-block (K, V) members, each member a tuple
    of ``(payload, meta)`` encoded leaves (one leaf for native pools,
    ``(values, scales)`` for quantized ones)."""

    blocks: list
    nbytes: int  # encoded bytes (payload sum)
    raw_nbytes: int


class HostKVTier:
    """The host-DRAM (optionally disk-backed) spill tier under the
    :class:`Pager` — ROADMAP item 3's "cache tiers below the Pager".

    Pages evicted from the HBM prefix LRU land here under the SAME
    content keys the admission probe computes, encoded by the
    ``ops.quantize`` page codec stack: the WARM sub-tier keeps a
    lossless codec (readmits are bit-exact), pages demoted past the
    warm capacity re-encode with the COLD codec (lossy allowed —
    every page here is rc=0 by construction, never referenced by a
    live slot), and pages past the total host capacity either persist
    to ``disk_dir`` or drop (counted). ``get`` decodes a page back to
    its pool-shaped host arrays for the readmit landing path
    (``ContinuousBatcher._maybe_readmit`` -> ``Pager.adopt_cached``
    -> ``_adopt_pages``).

    Plain-python bookkeeping like the Pager itself — no jax, no
    metrics registry (the batcher bridges the books to ``cache_tier.*``
    counters and ``memory.host_bytes`` / ``memory.pages_spilled``
    gauges); thread-safety follows the pager's model (mutations on the
    ticking thread, ``stats()`` tolerant of racing reads)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self._warm: collections.OrderedDict[bytes, _HostPage] = (
            collections.OrderedDict()
        )
        self._cold: collections.OrderedDict[bytes, _HostPage] = (
            collections.OrderedDict()
        )
        #: key -> (path, blocks-meta) for disk-persisted pages.
        self._disk: dict[bytes, tuple[str, list]] = {}
        self._bytes = 0
        self.spilled = 0
        self.dropped = 0
        self.codec_bytes_saved = 0
        if cfg.disk_dir:
            os.makedirs(cfg.disk_dir, exist_ok=True)

    # -- encoding ----------------------------------------------------------

    @staticmethod
    def _encode(blocks, codec: str) -> _HostPage:
        from adapt_tpu.ops.quantize import encode_page

        enc, nbytes, raw = [], 0, 0
        for k, v in blocks:
            pair = []
            for member in (k, v):
                leaves = (
                    member if isinstance(member, tuple) else (member,)
                )
                out = []
                for leaf in leaves:
                    payload, meta = encode_page(np.asarray(leaf), codec)
                    nbytes += len(payload)
                    raw += meta["raw_nbytes"]
                    out.append((payload, meta))
                pair.append(tuple(out))
            enc.append(tuple(pair))
        return _HostPage(blocks=enc, nbytes=nbytes, raw_nbytes=raw)

    @staticmethod
    def _decode(entry: _HostPage) -> list:
        from adapt_tpu.ops.quantize import decode_page

        blocks = []
        for km, vm in entry.blocks:
            pair = []
            for member in (km, vm):
                leaves = [decode_page(p, m) for p, m in member]
                pair.append(
                    leaves[0] if len(leaves) == 1 else tuple(leaves)
                )
            blocks.append(tuple(pair))
        return blocks

    def _book(self, entry: _HostPage, sign: int) -> None:
        self._bytes += sign * entry.nbytes

    # -- the tier API ------------------------------------------------------

    def contains(self, key: bytes) -> bool:
        return (
            key in self._warm or key in self._cold or key in self._disk
        )

    def put(self, key: bytes, blocks) -> tuple[int, int]:
        """Spill one page (per-block ``(K, V)`` host leaves, pool
        shapes ``(kvh, page, w)``) into the WARM sub-tier under its
        content key. Idempotent for resident keys (MRU touch only).
        Returns ``(raw_bytes, encoded_bytes)`` for the caller's
        accounting."""
        if self.contains(key):
            if key in self._warm:
                self._warm.move_to_end(key)
            return (0, 0)
        entry = self._encode(blocks, self.cfg.warm_codec)
        self._warm[key] = entry
        self._book(entry, +1)
        self.spilled += 1
        self.codec_bytes_saved += entry.raw_nbytes - entry.nbytes
        self._demote()
        return (entry.raw_nbytes, entry.nbytes)

    def _demote(self) -> None:
        """Warm overflow -> COLD (re-encode through the cold codec:
        warm is lossless, so the cold payload is exactly what a
        direct cold-encode of the original would hold); cold overflow
        -> disk when configured, else dropped (counted)."""
        cold_cap = self.cfg.host_capacity_pages - self.cfg.warm_capacity_pages
        while len(self._warm) > self.cfg.warm_capacity_pages:
            key, entry = self._warm.popitem(last=False)
            self._book(entry, -1)
            if cold_cap <= 0:
                self._overflow(key, entry)
                continue
            cold = (
                entry
                if self.cfg.cold_codec == self.cfg.warm_codec
                else self._encode(self._decode(entry), self.cfg.cold_codec)
            )
            if cold is not entry:
                self.codec_bytes_saved += entry.nbytes - cold.nbytes
            self._cold[key] = cold
            self._book(cold, +1)
        while (
            len(self._cold) > max(cold_cap, 0) and self._cold
        ):
            key, entry = self._cold.popitem(last=False)
            self._book(entry, -1)
            self._overflow(key, entry)

    def _overflow(self, key: bytes, entry: _HostPage) -> None:
        if not self.cfg.disk_dir:
            self.dropped += 1
            return
        import hashlib
        import pickle

        path = os.path.join(
            self.cfg.disk_dir,
            hashlib.sha256(key).hexdigest()[:32] + ".kvpage",
        )
        with open(path, "wb") as f:
            pickle.dump(entry, f)
        self._disk[key] = (path, None)

    def get(self, key: bytes):
        """Decoded per-block ``(K, V)`` host arrays for ``key``, or
        None. MRU-touches the entry (it stays host-resident after a
        readmit: the HBM copy is rc=0 evictable and may bounce right
        back)."""
        entry = self._warm.get(key)
        if entry is not None:
            self._warm.move_to_end(key)
            return self._decode(entry)
        entry = self._cold.get(key)
        if entry is not None:
            self._cold.move_to_end(key)
            return self._decode(entry)
        disk = self._disk.get(key)
        if disk is not None:
            import pickle

            try:
                with open(disk[0], "rb") as f:
                    entry = pickle.load(f)
            except OSError:
                del self._disk[key]
                return None
            return self._decode(entry)
        return None

    @property
    def pages(self) -> int:
        return len(self._warm) + len(self._cold)

    @property
    def host_bytes(self) -> int:
        return self._bytes

    def stats(self) -> HostTierStats:
        return HostTierStats(
            pages=self.pages,
            warm=len(self._warm),
            cold=len(self._cold),
            disk=len(self._disk),
            host_bytes=self._bytes,
            spilled=self.spilled,
            dropped=self.dropped,
            codec_bytes_saved=self.codec_bytes_saved,
        )


@partial(jax.jit, donate_argnums=(0,))
def insert_prefill_pages(pool, pages, kv):
    """Scatter a prefilled request's contiguous (1, kv_h, S, hd) K or V
    into its physical ``pages`` ((n,) int32, logical order). S pads up
    to n*page positions — pad columns hold zeros that sit beyond the
    prompt (masked until decode overwrites them). One scatter on the
    page axis; jit specializes per (n, S), both bucket-bounded."""
    n = pages.shape[0]
    _, kvh, page, hd = pool.shape
    s = kv.shape[2]
    kvp = jnp.pad(kv[0], ((0, 0), (0, n * page - s), (0, 0)))
    kvp = jnp.swapaxes(kvp.reshape(kvh, n, page, hd), 0, 1)
    return pool.at[pages].set(kvp.astype(pool.dtype))
