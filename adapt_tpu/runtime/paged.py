"""Host-side page bookkeeping for the paged KV cache.

The device side is ``ops/paged_attention`` (pools + the scalar-prefetch
kernel); this module owns the ALLOCATOR: a free list of physical pages,
per-slot page ownership, and the (slots, pages_per_slot) page table the
compiled step consumes. All of it is plain numpy/python on the serving
control path — page churn is a few integers per request, never worth a
device round trip.

Conventions (shared with ``ops/paged_attention``):
- page 0 is the shared TRASH page: never allocated, the target of every
  unallocated table entry and of idle slots' garbage writes. Reads of it
  are always masked; concurrent garbage writes to it are unordered and
  unread.
- a slot's table row holds its pages in logical order; entries past its
  allocation point at the trash page.

No reference analog (SURVEY.md §2.2) — serving-memory frontier.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class PagerStats:
    num_pages: int  # total pool pages incl. trash
    free: int
    in_use: int  # excl. trash


class Pager:
    """Free-list page allocator over a pool of ``num_pages`` physical
    pages (page 0 reserved as trash) for ``slots`` lockstep slots whose
    table rows are ``pages_per_slot`` wide."""

    def __init__(self, num_pages: int, slots: int, pages_per_slot: int):
        if num_pages < 2:
            raise ValueError(f"num_pages must be >= 2, got {num_pages}")
        if pages_per_slot < 1:
            raise ValueError(
                f"pages_per_slot must be >= 1, got {pages_per_slot}"
            )
        self.num_pages = num_pages
        self.pages_per_slot = pages_per_slot
        # Pop from the end -> low page ids hand out first (determinism
        # helps test reproducibility; no perf meaning).
        self._free = list(range(num_pages - 1, 0, -1))
        self._owned: list[list[int]] = [[] for _ in range(slots)]

    def can_alloc(self, n: int) -> bool:
        return len(self._free) >= n

    def alloc(self, slot: int, n: int) -> bool:
        """Grant ``n`` MORE pages to ``slot``; all-or-nothing. False if
        the pool cannot cover it (caller leaves the request queued)."""
        owned = self._owned[slot]
        if len(owned) + n > self.pages_per_slot:
            raise ValueError(
                f"slot {slot}: {len(owned)}+{n} pages exceeds table "
                f"width {self.pages_per_slot}"
            )
        if len(self._free) < n:
            return False
        for _ in range(n):
            owned.append(self._free.pop())
        return True

    def free_slot(self, slot: int) -> None:
        """Return all of ``slot``'s pages to the pool."""
        self._free.extend(reversed(self._owned[slot]))
        self._owned[slot] = []

    def owned(self, slot: int) -> list[int]:
        return list(self._owned[slot])

    def table(self) -> np.ndarray:
        """(slots, pages_per_slot) int32; unallocated entries -> trash
        page 0."""
        t = np.zeros((len(self._owned), self.pages_per_slot), np.int32)
        for i, pages in enumerate(self._owned):
            t[i, : len(pages)] = pages
        return t

    def stats(self) -> PagerStats:
        in_use = sum(len(p) for p in self._owned)
        return PagerStats(
            num_pages=self.num_pages,
            free=len(self._free),
            in_use=in_use,
        )


@partial(jax.jit, donate_argnums=(0,))
def insert_prefill_pages(pool, pages, kv):
    """Scatter a prefilled request's contiguous (1, kv_h, S, hd) K or V
    into its physical ``pages`` ((n,) int32, logical order). S pads up
    to n*page positions — pad columns hold zeros that sit beyond the
    prompt (masked until decode overwrites them). One scatter on the
    page axis; jit specializes per (n, S), both bucket-bounded."""
    n = pages.shape[0]
    _, kvh, page, hd = pool.shape
    s = kv.shape[2]
    kvp = jnp.pad(kv[0], ((0, 0), (0, n * page - s), (0, 0)))
    kvp = jnp.swapaxes(kvp.reshape(kvh, n, page, hd), 0, 1)
    return pool.at[pages].set(kvp.astype(pool.dtype))
