"""Disaggregated prefill/decode serving: a prefill tier that streams
KV pages to the decode batcher, so decode ticks never run a long
prompt's prefill inline.

The pathology (measured by ``continuous.prefill_stall_s`` and the
``benchmarks/load`` long-tail preset): the collocated
``ContinuousBatcher`` runs every admission's prefill INSIDE the tick
loop, so under heavy-tailed prompt lengths a p99 prompt's prefill wall
lands directly on every decoding request's inter-token latency — the
decode batch convoys behind the fattest prefill. Production fleets
split the two phases onto separate pools (compute-bound prefill,
latency/bandwidth-bound decode — the same specialization the source
paper applies to its pipeline workers, PAPER.md §0); this module is
that split, TPU-native and single-process-testable:

- :class:`PrefillWorker` — the prefill tier: admission + CHUNKED
  prefill against its own paged pool (one page-aligned chunk pass per
  ``step()``, the Sarathi-style bound on any single stall), then a
  page-gather and handoff of the prompt's FULL pages' K/V. The worker
  is deliberately layout-blind about the decode side: it ships the
  full head range, host-staged, and never needs to know the decode
  mesh.
- **The wire** — a handoff is one ``comm.framing.Message``
  (``MSG_KV_PAGES``): the K/V page chunks ride as concatenated
  zero-copy codec frames (``codec.pack_frames`` — scatter-write parts
  on send, views of the receive buffer on receive; the PR-1 contract,
  pinned via ``codec.copy_stats()``), described by the new
  ``FLAG_PAGE_ANNEX`` page-range annex (request id, page geometry,
  per-tensor frame lengths). ``loopback()`` is the in-process
  transport: it performs the kernel's gather into one buffer and
  re-parses it through the SAME ``frame_parts``/``parse_frame`` pair
  the socket paths use, so corruption/truncation behave exactly as
  they would off a real socket.
- **Decode-side landing** — ``ContinuousBatcher.adopt_prefill_pages``:
  pages register in the paged PREFIX CACHE under the same content keys
  admission probes (rc=0, resident, evictable), their bytes scatter in
  shard-locally via a ``parallel.sharding.KVHandoffPlan`` (head-
  sharded decode pools receive per-shard slices — aligned union,
  never a gather), and the request then enters through the ordinary
  ``submit()``: admission sees a prefix-cache hit and prefills only
  the suffix. Because the landing path IS the existing prefix-cache
  insertion path, int8 pools (values + scales move under one plan),
  tensor parallelism and speculative mode compose for free, and
  greedy streams stay bit-identical to the collocated path.
- :class:`DisaggServer` — the disaggregated submit path: a placement
  policy (``config.DisaggConfig``: prompt-length threshold, tightened
  when decode occupancy is high) chooses collocated vs disaggregated
  PER REQUEST, with automatic collocated fallback whenever the
  prefill tier cannot help (pool pressure, dead lease, no full page,
  corrupt handoff) — placement is an optimization, never a
  correctness gate. With a ``control.registry.WorkerRegistry`` the
  prefill pool holds a ROLE-TAGGED lease (``role="prefill"``): the
  pipeline dispatcher's acquisition skips it, and the policy stops
  routing to a tier whose lease expired.

Observability: ``disagg.{handoff_bytes,handoff_s,pages_streamed}``
(+ ``disagg.handoff_bytes_raw`` — wire bytes are POST-codec when a
``wire_codec`` is set, so raw/wire is the compression ratio)
plus the ``kv_handoff`` flight event per landing;
``continuous.prefill_stall_s`` on the decode batcher shows what the
handoff removed. ``docs/SERVING.md`` "Disaggregated prefill/decode"
covers sizing and when collocated wins.

Single-process scope (v1): the server drives both tiers from one
thread — the prefill CHUNK is the stall bound, which is what the
load harness measures. The wire format is the cross-host format; a
remote prefill tier sends the same ``MSG_KV_PAGES`` frames through
``comm.framing.send_msg`` unchanged.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import time
import weakref
import zlib
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from adapt_tpu.comm import codec
from adapt_tpu.comm.framing import (
    MSG_KV_PAGES,
    Message,
    frame_parts,
    parse_frame,
)
from adapt_tpu.config import DisaggConfig, PrefillConfig, SLOSpec
from adapt_tpu.models.transformer_lm import TransformerLM
from adapt_tpu.parallel.sp_prefill import SPPrefiller, build_sp_mesh
from adapt_tpu.runtime.capacity import prefill_tier_book
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.paged import Pager
from adapt_tpu.runtime.scheduler import QueueFullError
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.profiling import (
    aggregate_size_fn,
    global_compile_sentinel,
)
from adapt_tpu.utils.tracing import global_flight_recorder, global_tracer

log = get_logger("disagg")

_LEN_PREFIX = 8  # comm.framing._LEN.size — the frame length prefix


class HandoffError(RuntimeError):
    """A KV handoff frame could not be decoded or landed (corrupt or
    truncated wire bytes, geometry mismatch). The server fails the
    REQUEST cleanly — empty result, ``request_failed`` flight event —
    and keeps serving."""


#: Live prefill workers (weak) — the ONE "disagg.prefill" compile-
#: sentinel watch sums their per-instance chunk-program caches, the
#: same aggregation discipline as the batcher's prefill family.
_LIVE_WORKERS: "weakref.WeakSet[PrefillWorker]" = weakref.WeakSet()


def _worker_family_size(w: "PrefillWorker") -> int:
    return sum(f._cache_size() for f in list(w._fn_cache.values()))


@dataclasses.dataclass
class KVHandoff:
    """One prefilled request's streamable state: the prompt, the page
    geometry, and per-block page-major K/V chunks covering the
    prompt's first ``n_pages`` FULL pages (``(n_pages, kv_heads,
    page_size, head_dim)`` per member; quantized pools carry
    ``(values, scales)`` tuples — the scale plane is part of the
    page)."""

    req_id: int
    prompt: np.ndarray
    page_size: int
    n_pages: int
    quantized: bool
    #: One ``(K, V)`` pair per decoder block.
    blocks: list
    #: KV dtype on the wire: "native", "int8" or "int4" (int4 members
    #: carry PACKED ``head_dim // 2`` value lanes — the width is part
    #: of the wire geometry the decode side validates). Defaults to
    #: the legacy mapping of ``quantized``.
    kv_dtype: str = ""

    def __post_init__(self):
        if not self.kv_dtype:
            self.kv_dtype = "int8" if self.quantized else "native"


def _leaves(handoff: KVHandoff) -> list[np.ndarray]:
    """The handoff's tensors in WIRE ORDER: prompt first, then each
    block's K members then V members (quantized pairs flatten to
    values, scales)."""
    out: list[np.ndarray] = [np.ascontiguousarray(handoff.prompt, np.int32)]
    for k, v in handoff.blocks:
        for member in (k, v):
            if isinstance(member, tuple):
                out.extend(member)
            else:
                out.append(member)
    return out


def handoff_raw_nbytes(handoff: KVHandoff) -> int:
    """Uncompressed payload bytes of a handoff (every wire tensor's
    host nbytes) — the numerator dashboards divide
    ``disagg.handoff_bytes`` by to read the wire compression ratio."""
    return sum(int(arr.nbytes) for arr in _leaves(handoff))


def _wire_tensors(
    handoff: KVHandoff, head_ranges: list[tuple[int, int]] | None
) -> list[np.ndarray]:
    """The tensors actually framed, in wire order. Without
    ``head_ranges`` this is :func:`_leaves` verbatim. With them, every
    KV leaf (never the prompt) ships as one contiguous slice per
    ``(lo, hi)`` destination head tile — sender-side resharding: the
    wire already carries the aligned-union slices the destination's
    :class:`~adapt_tpu.parallel.sharding.KVHandoffPlan` would cut, so
    a tp=2 prefill tier feeds a tp=4 decode replica without either
    side materializing a cross-mesh gather. The ranges must tile the
    head axis exactly (``parallel.sharding.head_tiles`` builds legal
    ones) or this raises — a slicing the receiver cannot reassemble
    must fail at pack time, by name."""
    leaves = _leaves(handoff)
    if not head_ranges:
        return leaves
    out = [leaves[0]]
    for arr in leaves[1:]:
        h = int(arr.shape[1])
        cover = 0
        for lo, hi in head_ranges:
            if int(lo) != cover or hi <= lo:
                raise HandoffError(
                    f"head_ranges {head_ranges} do not tile the "
                    f"{h}-head axis contiguously"
                )
            cover = int(hi)
        if cover != h:
            raise HandoffError(
                f"head_ranges cover {cover} of {h} kv heads"
            )
        for lo, hi in head_ranges:
            # One contiguous copy per tile — the same bytes a
            # destination shard's device_put would stage anyway.
            out.append(np.ascontiguousarray(arr[:, lo:hi]))
    return out


def pack_handoff(
    handoff: KVHandoff,
    wire_codec: str = "raw",
    head_ranges: list[tuple[int, int]] | None = None,
) -> Message:
    """Frame a handoff for the comm tier: every tensor becomes one
    zero-copy codec frame (``codec.pack_frames`` with the raw codec —
    scatter-write parts, no payload copy; ``codec.copy_stats()`` pins
    it), concatenated in wire order as the message payload; the
    page-range annex carries the geometry and per-tensor frame
    lengths needed to slice them back out.

    ``wire_codec`` != "raw" compresses each tensor through the
    ``ops.quantize`` page codec stack before framing (lossless "lz",
    or lossy "int8"/"int4"/"zfp" on FLOAT tensors only — the prompt
    and int value planes always pack lossless). The annex then
    carries per-tensor codec meta, and the crc is computed over the
    COMPRESSED payload — corruption is detected before any decode
    touches the bytes, exactly like the raw path.

    ``head_ranges`` (destination head tiles from
    ``parallel.sharding.head_tiles``) reshards SENDER-SIDE: each KV
    tensor frames as one slice per tile, in tile order, and the annex
    records the tiling so :func:`unpack_handoff` can reassemble the
    full head range — the cross-replica tp-mismatch wire (a tp=2
    prefill pool feeding a tp=4 decode replica ships four 2-head
    slices per leaf, never a gathered whole)."""
    parts: list = []
    frame_lens: list[int] = []
    crc = 0
    leaf_meta: list[dict] | None = None
    wire = _wire_tensors(handoff, head_ranges)
    if wire_codec != "raw":
        from adapt_tpu.ops.quantize import encode_page

        leaf_meta = []
        for arr in wire:
            payload, meta = encode_page(np.asarray(arr), wire_codec)
            frame_lens.append(len(payload))
            leaf_meta.append(meta)
            crc = zlib.crc32(payload, crc)
            parts.append(memoryview(payload))
    else:
        raw = codec.get_codec("none")
        for arr in wire:
            frames = codec.pack_frames(raw, arr)
            frame_lens.append(codec.frames_nbytes(frames))
            for p in frames:
                # Payload integrity: flipped bits in a KV page would
                # otherwise scatter SILENTLY into a live pool (raw codec
                # frames parse fine whatever the bytes hold). One crc
                # pass over views — no copy, ~free next to the transfer
                # itself.
                crc = zlib.crc32(p, crc)
            parts.extend(frames)
    meta = {
        "req_id": int(handoff.req_id),
        "page_size": int(handoff.page_size),
        "n_pages": int(handoff.n_pages),
        "quantized": bool(handoff.quantized),
        "kv_dtype": handoff.kv_dtype,
        "blocks": len(handoff.blocks),
        "prompt_len": int(handoff.prompt.shape[0]),
        "frame_lens": frame_lens,
        "crc32": crc,
    }
    if head_ranges:
        meta["head_ranges"] = [
            [int(lo), int(hi)] for lo, hi in head_ranges
        ]
    if leaf_meta is not None:
        meta["wire_codec"] = wire_codec
        meta["leaf_meta"] = leaf_meta
    annex = json.dumps(meta).encode()
    return Message(
        msg_type=MSG_KV_PAGES,
        stage_index=0,
        request_id=int(handoff.req_id),
        attempt=0,
        payload=parts,
        page_annex=annex,
    )


def unpack_handoff(msg: Message) -> KVHandoff:
    """Decode a ``MSG_KV_PAGES`` message back into a :class:`KVHandoff`.
    The returned arrays VIEW the message's receive buffer (the
    zero-copy receive contract — ``codec.unpack_many`` slices, never
    joins). Any malformed annex, frame or geometry raises
    :class:`HandoffError` — a corrupt handoff must fail the request by
    name, never scatter garbage into a live pool."""
    try:
        if msg.msg_type != MSG_KV_PAGES:
            raise ValueError(f"not a KV-pages message: {msg.msg_type}")
        if msg.page_annex is None:
            raise ValueError("missing page annex")
        meta = json.loads(msg.page_annex.decode())
        n_blocks = int(meta["blocks"])
        quantized = bool(meta["quantized"])
        # The crc always runs on the WIRE payload — post-codec bytes
        # when wire compression is on — so corruption is caught before
        # any codec decode touches the buffer.
        got_crc = zlib.crc32(msg.payload)
        if got_crc != int(meta["crc32"]):
            raise ValueError(
                f"payload crc mismatch ({got_crc:#x} != "
                f"{int(meta['crc32']):#x}) — corrupt KV pages"
            )
        wire_codec = meta.get("wire_codec")
        if wire_codec:
            # Compressed annex: slice the payload by the per-tensor
            # frame lengths and decode each through the page codec
            # stack. Decoded tensors are fresh host arrays (the
            # zero-copy receive contract applies to the raw path
            # only — a compressed wire trades the view for the
            # bandwidth).
            from adapt_tpu.ops.quantize import decode_page

            mv = memoryview(msg.payload)
            lens = [int(x) for x in meta["frame_lens"]]
            if sum(lens) != len(mv):
                raise ValueError(
                    f"frame lengths sum to {sum(lens)}, payload is "
                    f"{len(mv)} bytes"
                )
            arrs, off = [], 0
            for ln, lmeta in zip(lens, meta["leaf_meta"]):
                arrs.append(decode_page(mv[off:off + ln], lmeta))
                off += ln
        else:
            arrs = codec.unpack_many(msg.payload, meta["frame_lens"])
        per_block = 4 if quantized else 2
        ranges = meta.get("head_ranges")
        if ranges:
            # Sender-side-resharded wire: each KV tensor arrived as
            # one slice per destination head tile. Reassemble the full
            # head range on the HOST (np.concatenate along the head
            # axis — the fetch_head_shards discipline: host concat,
            # never a device-side gather); adoption re-slices per the
            # local pool's own plan, so a tp-matched receiver pays one
            # view, not a reorder.
            r = len(ranges)
            if len(arrs) != 1 + n_blocks * per_block * r:
                raise ValueError(
                    f"{len(arrs)} tensors for {n_blocks} blocks x "
                    f"{r} head tiles (quantized={quantized})"
                )
            widths = [int(hi) - int(lo) for lo, hi in ranges]
            joined = [arrs[0]]
            for i in range(n_blocks * per_block):
                pieces = arrs[1 + i * r : 1 + (i + 1) * r]
                for p, w in zip(pieces, widths):
                    if p.ndim < 2 or p.shape[1] != w:
                        raise ValueError(
                            f"head tile shape {p.shape} != declared "
                            f"width {w}"
                        )
                joined.append(
                    pieces[0] if r == 1
                    else np.concatenate(pieces, axis=1)
                )
            arrs = joined
        if len(arrs) != 1 + n_blocks * per_block:
            raise ValueError(
                f"{len(arrs)} tensors for {n_blocks} blocks "
                f"(quantized={quantized})"
            )
        prompt = np.asarray(arrs[0], np.int32).reshape(-1)
        if prompt.shape[0] != int(meta["prompt_len"]):
            raise ValueError("prompt length mismatch")
        blocks = []
        it = iter(arrs[1:])
        for _ in range(n_blocks):
            if quantized:
                blocks.append(
                    ((next(it), next(it)), (next(it), next(it)))
                )
            else:
                blocks.append((next(it), next(it)))
        return KVHandoff(
            req_id=int(meta["req_id"]),
            prompt=prompt,
            page_size=int(meta["page_size"]),
            n_pages=int(meta["n_pages"]),
            quantized=quantized,
            blocks=blocks,
            kv_dtype=str(
                meta.get("kv_dtype")
                or ("int8" if quantized else "native")
            ),
        )
    except HandoffError:
        raise
    except Exception as e:  # noqa: BLE001 — every decode failure is one error
        raise HandoffError(f"malformed KV handoff: {e!r}") from e


def loopback(msg: Message) -> Message:
    """The in-process transport: gather the frame exactly as the
    kernel would (``frame_parts`` — the same scatter list
    ``send_msg`` hands to ``sendmsg``), then re-parse it through
    ``parse_frame`` (the same body ``recv_msg`` uses). The returned
    message's payload views the gathered buffer, so the receive side
    exercises the true zero-copy parse path; tests corrupt the
    gathered bytes to prove truncation fails cleanly."""
    wire = bytearray(b"".join(frame_parts(msg)))
    body = memoryview(wire)[_LEN_PREFIX:]
    expect = int.from_bytes(wire[:_LEN_PREFIX], "big")
    if len(body) != expect:
        raise HandoffError(
            f"truncated frame: {len(body)} of {expect} bytes"
        )
    try:
        return parse_frame(body)
    except ConnectionError as e:
        raise HandoffError(str(e)) from e


@dataclasses.dataclass
class _PrefillJob:
    req_id: int
    prompt: np.ndarray
    #: Positions to prefill: the prompt's full pages only ([0, m*P)) —
    #: the partial last page re-prefills decode-side as the suffix
    #: pass (the prefix probe never shares the final page anyway).
    target: int
    slot: int = -1
    pf_done: int = 0
    #: Set when an sp dispatch failed and the job fell back to the
    #: chunk path — the sp scan must not pick it up again (retrying a
    #: deterministic failure forever would starve the queue).
    no_sp: bool = False


class PrefillWorker:
    """The prefill tier: admission + chunked prefill against its OWN
    paged pool, producing :class:`KVHandoff`\\ s.

    Drives like a miniature batcher: :meth:`submit` queues a request,
    each :meth:`step` admits waiting jobs into free slots (FIFO,
    all-or-nothing page reservation) and runs ONE page-aligned chunk
    pass per active slot (``prefill_chunk`` bounds any single stall;
    ``None`` = the whole span in one pass — only sensible when the
    worker runs on its own thread/host), then gathers finished jobs'
    pages off the pool and frees them. The chunk math is EXACTLY the
    decode batcher's chunked-prefill body
    (``models.prefill_chunk_paged`` with the same power-of-two window
    padding), so handed pages are bit-identical to what the decode
    side's own chunked prefill would have written — the foundation of
    the disaggregated path's bit-identity contract."""

    def __init__(
        self,
        lm: TransformerLM,
        variables,
        page_size: int = 128,
        slots: int = 2,
        pool_pages: int | None = None,
        prefill_chunk: int | None = None,
        kv_cache_dtype: str = "native",
        name: str = "prefill0",
        prefill: PrefillConfig | None = None,
        sp_mesh=None,
    ):
        if kv_cache_dtype not in ("native", "int8", "int4"):
            raise ValueError(
                f"kv_cache_dtype={kv_cache_dtype!r}: expected 'native', "
                "'int8' or 'int4'"
            )
        if prefill_chunk is not None and (
            prefill_chunk < page_size or prefill_chunk % page_size
        ):
            raise ValueError(
                f"prefill_chunk must be a positive multiple of "
                f"page_size {page_size}, got {prefill_chunk}"
            )
        self.lm = lm
        self.variables = variables
        self.name = name
        self.page_size = page_size
        self.kv_cache_dtype = kv_cache_dtype
        self.quantized = kv_cache_dtype != "native"
        self._chunk = prefill_chunk
        g = lm.graph
        self._embed = g.node("embed").module
        self._blocks = [g.node(n).module for n in lm.block_names]
        block0 = self._blocks[0]
        self._heads = block0.cache_heads
        self._head_dim = block0.head_dim
        pps = -(-lm.max_len // page_size)
        if pool_pages is None:
            pool_pages = slots * pps + 1
        self._pager = Pager(pool_pages, slots, pps)
        heads, hd = self._heads, self._head_dim

        if kv_cache_dtype == "int4" and hd % 2:
            raise ValueError(
                f"kv_cache_dtype='int4' needs an even head_dim, got {hd}"
            )
        vw = hd // 2 if kv_cache_dtype == "int4" else hd

        def one_pool():
            if self.quantized:
                return (
                    jnp.zeros(
                        (pool_pages, heads, page_size, vw), jnp.int8
                    ),
                    jnp.zeros(
                        (pool_pages, heads, page_size, 1), jnp.float32
                    ),
                )
            return jnp.zeros(
                (pool_pages, heads, page_size, hd), block0.dtype
            )

        self._pools = [(one_pool(), one_pool()) for _ in lm.block_names]
        self._queue: collections.deque[_PrefillJob] = collections.deque()
        self._slots: list[_PrefillJob | None] = [None] * slots
        self._table_dev = None
        self._fn_cache: dict[Any, Any] = {}
        self.prefill_tokens = 0
        self.handoffs = 0
        # -- sequence-parallel long-context prefill ------------------------
        # ``PrefillConfig{sp_threshold, sp_width}``: jobs of at least
        # the threshold bypass the pool/chunk loop entirely — one
        # sp-sharded whole-span program (``parallel/sp_prefill``)
        # produces the handoff in a single :meth:`step` dispatch, and
        # the prompt's O(S^2) attention splits over the ring instead of
        # serializing on one chip. Failures fall back to the chunk path
        # when the pool can cover the job, else fail the request
        # cleanly through :attr:`failed_jobs` (drained by
        # ``DisaggServer.tick``).
        self._sp_cfg = prefill
        self._sp: SPPrefiller | None = None
        self.sp_prefills = 0
        self.failed_jobs: list[tuple[int, str]] = []
        if prefill is not None and prefill.enabled:
            mesh = sp_mesh
            if mesh is None:
                mesh = build_sp_mesh(
                    prefill.sp_width, 1, prefill.sp_axis
                )
            self._sp = SPPrefiller(
                lm, variables, mesh, page_size,
                kv_cache_dtype=kv_cache_dtype,
                sp_axis=prefill.sp_axis,
                tp_axis=(
                    "tp" if "tp" in getattr(mesh, "shape", {}) else None
                ),
                name=f"{name}-sp",
            )
            global_metrics().set_gauge(
                "prefill.sp_width", float(self._sp.sp)
            )
        _LIVE_WORKERS.add(self)
        global_compile_sentinel().register(
            "disagg.prefill",
            size_fn=aggregate_size_fn(_LIVE_WORKERS, _worker_family_size),
        )

    # -- compiled pieces ---------------------------------------------------

    def _chunk_fn(self, cbucket: int, n_pad: int):
        """One chunk pass over [pos0, pos0 + cbucket): the decode
        batcher's ``_prefill_suffix_fn`` body minus the sampling tail
        (the prefill tier never emits — the first token samples
        decode-side on the suffix pass). Specializes per (chunk
        bucket, pow2 window pages)."""
        key = ("chunk", cbucket, n_pad)
        if key in self._fn_cache:
            return self._fn_cache[key]

        @partial(jax.jit, donate_argnums=(1,))
        def chunkfn(variables, pools, pages, ids, pos):
            pos0 = pos[0]
            pos_ids = pos0 + jnp.arange(cbucket)[None]
            h = self._embed.apply(
                variables["embed"], ids, pos_ids,
                method="embed_positions",
            )
            new_pools = []
            for name, block, (kp, vp) in zip(
                self.lm.block_names, self._blocks, pools
            ):
                h, kp, vp = block.apply(
                    variables[name], h, kp, vp, pages, pos0,
                    method="prefill_chunk_paged",
                )
                new_pools.append((kp, vp))
            return new_pools

        self._fn_cache[key] = chunkfn
        return chunkfn

    def _gather_fn(self, nb: int):
        """Gather ``nb`` physical pages' K/V off every block's pool in
        one program (ONE device->host fetch for the whole handoff)."""
        key = ("gather", nb)
        if key in self._fn_cache:
            return self._fn_cache[key]

        @jax.jit
        def gather(pools, pages):
            return [
                jax.tree.map(lambda pool: pool[pages], pair)
                for pair in pools
            ]

        self._fn_cache[key] = gather
        return gather

    # -- request lifecycle -------------------------------------------------

    def submit(self, req_id: int, prompt) -> int:
        """Queue one prompt for prefill; returns the number of full
        pages the eventual handoff will cover. Raises ``ValueError``
        for prompts with no full page or that can never fit the
        pool — the placement policy screens both, so reaching here is
        a caller bug."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        s0 = prompt.shape[0]
        m = (s0 - 1) // self.page_size
        if m < 1:
            raise ValueError(
                f"prompt of {s0} tokens has no full {self.page_size}-"
                "token page to hand off"
            )
        # sp-eligible jobs never touch the pool (the sp program holds
        # the whole span sp-sharded), so the pool bound does not apply.
        if m > self._pager.num_allocatable and not self.sp_eligible(s0):
            raise ValueError(
                f"prompt needs {m} pages but the prefill pool holds "
                f"{self._pager.num_allocatable}"
            )
        self._queue.append(
            _PrefillJob(
                req_id=req_id, prompt=prompt, target=m * self.page_size
            )
        )
        return m

    def cancel(self, req_id: int) -> bool:
        """Drop a queued or mid-prefill job (its pages free
        immediately). False if the job is not here (already handed
        off, or never submitted)."""
        for i, job in enumerate(self._queue):
            if job.req_id == req_id:
                del self._queue[i]
                return True
        for i, job in enumerate(self._slots):
            if job is not None and job.req_id == req_id:
                self._pager.free_slot(i)
                self._slots[i] = None
                return True
        return False

    def pending(self) -> int:
        """Jobs queued or mid-prefill."""
        return len(self._queue) + sum(
            1 for j in self._slots if j is not None
        )

    def sp_eligible(self, s0: int) -> bool:
        """Whether a prompt of ``s0`` tokens takes the
        sequence-parallel path (``PrefillConfig.sp_threshold``) —
        also consulted by the placement policy, since sp jobs are
        exempt from the pool-capacity bound."""
        return (
            self._sp is not None
            and s0 >= self._sp_cfg.sp_threshold
            and (s0 - 1) // self.page_size >= 1
        )

    def _sp_pass(self, job: _PrefillJob) -> KVHandoff | None:
        """Run one sp-eligible job through the sp-sharded whole-span
        program: the entire prefill in ONE dispatch, handoff built
        straight from the program's page-major output — the job never
        touches the pool. On failure: chunk-path fallback when the
        pool can cover the job (front re-queue, FIFO restored), else
        the request fails cleanly via :attr:`failed_jobs`."""
        tracer = global_tracer()
        t0 = tracer.now() if tracer.enabled else 0.0
        try:
            m, blocks = self._sp.prefill(job.prompt)
        except Exception as e:  # noqa: BLE001 — degrade, never wedge
            if job.target // self.page_size <= (
                self._pager.num_allocatable
            ):
                log.exception(
                    "sp prefill failed for request %d; falling back "
                    "to the chunked path", job.req_id,
                )
                job.no_sp = True  # never re-picked by the sp scan
                self._queue.appendleft(job)
            else:
                self.failed_jobs.append((job.req_id, str(e)[:200]))
            return None
        toks = m * self.page_size
        self.prefill_tokens += toks
        self.sp_prefills += 1
        self.handoffs += 1
        reg = global_metrics()
        reg.inc("disagg.prefill_tokens_total", float(toks))
        reg.inc("disagg.sp_prefills_total")
        if tracer.enabled:
            tracer.add_span(
                "disagg.sp_prefill",
                start=t0,
                end=tracer.now(),
                request=job.req_id,
                pages=m,
                sp=self._sp.sp,
            )
        global_flight_recorder().record(
            "sp_prefill",
            request=job.req_id,
            pages=m,
            sp=self._sp.sp,
            tier="prefill",
        )
        return KVHandoff(
            req_id=job.req_id,
            prompt=job.prompt,
            page_size=self.page_size,
            n_pages=m,
            quantized=self.quantized,
            blocks=blocks,
            kv_dtype=self.kv_cache_dtype,
        )

    def _admit(self) -> None:
        for i, job in enumerate(self._slots):
            if job is not None or not self._queue:
                continue
            nxt = self._queue[0]
            n_pages = nxt.target // self.page_size
            # FIFO head-of-line, all-or-nothing — the batcher's own
            # admission discipline.
            if not self._pager.alloc(i, n_pages):
                self._pager.free_slot(i)
                return
            nxt = self._queue.popleft()
            nxt.slot, nxt.pf_done = i, 0
            self._slots[i] = nxt

    def _pass(self, job: _PrefillJob) -> None:
        P = self.page_size
        pos0 = job.pf_done
        clen = min(self._chunk or job.target, job.target - pos0)
        n_strip = (pos0 + clen) // P
        owned = self._pager.owned(job.slot)
        n_pad = 1
        while n_pad < n_strip:
            n_pad *= 2
        pages = owned[:n_strip] + [0] * (n_pad - n_strip)
        ids = np.zeros((1, clen), np.int32)
        ids[0, :] = job.prompt[pos0:pos0 + clen]
        self._pools = self._chunk_fn(clen, n_pad)(
            self.variables,
            self._pools,
            jnp.asarray(np.asarray(pages, np.int32)),
            jnp.asarray(ids),
            jnp.asarray(np.asarray([pos0], np.int32)),
        )
        job.pf_done = pos0 + clen
        self.prefill_tokens += clen
        global_metrics().inc(
            "disagg.prefill_tokens_total", float(clen)
        )

    def _finish(self, job: _PrefillJob) -> KVHandoff:
        P = self.page_size
        m = job.target // P
        owned = self._pager.owned(job.slot)[:m]
        nb = 1
        while nb < m:
            nb *= 2
        pages = np.asarray(owned + [0] * (nb - m), np.int32)
        gathered = self._gather_fn(nb)(self._pools, jnp.asarray(pages))
        host = jax.device_get(gathered)  # ONE fused fetch
        blocks = [
            jax.tree.map(lambda x: np.asarray(x)[:m], pair)
            for pair in host
        ]
        self._pager.free_slot(job.slot)
        self._slots[job.slot] = None
        self.handoffs += 1
        return KVHandoff(
            req_id=job.req_id,
            prompt=job.prompt,
            page_size=P,
            n_pages=m,
            quantized=self.quantized,
            blocks=blocks,
            kv_dtype=self.kv_cache_dtype,
        )

    def step(self) -> list[KVHandoff]:
        """One prefill-tier scheduling round: dispatch at most ONE
        sp-eligible job through the sequence-parallel program (its
        whole span in one sp-sharded pass — the sp counterpart of the
        chunk-pass stall bound), then admit waiting jobs, run ONE
        chunk pass per active slot, and hand off the finished ones.
        Returns this round's completed handoffs (possibly empty)."""
        done: list[KVHandoff] = []
        if self._sp is not None:
            for i, job in enumerate(self._queue):
                if not job.no_sp and self.sp_eligible(
                    job.prompt.shape[0]
                ):
                    del self._queue[i]
                    h = self._sp_pass(job)
                    if h is not None:
                        done.append(h)
                    break  # one sp dispatch per step — the stall bound
        self._admit()
        tracer = global_tracer()
        for job in list(self._slots):
            if job is None:
                continue
            t0 = tracer.now() if tracer.enabled else 0.0
            self._pass(job)
            if tracer.enabled:
                tracer.add_span(
                    "disagg.prefill_chunk",
                    start=t0,
                    end=tracer.now(),
                    request=job.req_id,
                    pos0=int(job.pf_done),
                )
            if job.pf_done >= job.target:
                done.append(self._finish(job))
        return done

    def stats(self) -> dict:
        ps = self._pager.stats()
        return {
            "queued": len(self._queue),
            "active": sum(1 for j in self._slots if j is not None),
            "prefill_tokens": self.prefill_tokens,
            "handoffs": self.handoffs,
            "sp_prefills": self.sp_prefills,
            "sp_width": self._sp.sp if self._sp is not None else 1,
            "pool_pages": ps.num_pages,
            "pages_in_use": ps.in_use,
        }


@dataclasses.dataclass
class _Routed:
    """Server-side request state: where the request currently lives."""

    tier: str  # "prefill" | "decode" | "done"
    rid: int | None = None  # decode-batcher id once submitted there
    kwargs: dict | None = None  # deferred decode.submit arguments
    t_submit: float = 0.0


class DisaggServer:
    """The disaggregated submit path: one placement policy in front of
    a :class:`PrefillWorker` and a decode-side
    :class:`~adapt_tpu.runtime.continuous.ContinuousBatcher` (which
    must run ``kv_layout="paged"`` — the handoff lands through the
    paged prefix cache).

    Mirrors the batcher's synchronous driver surface (``submit`` /
    ``tick`` / ``cancel`` / ``run`` / ``result`` / ``stats``), so the
    load harness drives either interchangeably. Each :meth:`tick`:
    heartbeats the prefill pool's role-tagged lease, runs one prefill
    scheduling round, lands completed handoffs over the loopback wire
    (real frames — the cross-host format), submits the landed
    requests to the decode batcher (prefix-cache hit admission), and
    runs one decode tick. Single-threaded by design (v1): the chunk
    pass is the stall bound the harness measures."""

    def __init__(
        self,
        decode: ContinuousBatcher,
        prefill: PrefillWorker,
        config: DisaggConfig | None = None,
        registry=None,
        lease_ttl_s: float = 2.0,
        telemetry_url: str | None = None,
        wire_codec: str | None = None,
    ):
        if not decode._paged:
            raise ValueError(
                "DisaggServer requires a paged decode batcher "
                "(kv_layout='paged') — the handoff lands through the "
                "prefix cache"
            )
        if prefill.page_size != decode._page:
            raise ValueError(
                f"prefill page size {prefill.page_size} != decode page "
                f"size {decode._page}"
            )
        if prefill.kv_cache_dtype != decode._kv_dtype:
            raise ValueError(
                "prefill/decode kv_cache_dtype mismatch "
                f"(prefill {prefill.kv_cache_dtype!r}, decode "
                f"{decode._kv_dtype!r})"
            )
        if prefill.lm.vocab != decode.lm.vocab:
            raise ValueError("prefill/decode vocab mismatch")
        self.decode = decode
        self.prefill = prefill
        self.cfg = config or DisaggConfig()
        #: MSG_KV_PAGES wire codec (``pack_handoff``). Explicit arg
        #: wins; otherwise inherited from the decode batcher's
        #: ``CacheTierConfig.wire_codec`` when it runs a cache tier
        #: (ONE config names every tier boundary's codec); "raw" —
        #: today's zero-copy frames — when neither names one.
        if wire_codec is None:
            tier_cfg = getattr(decode, "_tier_cfg", None)
            wire_codec = tier_cfg.wire_codec if tier_cfg else "raw"
        from adapt_tpu.ops.quantize import PAGE_CODECS

        if wire_codec not in PAGE_CODECS:
            raise ValueError(
                f"wire_codec={wire_codec!r}: expected one of "
                f"{PAGE_CODECS}"
            )
        self.wire_codec = wire_codec
        if self.cfg.busy_prompt_threshold <= decode._page:
            log.warning(
                "busy_prompt_threshold %d <= page size %d: busy-tier "
                "prompts just over the threshold may have no full page "
                "and will collocate anyway",
                self.cfg.busy_prompt_threshold, decode._page,
            )
        self._registry = registry
        self._lease_ttl = lease_ttl_s
        self._lease_key = f"prefill:{prefill.name}"
        #: Lease metadata. ``telemetry_url`` (the tier's exporter
        #: ``/telemetry.json``) advertises the HTTP-PULL federation
        #: fallback: a dispatcher that does not own this process's
        #: comm link discovers the endpoint off the lease and polls it
        #: (``utils.telemetry.FederatedStore.poll_registry``) — the
        #: lease is the membership record, so it is also the telemetry
        #: directory.
        self._lease_meta = {"role": "prefill"}
        if telemetry_url is not None:
            self._lease_meta["telemetry"] = telemetry_url
        if registry is not None:
            # ROLE-TAGGED lease: the pipeline dispatcher's _acquire
            # skips role-tagged workers, and this policy stops routing
            # to the tier when the lease expires (alive(role=)).
            self._lease_token = registry.register(
                self._lease_key,
                meta=dict(self._lease_meta),
                ttl_s=lease_ttl_s,
            )
        #: Lease-meta capacity book refresh (rate-limited): the
        #: prefill tier's ``/fleet/capacity`` path. register() on an
        #: EXISTING key replaces meta and renews the lease without
        #: firing join watchers, so the refresh is free of membership
        #: side effects. Gated on the decode batcher's capacity plane
        #: — ``CapacityConfig(enabled=False)`` is ONE switch for the
        #: whole replica.
        cap = decode._capacity
        self._cap_lease_s = (
            cap.cfg.lease_refresh_s if cap is not None else 0.0
        )
        self._cap_last_lease = 0.0
        #: Drain switch (close()): stops lease keepalive/resurrection
        #: so the placement policy falls back to collocated for good.
        self._closed = False
        self._route: dict[int, _Routed] = {}
        self._done: dict[int, np.ndarray] = {}
        #: sid -> decode rid for CLAIMED requests (route entries prune
        #: at claim so a long-lived server does not grow per-request
        #: state; this bounded map keeps logprobs() reachable after
        #: result() — same eviction discipline as the batcher's
        #: unclaimed-logprobs cap).
        self._claimed_rids: collections.OrderedDict[int, int] = (
            collections.OrderedDict()
        )
        self._next_sid = 0
        # Placement books (instance-scoped, mirrored as disagg.*
        # counters).
        self.disaggregated = 0
        self.collocated = 0
        self.failed = 0
        # Closed-loop degradation: a scheduler-configured decode
        # batcher's controller gains its busy-threshold rung the
        # moment this server fronts it (the controller holds the
        # server weakly — see runtime/scheduler).
        ctrl = getattr(decode, "_controller", None)
        if ctrl is not None:
            ctrl.attach_disagg(self)

    # -- placement ---------------------------------------------------------

    def _prefill_alive(self) -> bool:
        if self._registry is None:
            return True
        return self._lease_key in self._registry.alive(role="prefill")

    def _placement(self, s0: int, slo: SLOSpec | None = None) -> bool:
        """True = disaggregate. The knobs live in
        ``config.DisaggConfig``; every fallback is collocated.
        PRIORITY is visible here (``SLOSpec.priority``): a
        high-priority request (> 0) always sees the tight BUSY
        threshold — its TTFT budget is the one the decode tier's
        in-tick prefill stalls would blow, and its long prompt is
        exactly the work the decode tier must not pay inline while
        lower classes wait on inter-token latency."""
        m = (s0 - 1) // self.decode._page
        if m < 1:
            return False  # nothing to hand off
        slots = self.decode.slots
        occupancy = sum(
            1 for s in slots if s.req is not None
        ) / len(slots)
        busy = occupancy >= self.cfg.busy_occupancy or (
            slo is not None and slo.priority > 0
        )
        threshold = (
            self.cfg.busy_prompt_threshold
            if busy
            else self.cfg.prompt_threshold
        )
        if s0 < threshold:
            return False
        if m > self.prefill._pager.num_allocatable and not (
            self.prefill.sp_eligible(s0)
        ):
            # The prefill pool can never cover it — unless the tier's
            # sequence-parallel path will take it (sp jobs hold their
            # span sp-sharded in the program, never in the pool).
            return False
        return self._prefill_alive()

    # -- request lifecycle -------------------------------------------------

    def submit(
        self,
        prompt,
        steps: int,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
        rng=None,
        stop: list | None = None,
        on_token: Callable[[int, int, int], None] | None = None,
        slo: SLOSpec | None = None,
    ) -> int:
        """Queue one request; returns the SERVER-side id (use it with
        :meth:`cancel` / :meth:`result`). Collocated requests submit
        to the decode batcher immediately; disaggregated ones enter
        the prefill tier and reach the decode batcher when their
        pages land (TTFT/queue-wait/SLO all measure from THIS call —
        the decode submit carries the original arrival stamp)."""
        dec = self.decode
        # THE decode-side validation body, shared with the collocated
        # path: a disaggregated request fails HERE, synchronously,
        # exactly like a collocated submit would — never minutes later
        # at handoff landing.
        prompt, _ = dec.validate_request(
            prompt, steps, temperature=temperature, top_k=top_k,
            top_p=top_p, rng=rng, stop=stop, slo=slo,
        )
        s0 = prompt.shape[0]
        if s0 > self.prefill.lm.max_len:
            raise ValueError(
                f"prompt {s0} exceeds the prefill tier's max_len "
                f"{self.prefill.lm.max_len}"
            )
        sid = self._next_sid
        self._next_sid += 1
        if on_token is not None:
            # Callbacks must see the SERVER id — the id this submit
            # returned and the one cancel()/result() accept. The decode
            # batcher invokes them with its OWN rid, which desyncs from
            # sids as soon as placements interleave; a caller feeding
            # the callback's id back into cancel() would then target a
            # different request.
            user_cb = on_token

            def on_token(rid, tok, idx, _sid=sid, _cb=user_cb):
                _cb(_sid, tok, idx)

        kwargs = dict(
            steps=steps,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            eos_id=eos_id,
            rng=rng,
            stop=stop,
            on_token=on_token,
            slo=slo,
        )
        now = time.perf_counter()
        if self._placement(s0, slo):
            # Admission-control pre-check (records the rejection like
            # a collocated submit's would): a request the decode queue
            # would reject RIGHT NOW must fail synchronously, not
            # after its whole prefill ran — the landing-time rejection
            # in _land still backs up the race window.
            dec.admission_check(slo, request=sid)
            self.disaggregated += 1
            global_metrics().inc("disagg.disaggregated_total")
            self._route[sid] = _Routed(
                tier="prefill", kwargs=kwargs, t_submit=now
            )
            self.prefill.submit(sid, prompt)
            self._route[sid].kwargs["prompt"] = prompt
        else:
            self.collocated += 1
            global_metrics().inc("disagg.collocated_total")
            rid = dec.submit(prompt, t_submit=now, **kwargs)
            self._route[sid] = _Routed(
                tier="decode", rid=rid, t_submit=now
            )
        return sid

    def cancel(self, sid: int) -> bool:
        r = self._route.get(sid)
        if r is None or r.tier == "done":
            return False
        if r.tier == "decode":
            return self.decode.cancel(r.rid)
        # Still in the prefill tier: nothing streamed yet — drop with
        # an empty result, and emit the finish lifecycle edge so the
        # admit/finish books a driver reads off the flight recorder
        # stay coherent across tiers.
        if self.prefill.cancel(sid):
            self._done[sid] = np.zeros((0,), np.int32)
            r.tier = "done"
            r.kwargs = None  # drop the retained prompt/rng/callback
            global_flight_recorder().record(
                "cancel", request=sid, state="prefill"
            )
            global_flight_recorder().record(
                "finish", request=sid, reason="cancelled", tokens=0
            )
            return True
        return False

    def _fail(self, sid: int, err: Exception) -> None:
        """A handoff that cannot land fails the REQUEST cleanly: empty
        result (no wedged ``result()``), loud flight events, serving
        continues."""
        self.failed += 1
        self._done[sid] = np.zeros((0,), np.int32)
        r = self._route.get(sid)
        if r is not None:
            r.tier = "done"
            r.kwargs = None  # drop the retained prompt/rng/callback
        global_metrics().inc("disagg.handoff_failed_total")
        global_flight_recorder().record(
            "request_failed", request=sid, reason=str(err)[:200]
        )
        global_flight_recorder().record(
            "finish", request=sid, reason="failed", tokens=0
        )
        log.error("KV handoff failed for request %d: %s", sid, err)

    def _land(self, handoff: KVHandoff) -> None:
        """Stream one handoff over the wire and land it: frame ->
        loopback transport -> parse -> adopt into the decode pool ->
        decode submit (prefix-cache-hit admission)."""
        sid = handoff.req_id
        r = self._route.get(sid)
        if r is None or r.tier != "prefill":
            return  # cancelled between chunk passes and handoff
        t0 = time.perf_counter()
        try:
            msg = pack_handoff(handoff, wire_codec=self.wire_codec)
            wire_bytes = sum(
                p.nbytes if isinstance(p, memoryview) else len(p)
                for p in frame_parts(msg)
            )
            raw_bytes = handoff_raw_nbytes(handoff)
            landed = unpack_handoff(loopback(msg))
            adopted = self.decode.adopt_prefill_pages(
                landed.prompt,
                landed.blocks,
                landed.page_size,
                landed.kv_dtype,
            )
        except (HandoffError, ValueError) as e:
            self._fail(sid, e)
            return
        wall = time.perf_counter() - t0
        reg = global_metrics()
        # handoff_bytes counts WIRE (post-codec) bytes — the frames
        # actually shipped; handoff_bytes_raw the uncompressed payload,
        # so the wire compression ratio is raw/bytes on any dashboard
        # (they coincide when wire_codec == "raw").
        reg.inc("disagg.handoff_bytes", float(wire_bytes))
        reg.inc("disagg.handoff_bytes_raw", float(raw_bytes))
        reg.inc("disagg.pages_streamed", float(handoff.n_pages))
        reg.observe("disagg.handoff_s", wall)
        global_flight_recorder().record(
            "kv_handoff",
            request=sid,
            pages=handoff.n_pages,
            adopted=adopted,
            bytes=wire_bytes,
            blocks=len(handoff.blocks),
            wall_s=round(wall, 6),
        )
        kwargs = dict(r.kwargs)
        prompt = kwargs.pop("prompt")
        try:
            # submit() pre-validated the decode-side constraints, but
            # this stays guarded: a late rejection here must fail ONLY
            # this request (the module contract), never escape tick().
            rid = self.decode.submit(
                prompt, t_submit=r.t_submit, **kwargs
            )
        except (ValueError, TypeError, QueueFullError) as e:
            # QueueFullError: admission control filled up while the
            # prefill ran. The adopted pages stay registered rc=0 in
            # the prefix LRU (land-then-LRU — evictable capacity, or
            # a free prefix hit for a retry), the prefill tier's own
            # pages were already freed at handoff, and ONLY this
            # request fails; the batcher recorded request_rejected.
            self._fail(sid, e)
            return
        r.tier, r.rid, r.kwargs = "decode", rid, None

    def tick(self) -> int:
        """One server scheduling round: prefill step -> land handoffs
        -> decode tick. Returns the decode tick's active-slot count.

        When the decode batcher runs the pipelined tick runtime
        (``config.RuntimeConfig(pipeline_depth=2)``), its tick() here
        dispatches round *t* and commits round *t−1* — the handoffs
        landed above still enter admission on THIS call (admission is
        dispatch-side), only result delivery lags one round. The
        driver needs no pacing changes: :meth:`run`'s busy loop keys
        off slot occupancy, which the batcher releases at commit, and
        ``_collect`` drains the in-flight round explicitly before
        claiming results."""
        if (
            self._registry is not None
            and not self._closed
            and not self._registry.heartbeat(
                self._lease_key, self._lease_ttl
            )
        ):
            # The lease expired between ticks (e.g. a long compile gap
            # outlasted the TTL). This tier is self-evidently alive —
            # it is ticking — so re-register (etcd keepalive
            # semantics: expiry means re-register, not retire) instead
            # of silently degrading every future placement to
            # collocated. ``close()`` is the drain switch: a closed
            # server never resurrects its lease.
            self._lease_token = self._registry.register(
                self._lease_key,
                meta=dict(self._lease_meta),
                ttl_s=self._lease_ttl,
            )
        if (
            self._registry is not None
            and not self._closed
            and self._cap_lease_s > 0
        ):
            cap_now = time.monotonic()
            if cap_now - self._cap_last_lease >= self._cap_lease_s:
                self._cap_last_lease = cap_now
                self._lease_meta["capacity"] = prefill_tier_book(
                    self.prefill
                )
                self._lease_token = self._registry.register(
                    self._lease_key,
                    meta=dict(self._lease_meta),
                    ttl_s=self._lease_ttl,
                )
        for handoff in self.prefill.step():
            self._land(handoff)
        if self.prefill.failed_jobs:
            # An sp job that could neither run nor fall back to the
            # chunk path (pool too small for its span): fail the
            # REQUEST cleanly, exactly like a corrupt handoff.
            for sid, err in self.prefill.failed_jobs:
                self._fail(sid, RuntimeError(err))
            self.prefill.failed_jobs.clear()
        return self.decode.tick()

    def drain(self) -> int:
        """Commit the decode tier's in-flight pipelined round, if any
        (no-op at depth 1 / when idle) — the server-level pipeline
        boundary drivers reach for between measurement phases."""
        return self.decode.drain()

    def _busy(self) -> bool:
        if self.prefill.pending():
            return True
        st = self.decode.stats()
        return bool(st["active"] or st["queued"])

    def run(self, max_ticks: int = 100_000) -> dict[int, np.ndarray]:
        """Tick until every submitted request completed; returns
        ``{server_id: tokens}`` (failed/cancelled-in-prefill requests
        map to empty arrays) and clears the finished set."""
        ticks = 0
        while self._busy():
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(f"run() exceeded {max_ticks} ticks")
        return self._collect()

    def _collect(self) -> dict[int, np.ndarray]:
        # Pipeline boundary: commit any in-flight decode round before
        # claiming results (run() below would also drain, but only
        # after its occupancy check — be explicit at the handoff).
        self.decode.drain()
        dec_done = self.decode.run(max_ticks=1)  # drained: returns dict
        out = dict(self._done)
        self._done = {}
        claimed = list(out)
        for sid, r in self._route.items():
            if r.tier == "decode" and r.rid in dec_done:
                out[sid] = dec_done[r.rid]
                claimed.append(sid)
        # Claimed requests leave the routing table — a long-lived
        # server must not grow one entry per request served.
        for sid in claimed:
            self._remember_rid(sid)
        return out

    def result(self, sid: int, max_ticks: int = 100_000) -> np.ndarray:
        """Drive ticks until ``sid`` finishes; returns (and claims) its
        tokens — empty for a failed or prefill-cancelled request,
        never a wedge."""
        ticks = 0
        while True:
            if sid in self._done:
                self._remember_rid(sid)
                return self._done.pop(sid)
            r = self._route.get(sid)
            if r is None:
                raise KeyError(f"unknown request {sid}")
            if r.tier == "decode":
                # Claim opportunistically; decode.run() only returns
                # when IT is drained, so tick until the rid lands.
                with self.decode._cv:
                    if r.rid in self.decode._done:
                        out = self.decode._done.pop(r.rid)
                        self._remember_rid(sid)
                        return out
            if r.tier == "done":
                raise KeyError(f"request {sid} already claimed")
            self.tick()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError(
                    f"result({sid}) exceeded {max_ticks} ticks"
                )

    def _remember_rid(self, sid: int) -> None:
        """Prune ``sid``'s routing entry (claimed), keeping its decode
        rid in the bounded claimed map so :meth:`logprobs` still
        resolves."""
        r = self._route.pop(sid, None)
        if r is not None and r.rid is not None:
            self._claimed_rids[sid] = r.rid
            while len(self._claimed_rids) > 4096:
                self._claimed_rids.popitem(last=False)

    def logprobs(self, sid: int) -> np.ndarray:
        r = self._route.get(sid)
        rid = r.rid if r is not None else self._claimed_rids.get(sid)
        if rid is None:
            raise KeyError(f"no logprobs for request {sid}")
        return self.decode.logprobs(rid)

    # Harness compatibility: warmup() reads the model + buckets off
    # the driven object.
    @property
    def lm(self):
        return self.decode.lm

    @property
    def prompt_buckets(self):
        return self.decode.prompt_buckets

    def capacity_book(self) -> dict | None:
        """One self-describing book for the whole disaggregated pair:
        the decode batcher's capacity book with the prefill tier's
        book nested under ``"prefill"`` (None when the capacity plane
        is disabled). What a DisaggServer process hands
        ``serve_metrics(capacity_provider=...)``."""
        book = self.decode.capacity_book()
        if book is None:
            return None
        book = dict(book)
        book["prefill"] = prefill_tier_book(self.prefill)
        return book

    def stats(self) -> dict:
        out = self.decode.stats()
        pf = self.prefill.stats()
        out.update(
            prefill_queued=pf["queued"],
            prefill_active=pf["active"],
            prefill_tier_tokens=pf["prefill_tokens"],
            handoffs=pf["handoffs"],
            disaggregated=self.disaggregated,
            collocated_submits=self.collocated,
            handoff_failed=self.failed,
            # Sequence-parallel tier books: the worker's sp-path
            # dispatch count and live ring width (1 = sp off). A
            # decode-side sp_prefills (collocated sp) would be
            # clobbered here by design — a DisaggServer's sp work
            # happens in the prefill tier.
            sp_prefills=pf["sp_prefills"],
            sp_width=pf["sp_width"],
        )
        # "queued" should reflect the whole server, or a driver's
        # drain loop would stop while the prefill tier still holds
        # work.
        out["queued"] += pf["queued"] + pf["active"]
        return out

    def close(self) -> None:
        """Drain the prefill tier: release its role-tagged lease and
        stop resurrecting it — every later placement collocates. THE
        operator drain switch (a raw registry deregister alone would
        be re-registered by the next tick's keepalive). The decode
        batcher's own close() is the caller's to run."""
        self._closed = True
        if self._registry is not None:
            self._registry.deregister(
                self._lease_key, self._lease_token
            )
