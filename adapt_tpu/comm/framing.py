"""Wire framing for cross-host messages.

Capability parity with the reference's hand-rolled protocol
(``/root/reference/src/node_state.py:39-161``): length-prefixed framing
(there: 8-byte big-endian length + chunked non-blocking sends with a
``select`` spin; here: the same 8-byte BE length prefix over blocking
sockets — the chunk/spin loop is an artifact of non-blocking sockets the
design doesn't need) and a fixed routing header (there: a 4-byte
partition index, ``src/dispatcher.py:209-213``; here: a typed header
carrying message type, stage index, request id, attempt and a FLAGS
byte so re-dispatch and exactly-once work across hosts too).

Zero-copy hot path (the codec-framing design, ``comm/codec.py``):

- **Send** is a scatter write: ``Message.payload`` may be bytes, any
  buffer view, or a LIST of buffer parts (``codec.pack_frames``), and
  :func:`send_msg` hands ``[prefix+header, *parts]`` to
  ``socket.sendmsg`` — the kernel gathers, so the payload is never
  concatenated host-side.
- **Receive** lands each frame in ONE pre-sized ``bytearray`` via
  ``recv_into`` (no chunk-list join) and ``Message.payload`` is a
  memoryview of it — ``codec.unpack`` then returns arrays viewing that
  same buffer. Use :func:`payload_bytes` where real ``bytes`` are
  needed (JSON control payloads).

Observability annex (``FLAG_TRACE_ANNEX``): a message may carry a small
out-of-band blob — serialized tracer spans the remote worker recorded
for this request (``utils.tracing.export_spans``) — without disturbing
the payload's zero-copy contract: the annex rides length-prefixed
BEFORE the payload, so the payload remains one contiguous view of the
receive buffer. Cost when unused: one flags byte per frame.

Page-range annex (``FLAG_PAGE_ANNEX``): the disaggregated-serving KV
handoff (``runtime/disagg``) describes its payload — concatenated
codec frames holding whole KV-cache PAGES — in a second
length-prefixed annex (page count, per-tensor frame lengths, layout
geometry) that rides after the trace annex, still ahead of the
payload. Same contract: the page chunks themselves stay scatter-write
parts on send and one contiguous view on receive; the annex is the
only part that is parsed.

``frame_parts`` / ``parse_frame`` are the pure halves of
``send_msg`` / ``recv_msg`` — in-process transports (the
disaggregated handoff loopback) and tests reuse them so the wire
format cannot fork from the socket paths.
"""

from __future__ import annotations

import socket
import struct
from dataclasses import dataclass
from typing import Any

#: msg types (reference: implied by port number — 6000 data / 6001 config /
#: 6003 results; here: explicit enum in-band on one port).
MSG_DATA = 1
MSG_CONFIG = 2
MSG_RESULT = 3
MSG_ACK = 4
MSG_ERROR = 5
#: Disaggregated-serving KV handoff frame (``runtime/disagg``): payload
#: is concatenated codec frames of whole KV pages, described by the
#: page-range annex. (6..17 are claimed by ``comm.remote`` —
#: MSG_SET_ROUTE=16 / MSG_DATA_CHAINED=17 live there; the type byte is
#: ONE namespace across both modules, so new types must collide with
#: neither.)
MSG_KV_PAGES = 18
#: Telemetry federation report (``utils/telemetry``): payload is one
#: JSON ``TelemetryReporter.collect()`` dict — windowed metric deltas,
#: flight-event deltas, span exports — pushed periodically by a worker
#: process to its parent; ``request_id`` carries the report's
#: per-process sequence number. Next free value after MSG_KV_PAGES=18
#: in the shared type-byte namespace (1-5 here, 6-17 in
#: ``comm.remote``); the next new type is 20.
MSG_TELEMETRY = 19

#: header: type, stage_index (signed: canary probes use PING_STAGE = -1),
#: request_id (signed: probe ids are negative, disjoint from requests),
#: attempt, flags.
_HEADER = struct.Struct(">BiqIB")
_LEN = struct.Struct(">Q")
_ANNEX_LEN = struct.Struct(">I")

#: Flags-byte bits. TRACE_ANNEX: a u32-length-prefixed span blob
#: precedes the payload (stitched back into the dispatcher's trace by
#: ``comm.remote.RemoteWorkerProxy``). PAGE_ANNEX: a u32-length-
#: prefixed page-range blob (``runtime/disagg`` KV handoff metadata)
#: follows the trace annex (if any), still ahead of the payload.
FLAG_TRACE_ANNEX = 0x01
FLAG_PAGE_ANNEX = 0x02

#: The reference's ACK byte (src/dispatcher.py:250-260, src/node.py:52,88).
ACK_BYTE = b"\x06"


def _byte_view(part) -> memoryview:
    mv = part if isinstance(part, memoryview) else memoryview(part)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    return mv


def _payload_parts(payload) -> list[memoryview]:
    """Normalize a payload (bytes | buffer | list of either) to flat
    byte views for the scatter send."""
    if isinstance(payload, (list, tuple)):
        views = [_byte_view(p) for p in payload]
        return [v for v in views if v.nbytes]
    mv = _byte_view(payload)
    return [mv] if mv.nbytes else []


def payload_bytes(payload) -> bytes:
    """Materialize a received (or multi-part) payload as bytes — for
    small control payloads (JSON, error strings), not the data path."""
    if isinstance(payload, bytes):
        return payload
    if isinstance(payload, (list, tuple)):
        return b"".join(bytes(_byte_view(p)) for p in payload)
    return bytes(_byte_view(payload))


@dataclass(frozen=True)
class Message:
    msg_type: int
    stage_index: int
    request_id: int
    attempt: int
    #: bytes on receive-construct paths; any buffer view or a list of
    #: buffer parts (``codec.pack_frames``) on the send path.
    payload: Any
    #: Optional out-of-band blob (serialized trace spans). The wire
    #: flags byte is DERIVED from its presence — senders just set
    #: ``annex``; receivers see ``bytes`` or None.
    annex: bytes | None = None
    #: Optional page-range blob (disaggregated KV handoff metadata,
    #: ``runtime/disagg``). Same derived-flag rule as ``annex``.
    page_annex: bytes | None = None


def _sendmsg_all(sock: socket.socket, parts: list[memoryview]) -> None:
    """sendall semantics over ``socket.sendmsg``: loop until every part
    is on the wire, advancing views across partial sends (sendmsg, like
    send, may write any prefix of the gather list)."""
    while parts:
        try:
            sent = sock.sendmsg(parts)
        except (AttributeError, OSError) as e:
            # No sendmsg on this socket object (test doubles) — fall
            # back to sendall per part. OSError other than missing
            # support propagates.
            if not isinstance(e, AttributeError):
                raise
            for p in parts:
                sock.sendall(p)
            return
        while parts and sent >= parts[0].nbytes:
            sent -= parts[0].nbytes
            parts.pop(0)
        if sent:
            parts[0] = parts[0][sent:]


def frame_parts(msg: Message) -> list[memoryview]:
    """The frame as scatter-write parts: ``[length prefix + header
    (+ annexes), *payload views]`` — the pure half of :func:`send_msg`,
    shared with in-process transports (the disaggregated KV-handoff
    loopback) so the wire layout has ONE definition. Zero payload
    copies: the views alias the caller's buffers."""
    parts = _payload_parts(msg.payload)
    flags = 0
    head_extra = b""
    if msg.annex is not None:
        flags |= FLAG_TRACE_ANNEX
        head_extra += _ANNEX_LEN.pack(len(msg.annex)) + msg.annex
    if msg.page_annex is not None:
        flags |= FLAG_PAGE_ANNEX
        head_extra += _ANNEX_LEN.pack(len(msg.page_annex)) + msg.page_annex
    total = _HEADER.size + len(head_extra) + sum(p.nbytes for p in parts)
    header = _LEN.pack(total) + _HEADER.pack(
        msg.msg_type, msg.stage_index, msg.request_id, msg.attempt, flags
    ) + head_extra
    return [memoryview(header), *parts]


def send_msg(sock: socket.socket, msg: Message) -> None:
    # One gather write: prefix+header (+ annexes) and every payload part
    # go to the kernel as-is — zero host-side concatenation of the
    # payload.
    _sendmsg_all(sock, frame_parts(msg))


def _recv_exact_into(
    sock: socket.socket, buf: memoryview, retry_on_timeout: bool = True
) -> None:
    n, off = buf.nbytes, 0
    while off < n:
        try:
            got = sock.recv_into(buf[off:], min(n - off, 1 << 20))
        except TimeoutError:
            if retry_on_timeout:
                # A socket timeout usually exists to bound *sends* (a
                # wedged peer with full buffers must not hold a sender
                # forever). Reads keep the partial frame and retry —
                # liveness is the lease/watchdog's job, and abandoning
                # mid-frame would desync the stream.
                continue
            raise
        if not got:
            raise ConnectionError("peer closed mid-frame")
        off += got


def parse_frame(buf) -> Message:
    """Parse one frame BODY (everything after the 8-byte length prefix)
    into a :class:`Message` — the pure half of :func:`recv_msg`, shared
    with in-process transports. The payload is a memoryview of ``buf``
    (zero-copy: ``codec.unpack`` arrays share its memory); the annexes
    are materialized bytes (small, parsed)."""
    total = len(buf)
    if total < _HEADER.size:
        raise ConnectionError(f"short frame: {total}")
    msg_type, stage_index, request_id, attempt, flags = _HEADER.unpack_from(
        buf
    )
    off = _HEADER.size

    def annex_at(off: int) -> tuple[bytes, int]:
        if total < off + _ANNEX_LEN.size:
            raise ConnectionError(f"short annexed frame: {total}")
        (alen,) = _ANNEX_LEN.unpack_from(buf, off)
        off += _ANNEX_LEN.size
        if total < off + alen:
            raise ConnectionError(f"annex overruns frame: {alen}")
        return bytes(buf[off : off + alen]), off + alen

    annex: bytes | None = None
    page_annex: bytes | None = None
    if flags & FLAG_TRACE_ANNEX:
        annex, off = annex_at(off)
    if flags & FLAG_PAGE_ANNEX:
        page_annex, off = annex_at(off)
    return Message(
        msg_type=msg_type,
        stage_index=stage_index,
        request_id=request_id,
        attempt=attempt,
        payload=memoryview(buf)[off:],
        annex=annex,
        page_annex=page_annex,
    )


def recv_msg(sock: socket.socket, retry_on_timeout: bool = True) -> Message:
    """``retry_on_timeout=False`` turns the socket's timeout into a hard
    receive deadline (used where a silent peer must not hold a serial
    loop — e.g. the gateway's HELLO handshake). The returned payload is
    a memoryview of the frame's single receive buffer (zero-copy:
    ``codec.unpack`` arrays share its memory)."""
    lenbuf = bytearray(_LEN.size)
    _recv_exact_into(sock, memoryview(lenbuf), retry_on_timeout)
    (total,) = _LEN.unpack(lenbuf)
    if total < _HEADER.size:
        raise ConnectionError(f"short frame: {total}")
    buf = bytearray(total)
    _recv_exact_into(sock, memoryview(buf), retry_on_timeout)
    return parse_frame(buf)
