"""Wire framing for cross-host messages.

Capability parity with the reference's hand-rolled protocol
(``/root/reference/src/node_state.py:39-161``): length-prefixed framing
(there: 8-byte big-endian length + chunked non-blocking sends with a
``select`` spin; here: the same 8-byte BE length prefix over blocking
sockets with ``sendall`` — the chunk/spin loop is an artifact of
non-blocking sockets the design doesn't need) and a fixed routing header
(there: a 4-byte partition index, ``src/dispatcher.py:209-213``; here: a
typed header carrying message type, stage index, request id and attempt so
re-dispatch and exactly-once work across hosts too).
"""

from __future__ import annotations

import socket
import struct
from dataclasses import dataclass

#: msg types (reference: implied by port number — 6000 data / 6001 config /
#: 6003 results; here: explicit enum in-band on one port).
MSG_DATA = 1
MSG_CONFIG = 2
MSG_RESULT = 3
MSG_ACK = 4
MSG_ERROR = 5

#: header: type, stage_index (signed: canary probes use PING_STAGE = -1),
#: request_id (signed: probe ids are negative, disjoint from requests),
#: attempt.
_HEADER = struct.Struct(">BiqI")
_LEN = struct.Struct(">Q")

#: The reference's ACK byte (src/dispatcher.py:250-260, src/node.py:52,88).
ACK_BYTE = b"\x06"


@dataclass(frozen=True)
class Message:
    msg_type: int
    stage_index: int
    request_id: int
    attempt: int
    payload: bytes


def send_msg(sock: socket.socket, msg: Message) -> None:
    header = _HEADER.pack(
        msg.msg_type, msg.stage_index, msg.request_id, msg.attempt
    )
    sock.sendall(_LEN.pack(len(header) + len(msg.payload)) + header + msg.payload)


def _recv_exact(
    sock: socket.socket, n: int, retry_on_timeout: bool = True
) -> bytes:
    chunks = []
    while n:
        try:
            chunk = sock.recv(min(n, 1 << 20))
        except TimeoutError:
            if retry_on_timeout:
                # A socket timeout usually exists to bound *sends* (a
                # wedged peer with full buffers must not hold a sender
                # forever). Reads keep the partial frame and retry —
                # liveness is the lease/watchdog's job, and abandoning
                # mid-frame would desync the stream.
                continue
            raise
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket, retry_on_timeout: bool = True) -> Message:
    """``retry_on_timeout=False`` turns the socket's timeout into a hard
    receive deadline (used where a silent peer must not hold a serial
    loop — e.g. the gateway's HELLO handshake)."""
    (total,) = _LEN.unpack(_recv_exact(sock, _LEN.size, retry_on_timeout))
    if total < _HEADER.size:
        raise ConnectionError(f"short frame: {total}")
    buf = _recv_exact(sock, total, retry_on_timeout)
    msg_type, stage_index, request_id, attempt = _HEADER.unpack(
        buf[: _HEADER.size]
    )
    return Message(
        msg_type=msg_type,
        stage_index=stage_index,
        request_id=request_id,
        attempt=attempt,
        payload=buf[_HEADER.size :],
    )
