"""Cross-host workers: a stage server process + a dispatcher-side proxy.

This is the multi-machine path of the reference, rebuilt: a worker process
(reference: ``python -m src.node``, ``/root/reference/src/node.py:210-211``)
serves stage configuration and data over TCP (there: four ports with
implicit message types, ``src/node.py:19-22``; here: one duplex connection
with typed frames, ``comm.framing``), and the dispatcher drives it through
``RemoteWorkerProxy`` — the same interface as the in-process
``StageWorker``, so the control plane (late binding, watchdog, re-dispatch)
is topology-blind.

Configuration transfers the model by *name + cut list + weights* (the
worker rebuilds the graph from the shared model registry and loads
flax-serialized weights), the TPU-native analog of the reference shipping
Keras architecture JSON + weight arrays (``src/dispatcher.py:223-264``,
``src/node.py:40-45``). Activations cross with a configurable codec
(``comm.codec``) — the zfp+lz4-at-DCN-boundaries design of SURVEY §2.3.

Heartbeats ride the same connection as typed ping frames; the proxy renews
the worker's registry lease only when pings arrive, so a dead process or a
cut link expires the lease exactly like a crashed in-process worker.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any

import jax
import numpy as np

from adapt_tpu.comm import codec as codec_lib
from adapt_tpu.comm.framing import (
    MSG_ACK,
    MSG_CONFIG,
    MSG_DATA,
    MSG_ERROR,
    MSG_RESULT,
    Message,
    recv_msg,
    send_msg,
)
from adapt_tpu.config import FaultConfig
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.control.worker import TaskResult, WorkerState
from adapt_tpu.utils.logging import get_logger

log = get_logger("remote")

MSG_KILL = 6  # chaos hook for fault-injection tests
MSG_PING = 7
MSG_CONFIG_ERR = 8
#: Dispatcher-initiated canary probe (control.dispatcher watchdog) and its
#: answer. Distinct from MSG_PING: pings are *server-initiated* transport
#: heartbeats that only prove the link + ping thread; a probe answer must
#: round-trip the serve loop itself, so a hung server misses it.
MSG_PROBE = 9
MSG_PROBE_ACK = 10


# --------------------------------------------------------------------------
# Worker-process side
# --------------------------------------------------------------------------


class RemoteStageServer:
    """Serves stage configure/execute for one device over one TCP port."""

    def __init__(
        self,
        port: int,
        device_index: int = 0,
        heartbeat_s: float = 0.5,
        host: str = "127.0.0.1",
    ):
        self.port = port
        self.host = host
        self.device = jax.devices()[device_index]
        self.heartbeat_s = heartbeat_s
        self._graph_cache: dict[str, Any] = {}
        self._stages: dict[int, tuple[Any, Any]] = {}  # idx -> (fn, vars)
        self._codec: codec_lib.Codec = codec_lib.get_codec("none")
        self._hung = False
        self._crashed = False

    def _build_stage(self, cfg: dict, weights: bytes):
        """Rebuild the named model, slice it, and load the stage weights."""
        from flax import serialization

        from adapt_tpu.graph.partition import partition
        from adapt_tpu.models import MODEL_REGISTRY

        key = json.dumps(
            [cfg["model"], cfg.get("num_classes", 1000), cfg["cuts"]],
            sort_keys=True,
        )
        if key not in self._graph_cache:
            factory, default_shape = MODEL_REGISTRY[cfg["model"]]
            graph = factory(num_classes=cfg.get("num_classes", 1000))
            plan = partition(graph, cfg["cuts"])
            input_shape = cfg.get("input_shape") or [1, *default_shape]
            template = jax.eval_shape(
                graph.init,
                jax.random.PRNGKey(0),
                jax.ShapeDtypeStruct(tuple(input_shape), jax.numpy.float32),
            )
            self._graph_cache[key] = (plan, template)
        plan, template = self._graph_cache[key]
        idx = cfg["stage_index"]
        if not 0 <= idx < plan.num_stages:
            raise ValueError(
                f"stage index {idx} out of range (plan has "
                f"{plan.num_stages} stages)"
            )
        spec = plan.stages[idx]
        stage_template = {n: template[n] for n in spec.node_names}
        variables = serialization.from_bytes(stage_template, weights)
        variables = jax.device_put(variables, self.device)
        jax.block_until_ready(variables)
        fn = jax.jit(plan.stage_apply(spec))
        self._stages[idx] = (fn, variables)
        self._codec = codec_lib.get_codec(cfg.get("codec", "none"))

    def _handle(self, conn: socket.socket) -> None:
        stop_ping = threading.Event()
        # The ping thread and the serve loop both write this connection;
        # without a lock a ping frame can land inside a partially-sent
        # result frame and corrupt the stream.
        send_lock = threading.Lock()

        def reply(msg: Message) -> None:
            with send_lock:
                send_msg(conn, msg)

        def ping_loop():
            while not stop_ping.wait(self.heartbeat_s):
                if self._crashed:
                    return
                try:
                    reply(Message(MSG_PING, 0, 0, 0, b""))
                except OSError:
                    return

        threading.Thread(target=ping_loop, daemon=True).start()
        try:
            while not self._crashed:
                msg = recv_msg(conn)
                if msg.msg_type == MSG_CONFIG:
                    hlen = int.from_bytes(msg.payload[:4], "big")
                    cfg = json.loads(msg.payload[4 : 4 + hlen].decode())
                    weights = msg.payload[4 + hlen :]
                    try:
                        self._build_stage(cfg, weights)
                        reply(Message(MSG_ACK, msg.stage_index, 0, 0, b""))
                    except Exception as e:  # noqa: BLE001
                        log.error("remote configure failed: %s", e)
                        reply(
                            Message(
                                MSG_CONFIG_ERR,
                                msg.stage_index,
                                0,
                                0,
                                str(e).encode(),
                            )
                        )
                elif msg.msg_type == MSG_DATA:
                    if self._hung:
                        continue  # swallow; watchdog must recover
                    self._execute(reply, msg)
                elif msg.msg_type == MSG_PROBE:
                    if self._hung:
                        continue  # swallow like data; probe deadline fires
                    reply(
                        Message(
                            MSG_PROBE_ACK,
                            msg.stage_index,
                            msg.request_id,
                            msg.attempt,
                            b"",
                        )
                    )
                elif msg.msg_type == MSG_KILL:
                    mode = msg.payload.decode()
                    log.warning("remote worker kill: %s", mode)
                    if mode == "hang":
                        self._hung = True
                    else:
                        self._crashed = True
                        break
        except (ConnectionError, OSError):
            pass
        finally:
            stop_ping.set()
            conn.close()

    def _execute(self, reply, msg: Message) -> None:
        try:
            entry = self._stages.get(msg.stage_index)
            if entry is None:
                raise RuntimeError(f"stage {msg.stage_index} not configured")
            fn, variables = entry
            x = codec_lib.unpack(msg.payload)
            y = fn(variables, jax.device_put(x, self.device))
            y.block_until_ready()
            out = codec_lib.pack(self._codec, np.asarray(y))
            reply(
                Message(
                    MSG_RESULT, msg.stage_index, msg.request_id, msg.attempt, out
                )
            )
        except Exception as e:  # noqa: BLE001
            reply(
                Message(
                    MSG_ERROR,
                    msg.stage_index,
                    msg.request_id,
                    msg.attempt,
                    str(e).encode(),
                )
            )

    def serve_forever(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self.port))
        srv.listen(4)
        log.info("remote stage server on %s:%d", self.host, self.port)
        while not self._crashed:
            try:
                srv.settimeout(0.5)
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._handle(conn)
        srv.close()


# --------------------------------------------------------------------------
# Dispatcher side
# --------------------------------------------------------------------------


class RemoteWorkerProxy:
    """Drives a RemoteStageServer; presents the StageWorker interface."""

    def __init__(
        self,
        worker_id: str,
        address: tuple[str, int],
        registry: WorkerRegistry,
        result_queue,
        model_config: dict,
        codec_name: str = "none",
        fault: FaultConfig | None = None,
    ):
        self.worker_id = worker_id
        self.address = address
        self._registry = registry
        self._results = result_queue
        self._fault = fault or FaultConfig()
        self._model_config = model_config
        self._codec = codec_lib.get_codec(codec_name)
        self._codec_name = codec_name
        self._sock: socket.socket | None = None
        self._send_lock = threading.Lock()
        self._configured: set[int] = set()
        self._config_acks: dict[int, threading.Event] = {}
        self._config_errors: dict[int, str] = {}
        self._inflight_count = 0
        self._count_lock = threading.Lock()
        self._stop = threading.Event()
        self._reader: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "RemoteWorkerProxy":
        deadline = time.monotonic() + self._fault.startup_wait_s
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection(self.address, timeout=5.0)
                self._sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                break
            except OSError as e:
                last = e
                time.sleep(0.1)
        if self._sock is None:
            raise ConnectionError(
                f"cannot reach remote worker at {self.address}: {last}"
            )
        self._registry.register(
            self.worker_id,
            meta={"address": f"{self.address[0]}:{self.address[1]}"},
            ttl_s=self._fault.lease_ttl_s,
        )
        self._reader = threading.Thread(
            target=self._read_loop, name=f"{self.worker_id}-reader", daemon=True
        )
        self._reader.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._reader is not None:
            self._reader.join(timeout=2.0)
        self._registry.deregister(self.worker_id)

    # -- StageWorker interface ----------------------------------------------

    @property
    def state(self) -> WorkerState:
        if self._stop.is_set():
            return WorkerState.DEAD
        with self._count_lock:
            return (
                WorkerState.BUSY if self._inflight_count else WorkerState.IDLE
            )

    @property
    def queue_depth(self) -> int:
        with self._count_lock:
            return self._inflight_count

    def is_configured(self, stage_index: int) -> bool:
        return stage_index in self._configured

    def configure(self, stage_index: int, fn, host_variables, spec=None) -> None:
        """Ship (model name, cuts, stage index, weights) and wait for ACK.
        ``fn`` is ignored — the remote compiles its own stage program."""
        from flax import serialization

        del fn, spec
        header = json.dumps(
            {
                **self._model_config,
                "stage_index": stage_index,
                "codec": self._codec_name,
            }
        ).encode()
        weights = serialization.to_bytes(host_variables)
        payload = len(header).to_bytes(4, "big") + header + weights
        ack = threading.Event()
        self._config_acks[stage_index] = ack
        with self._send_lock:
            send_msg(
                self._sock, Message(MSG_CONFIG, stage_index, 0, 0, payload)
            )
        if not ack.wait(self._fault.configure_timeout_s):
            raise TimeoutError(
                f"no config ACK for stage {stage_index} from "
                f"{self.worker_id}"
            )
        err = self._config_errors.pop(stage_index, None)
        if err is not None:
            raise RuntimeError(f"remote configure failed: {err}")
        self._configured.add(stage_index)

    def submit(self, task) -> None:
        if task.stage_index < 0:
            # Canary probe (control.dispatcher watchdog): no payload, no
            # in-flight accounting — the dispatcher tracks it in _probes.
            # Bounded lock wait: the watchdog thread calls this, and it
            # must never block behind a configure() holding _send_lock
            # across a multi-hundred-MB weights send to a wedged peer.
            if not self._send_lock.acquire(timeout=1.0):
                raise TimeoutError(
                    f"{self.worker_id} send channel busy; probe dropped"
                )
            try:
                send_msg(
                    self._sock,
                    Message(
                        MSG_PROBE,
                        task.stage_index,
                        task.request_id,
                        task.attempt,
                        b"",
                    ),
                )
            finally:
                self._send_lock.release()
            return
        payload = codec_lib.pack(self._codec, np.asarray(task.payload))
        with self._count_lock:
            self._inflight_count += 1
        with self._send_lock:
            send_msg(
                self._sock,
                Message(
                    MSG_DATA,
                    task.stage_index,
                    task.request_id,
                    task.attempt,
                    payload,
                ),
            )

    def kill(self, mode: str = "crash") -> None:
        with self._send_lock:
            send_msg(self._sock, Message(MSG_KILL, 0, 0, 0, mode.encode()))

    # -- internals -----------------------------------------------------------

    def _read_loop(self) -> None:
        while not self._stop.is_set():
            try:
                msg = recv_msg(self._sock)
            except (ConnectionError, OSError):
                break
            if msg.msg_type == MSG_PING:
                self._registry.heartbeat(
                    self.worker_id, ttl_s=self._fault.lease_ttl_s
                )
            elif msg.msg_type == MSG_PROBE_ACK:
                self._results.put(
                    TaskResult(
                        request_id=msg.request_id,
                        stage_index=msg.stage_index,
                        attempt=msg.attempt,
                        worker_id=self.worker_id,
                    )
                )
            elif msg.msg_type == MSG_ACK:
                ev = self._config_acks.get(msg.stage_index)
                if ev is not None:
                    ev.set()
            elif msg.msg_type == MSG_CONFIG_ERR:
                self._config_errors[msg.stage_index] = msg.payload.decode()
                ev = self._config_acks.get(msg.stage_index)
                if ev is not None:
                    ev.set()
            elif msg.msg_type in (MSG_RESULT, MSG_ERROR):
                with self._count_lock:
                    self._inflight_count = max(0, self._inflight_count - 1)
                if msg.msg_type == MSG_RESULT:
                    self._results.put(
                        TaskResult(
                            request_id=msg.request_id,
                            stage_index=msg.stage_index,
                            attempt=msg.attempt,
                            worker_id=self.worker_id,
                            output=codec_lib.unpack(msg.payload),
                        )
                    )
                else:
                    self._results.put(
                        TaskResult(
                            request_id=msg.request_id,
                            stage_index=msg.stage_index,
                            attempt=msg.attempt,
                            worker_id=self.worker_id,
                            error=msg.payload.decode(),
                        )
                    )
        # Socket gone: stop renewing the lease; the reaper will evict us.


def main() -> None:
    """CLI entry: ``python -m adapt_tpu.comm.remote --port 7001``
    (the reference's ``python -m src.node``, README.md:44)."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--device-index", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--heartbeat", type=float, default=0.5)
    args = p.parse_args()
    RemoteStageServer(
        args.port,
        device_index=args.device_index,
        heartbeat_s=args.heartbeat,
        host=args.host,
    ).serve_forever()


if __name__ == "__main__":
    main()
