"""Cross-host workers: a stage server process + a dispatcher-side proxy.

This is the multi-machine path of the reference, rebuilt: a worker process
(reference: ``python -m src.node``, ``/root/reference/src/node.py:210-211``)
serves stage configuration and data over TCP (there: four ports with
implicit message types, ``src/node.py:19-22``; here: one duplex connection
with typed frames, ``comm.framing``), and the dispatcher drives it through
``RemoteWorkerProxy`` — the same interface as the in-process
``StageWorker``, so the control plane (late binding, watchdog, re-dispatch)
is topology-blind.

Configuration transfers the model by *name + cut list + weights* (the
worker rebuilds the graph from the shared model registry and loads
flax-serialized weights), the TPU-native analog of the reference shipping
Keras architecture JSON + weight arrays (``src/dispatcher.py:223-264``,
``src/node.py:40-45``). Activations cross with a configurable codec
(``comm.codec``) — the zfp+lz4-at-DCN-boundaries design of SURVEY §2.3.

Heartbeats ride the same connection as typed ping frames; the proxy renews
the worker's registry lease only when pings arrive, so a dead process or a
cut link expires the lease exactly like a crashed in-process worker.
"""

from __future__ import annotations

import hmac
import itertools
import json
import socket
import threading
import time
from typing import Any

import jax
import numpy as np

from adapt_tpu.comm import codec as codec_lib
from adapt_tpu.comm.framing import (
    MSG_ACK,
    MSG_CONFIG,
    MSG_DATA,
    MSG_ERROR,
    MSG_RESULT,
    MSG_TELEMETRY,
    Message,
    payload_bytes,
    recv_msg,
    send_msg,
)
from adapt_tpu.config import FaultConfig
from adapt_tpu.control.registry import WorkerRegistry
from adapt_tpu.control.worker import TaskResult, WorkerState
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.telemetry import (
    TelemetryReporter,
    global_federated_store,
)
from adapt_tpu.utils.tracing import (
    export_spans,
    global_flight_recorder,
    global_tracer,
)

log = get_logger("remote")

MSG_KILL = 6  # chaos hook for fault-injection tests
MSG_PING = 7
MSG_CONFIG_ERR = 8
#: Dispatcher-initiated canary probe (control.dispatcher watchdog) and its
#: answer. Distinct from MSG_PING: pings are *server-initiated* transport
#: heartbeats that only prove the link + ping thread; a probe answer must
#: round-trip the serve loop itself, so a hung server misses it.
MSG_PROBE = 9
MSG_PROBE_ACK = 10
#: Streamed configure (reference: count-prefixed sequence of per-array
#: compressed frames, ``src/dispatcher.py:76-89`` / ``src/node.py:
#: 101-119``): MSG_CONFIG carries the JSON header (model, cuts, stage,
#: array count, generation in ``request_id``), then one MSG_CONFIG_ARRAY
#: per weight leaf (``attempt`` = leaf index), then MSG_CONFIG_END. Each
#: frame takes the send lock independently, so probes and data interleave
#: with a multi-hundred-MB weights transfer instead of queueing behind it.
MSG_CONFIG_ARRAY = 11
MSG_CONFIG_END = 12
#: Worker-initiated join (reference: the WORKER writes /workers/<ip> into
#: etcd and the dispatcher discovers it, ``src/node_state.py:17-20``):
#: a fresh worker dials the dispatcher's WorkerGateway and announces
#: itself with MSG_HELLO {worker_id}; the gateway wraps the accepted
#: socket in a RemoteWorkerProxy, registers the lease, and answers
#: MSG_HELLO_ACK. The pool can now GROW at runtime, not only shrink.
MSG_HELLO = 13
MSG_HELLO_ACK = 14
#: Drop a stage binding (and/or an in-flight configure). Sent by the proxy
#: when a configure fails or is aborted after CONFIG_END already went out:
#: without it the server would install and pin the stage weights for a
#: handshake the dispatcher has already declared dead. ``request_id`` is
#: the generation to revoke, or 0 to drop whatever is installed.
MSG_UNCONFIGURE = 15
#: Install (or clear) a direct next-hop for a stage's outputs: Gen-1 chain
#: topology (the reference worker forwards activations straight to the
#: next worker's data port, ``/root/reference/src/node.py:163-179``),
#: rebuilt as an OPT-IN fast path for static healthy pools. Payload JSON:
#: ``{"next": [host, port], "next_stage": j}`` = forward my stage's
#: output as MSG_DATA for stage ``j`` directly to that worker;
#: ``{"next": null}`` = I am the chain tail — send MSG_RESULT on the
#: dispatcher link; ``{"clear": true}`` = revert to hub routing. The
#: worker ACKs with the frame's ``request_id`` (a proxy generation), so
#: route installs are reliable, not fire-and-forget. Errors (exec OR
#: forward failures) always go hub-ward on the dispatcher link — the
#: chain carries the data plane only, the hub keeps the control plane
#: (probes, deadlines, exactly-once, re-dispatch).
MSG_SET_ROUTE = 16
#: Chain-routed data. Routes apply ONLY to this type: after a chain
#: failure the hub falls back to per-stage dispatch with plain MSG_DATA,
#: which must return results hub-ward even if a stale route is still
#: installed on the worker (clears are best-effort on a possibly-dead
#: link). The frame type, not worker state, decides the topology.
MSG_DATA_CHAINED = 17


# --------------------------------------------------------------------------
# Worker-process side
# --------------------------------------------------------------------------


class RemoteStageServer:
    """Serves stage configure/execute for one device over one TCP port."""

    def __init__(
        self,
        port: int,
        device_index: int = 0,
        heartbeat_s: float = 0.5,
        host: str = "127.0.0.1",
        allow_registry: bool = True,
        telemetry_s: float = 2.0,
    ):
        """``allow_registry=False`` — serve ONLY architecture-by-value
        configures (``graph_spec`` in the header): the stance of a bare
        worker image that ships the framework but no model zoo
        (reference: any worker can ``model_from_json`` anything,
        ``src/node.py:40-45``).

        ``telemetry_s`` — cadence of telemetry-federation reports
        (``MSG_TELEMETRY``: windowed metric deltas, flight events,
        span exports) pushed on the DISPATCHER link's heartbeat
        thread; 0 disables the push. Reports ride only the primary
        (dispatcher) connection — chain-peer links would discard them
        unread, and two links pushing would split the deltas."""
        self.port = port
        self.host = host
        self.device = jax.devices()[device_index]
        self.heartbeat_s = heartbeat_s
        self.allow_registry = allow_registry
        self.telemetry_s = telemetry_s
        #: How this process names itself in telemetry reports (the
        #: dispatcher-side ingest overrides it with the lease's
        #: worker id — a dial-out server only knows its port).
        self.telemetry_worker = f"{host}:{port}"
        self._telemetry: TelemetryReporter | None = None
        #: Reports collected but not delivered (the link died between
        #: collect and send): collect() CONSUMES its snapshot window,
        #: so a dropped report would permanently lose that window's
        #: deltas from the fleet totals. Bounded — a long outage
        #: degrades to losing the oldest windows, loudly countable as
        #: a seq gap on the parent, never unbounded memory here.
        self._telemetry_backlog: list[tuple[int, bytes]] = []
        self._graph_cache: dict[str, Any] = {}
        self._stages: dict[int, tuple[Any, Any]] = {}  # idx -> (fn, vars)
        self._stage_gen: dict[int, int] = {}  # idx -> installing generation
        self._codec: codec_lib.Codec = codec_lib.get_codec("none")
        self._hung = False
        self._crashed = False
        #: stage -> {"next": (host, port) | None, "next_stage": int}.
        #: Present = chain mode for that stage; "next" None = chain tail.
        self._routes: dict[int, dict] = {}
        #: (host, port) -> (socket, send lock) persistent forward links.
        self._fwd: dict[tuple, tuple[socket.socket, threading.Lock]] = {}
        self._fwd_lock = threading.Lock()
        #: reply() of the dispatcher connection (the one control frames
        #: arrive on). Chain-tail results and chain errors go here — the
        #: data may have arrived on a peer worker's connection, but the
        #: hub owns completion and recovery.
        self._primary_reply = None

    def _build_stage(self, cfg: dict, leaves: list):
        """Rebuild the model — by REGISTRY NAME (shared model zoo) or by
        VALUE (``graph_spec``: the serialized LayerGraph itself, so an
        empty-registry worker can serve custom cuts/hyperparams/DAGs;
        reference ``model_from_json``, ``src/node.py:40-45``) — slice it,
        and load the stage weights from the streamed per-array ``leaves``
        (reference receiver: ``src/node.py:101-119``, count-prefixed
        per-array frames)."""
        from adapt_tpu.graph.partition import partition
        from adapt_tpu.graph.spec import graph_from_spec

        model_kwargs = cfg.get("model_kwargs", {})
        graph_spec = cfg.get("graph_spec")
        key = json.dumps(
            [
                cfg.get("model"),
                graph_spec,
                cfg.get("num_classes", 1000),
                cfg["cuts"],
                model_kwargs,
            ],
            sort_keys=True,
        )
        if key not in self._graph_cache:
            if graph_spec is not None:
                graph = graph_from_spec(graph_spec)
                input_shape = cfg.get("input_shape")
                if input_shape is None:
                    raise ValueError(
                        "graph_spec configure needs an explicit input_shape"
                    )
            elif not self.allow_registry:
                raise RuntimeError(
                    "this worker serves architecture-by-value only "
                    "(--no-registry); send a graph_spec, not a model name"
                )
            else:
                from adapt_tpu.models import MODEL_REGISTRY

                factory, default_shape = MODEL_REGISTRY[cfg["model"]]
                # model_kwargs: extra factory arguments (e.g. resnet50's
                # stem="s2d") — the joiner must rebuild the EXACT graph the
                # dispatcher partitioned or the streamed weights won't fit.
                graph = factory(
                    num_classes=cfg.get("num_classes", 1000), **model_kwargs
                )
                input_shape = cfg.get("input_shape") or [1, *default_shape]
            plan = partition(graph, cfg["cuts"])
            template = jax.eval_shape(
                graph.init,
                jax.random.PRNGKey(0),
                jax.ShapeDtypeStruct(tuple(input_shape), jax.numpy.float32),
            )
            self._graph_cache[key] = (plan, template)
        plan, template = self._graph_cache[key]
        idx = cfg["stage_index"]
        if not 0 <= idx < plan.num_stages:
            raise ValueError(
                f"stage index {idx} out of range (plan has "
                f"{plan.num_stages} stages)"
            )
        spec = plan.stages[idx]
        stage_template = {n: template[n] for n in spec.node_names}
        t_leaves, treedef = jax.tree_util.tree_flatten(stage_template)
        if len(leaves) != len(t_leaves):
            raise ValueError(
                f"stage {idx}: got {len(leaves)} weight arrays, template "
                f"has {len(t_leaves)}"
            )
        variables = jax.tree_util.tree_unflatten(treedef, leaves)
        variables = jax.device_put(variables, self.device)
        jax.block_until_ready(variables)
        fn = jax.jit(plan.stage_apply(spec))
        self._stages[idx] = (fn, variables)
        self._codec = codec_lib.get_codec(cfg.get("codec", "none"))

    #: Bound on forward-link sends: a wedged next hop must error this
    #: request hub-ward (where the replay machinery lives), not freeze the
    #: serving thread forever while pings keep the lease alive.
    FWD_SEND_TIMEOUT_S = 15.0

    def _fwd_connect(
        self, addr: tuple
    ) -> tuple[socket.socket, threading.Lock]:
        """Persistent forward link to the next chain worker. The peer's
        serve loop answers pings (and nothing we care about) on it, so a
        drain thread discards inbound frames — without it the peer's ping
        writes would slowly fill the TCP buffer of a socket nobody reads."""
        with self._fwd_lock:
            entry = self._fwd.get(addr)
            if entry is not None:
                return entry
            sock = socket.create_connection(addr, timeout=5.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Timeout bounds SENDS; the drain thread's reads retry through
            # it (framing's retry_on_timeout default).
            sock.settimeout(self.FWD_SEND_TIMEOUT_S)
            entry = (sock, threading.Lock())
            self._fwd[addr] = entry

        def drain():
            try:
                while True:
                    recv_msg(sock)
            except (ConnectionError, OSError):
                self._fwd_drop(addr, sock)

        threading.Thread(target=drain, daemon=True).start()
        return entry

    def _fwd_drop(self, addr: tuple, sock: socket.socket) -> None:
        """Evict (and close) a forward link. A send failure MUST come
        through here: bytes may be half-written, so the stream is
        unusable — a later ``setup_chain`` over the same topology has to
        re-dial, not cache-hit a desynced socket."""
        with self._fwd_lock:
            if self._fwd.get(addr) is not None and self._fwd[addr][0] is sock:
                del self._fwd[addr]
        try:
            sock.close()
        except OSError:
            pass

    def _fwd_gc(self) -> None:
        """Close forward links no live route references (route cleared or
        re-pointed): without this, every chain reconfiguration would leak
        a socket here plus a handler+ping thread pair on the peer."""
        live = {r["next"] for r in self._routes.values() if r["next"]}
        with self._fwd_lock:
            dead = [
                (a, s) for a, (s, _) in self._fwd.items() if a not in live
            ]
        for addr, sock in dead:
            self._fwd_drop(addr, sock)

    def _handle(self, conn: socket.socket) -> int:
        """Serve one connection until it closes; returns the number of
        messages processed (0 = the peer closed before saying anything —
        the shape of a gateway join rejection)."""
        n_msgs = 0
        stop_ping = threading.Event()
        # The ping thread and the serve loop both write this connection;
        # without a lock a ping frame can land inside a partially-sent
        # result frame and corrupt the stream.
        send_lock = threading.Lock()

        def reply(msg: Message) -> None:
            with send_lock:
                send_msg(conn, msg)

        def ping_loop():
            # Telemetry cadence in heartbeat units (the push shares the
            # ping thread so a wedged serve loop stops reporting — which
            # is exactly the staleness signal the parent's
            # fleet.report_age_s gauge surfaces).
            every = (
                max(1, round(self.telemetry_s / self.heartbeat_s))
                if self.telemetry_s > 0
                else 0
            )
            beats = 0
            while not stop_ping.wait(self.heartbeat_s):
                if self._crashed:
                    return
                try:
                    reply(Message(MSG_PING, 0, 0, 0, b""))
                except OSError:
                    return
                beats += 1
                if not every or beats % every:
                    continue
                if self._primary_reply is not reply:
                    continue  # only the dispatcher link carries reports
                try:
                    if self._telemetry is None:
                        self._telemetry = TelemetryReporter(
                            "stage", self.telemetry_worker
                        )
                        # Capacity plane: a stage worker is a minimal
                        # source — which stages it holds, so the fleet
                        # capacity view shows it with first-class
                        # staleness. Function-scoped import: comm must
                        # not depend on runtime at module level.
                        from adapt_tpu.runtime.capacity import stage_book

                        self._telemetry.capacity_provider = (
                            lambda: stage_book(len(self._stages))
                        )
                    report = self._telemetry.collect()
                    # default=str: a non-JSON value (numpy scalar in a
                    # gauge or flight datum) degrades to its repr —
                    # the same hazard rule the exporter's JSON
                    # endpoints apply — instead of killing this
                    # worker's telemetry forever.
                    self._telemetry_backlog.append(
                        (
                            int(report["seq"]),
                            json.dumps(report, default=str).encode(),
                        )
                    )
                    del self._telemetry_backlog[:-8]
                    # Oldest first (the store's seq-gap loss detector
                    # relies on in-order arrival); a frame that fails
                    # to send stays queued for the next beat or the
                    # next dispatcher connection.
                    while self._telemetry_backlog:
                        seq, blob = self._telemetry_backlog[0]
                        reply(Message(MSG_TELEMETRY, 0, seq, 0, blob))
                        self._telemetry_backlog.pop(0)
                except OSError:
                    return
                except Exception:  # noqa: BLE001 — telemetry must never
                    log.exception("telemetry push failed")  # kill pings

        threading.Thread(target=ping_loop, daemon=True).start()
        # (stage, generation) -> {"cfg": dict, "arrays": {index: ndarray}}:
        # a configure in flight, assembled from interleaved frames. Two
        # concurrent configures for the same stage (the dispatcher recovery
        # path) stay separate because the generation disambiguates.
        pending: dict[tuple[int, int], dict] = {}
        try:
            while not self._crashed:
                msg = recv_msg(conn)
                n_msgs += 1
                if pending:
                    # Purge abandoned configures on every message: an
                    # aborted mid-stream configure whose UNCONFIGURE also
                    # got lost must not retain its buffered weight arrays
                    # for the life of the connection. Idle-based (not
                    # supersede-on-same-stage) so neither a LIVE concurrent
                    # configure of the same stage — the dispatcher recovery
                    # path — nor a slow-but-streaming transfer is evicted.
                    now = time.monotonic()
                    for key in [
                        k
                        for k, e in pending.items()
                        if now - e["ts"] > 300.0
                    ]:
                        del pending[key]
                if msg.msg_type == MSG_CONFIG:
                    # Only the dispatcher configures; remember its link so
                    # chained results/errors route hub-ward even when the
                    # triggering data frame came from a peer worker.
                    self._primary_reply = reply
                    cfg = json.loads(payload_bytes(msg.payload).decode())
                    pending[(msg.stage_index, msg.request_id)] = {
                        "cfg": cfg,
                        "arrays": {},
                        "ts": time.monotonic(),
                    }
                elif msg.msg_type == MSG_SET_ROUTE:
                    self._primary_reply = reply
                    try:
                        info = json.loads(payload_bytes(msg.payload).decode())
                        if info.get("clear"):
                            self._routes.pop(msg.stage_index, None)
                            self._fwd_gc()
                        else:
                            nxt = info.get("next")
                            route = {
                                "next": tuple(nxt) if nxt else None,
                                "next_stage": info.get("next_stage", -1),
                            }
                            if route["next"] is not None:
                                # Pre-dial so an unreachable next hop fails
                                # the install, not the first request.
                                self._fwd_connect(route["next"])
                            self._routes[msg.stage_index] = route
                            self._fwd_gc()
                        reply(
                            Message(
                                MSG_ACK, msg.stage_index, msg.request_id, 0, b""
                            )
                        )
                    except Exception as e:  # noqa: BLE001
                        log.error("route install failed: %s", e)
                        reply(
                            Message(
                                MSG_CONFIG_ERR,
                                msg.stage_index,
                                msg.request_id,
                                0,
                                str(e).encode(),
                            )
                        )
                elif msg.msg_type == MSG_CONFIG_ARRAY:
                    entry = pending.get((msg.stage_index, msg.request_id))
                    if entry is not None:
                        entry["arrays"][msg.attempt] = codec_lib.unpack(
                            msg.payload
                        )
                        # Keep-alive: the purge below is idle-based, so a
                        # legitimately slow (>300 s) streaming transfer is
                        # never evicted while frames still arrive.
                        entry["ts"] = time.monotonic()
                elif msg.msg_type == MSG_CONFIG_END:
                    key = (msg.stage_index, msg.request_id)
                    entry = pending.pop(key, None)
                    try:
                        if entry is None:
                            raise RuntimeError(
                                f"CONFIG_END for unknown configure {key}"
                            )
                        cfg, arrays = entry["cfg"], entry["arrays"]
                        n = cfg["n_arrays"]
                        if len(arrays) != n:
                            raise RuntimeError(
                                f"stage {msg.stage_index}: received "
                                f"{len(arrays)}/{n} weight arrays"
                            )
                        leaves = [arrays[i] for i in range(n)]
                        self._build_stage(cfg, leaves)
                        self._stage_gen[msg.stage_index] = msg.request_id
                        reply(
                            Message(
                                MSG_ACK,
                                msg.stage_index,
                                msg.request_id,
                                0,
                                b"",
                            )
                        )
                    except Exception as e:  # noqa: BLE001
                        log.error("remote configure failed: %s", e)
                        reply(
                            Message(
                                MSG_CONFIG_ERR,
                                msg.stage_index,
                                msg.request_id,
                                0,
                                str(e).encode(),
                            )
                        )
                elif msg.msg_type == MSG_UNCONFIGURE:
                    gen = msg.request_id
                    pending.pop((msg.stage_index, gen), None)
                    # Revoke the install only if it came from the revoked
                    # generation (or unconditionally for gen 0) — a newer
                    # configure's binding must survive an old revoke.
                    if gen == 0 or self._stage_gen.get(msg.stage_index) == gen:
                        self._stages.pop(msg.stage_index, None)
                        self._stage_gen.pop(msg.stage_index, None)
                        log.info(
                            "stage %d unconfigured (gen %d)",
                            msg.stage_index,
                            gen,
                        )
                elif msg.msg_type == MSG_HELLO_ACK:
                    continue  # join handshake answer; nothing to do
                elif msg.msg_type in (MSG_DATA, MSG_DATA_CHAINED):
                    if self._hung:
                        continue  # swallow; watchdog must recover
                    self._execute(reply, msg)
                elif msg.msg_type == MSG_PROBE:
                    if self._hung:
                        continue  # swallow like data; probe deadline fires
                    reply(
                        Message(
                            MSG_PROBE_ACK,
                            msg.stage_index,
                            msg.request_id,
                            msg.attempt,
                            b"",
                        )
                    )
                elif msg.msg_type == MSG_KILL:
                    mode = payload_bytes(msg.payload).decode()
                    log.warning("remote worker kill: %s", mode)
                    if mode == "hang":
                        self._hung = True
                    else:
                        self._crashed = True
                        break
        except (ConnectionError, OSError):
            pass
        finally:
            stop_ping.set()
            conn.close()
        return n_msgs

    def _execute(self, reply, msg: Message) -> None:
        # Chain errors must reach the HUB (which owns re-dispatch), not the
        # upstream peer whose forward socket nobody answers on (its drain
        # thread discards frames). Routes bind to the frame type: hub-path
        # MSG_DATA ignores them.
        chained = msg.msg_type == MSG_DATA_CHAINED
        route = self._routes.get(msg.stage_index) if chained else None
        err_reply = (self._primary_reply or reply) if chained else reply
        try:
            if chained and route is None:
                # The route was cleared while this frame was in flight:
                # there is no legitimate routeless chained frame. Error
                # hub-ward NOW so the dispatcher replays immediately
                # instead of waiting out a full chain deadline.
                raise RuntimeError(
                    f"chained frame for stage {msg.stage_index} arrived "
                    "after its route was cleared"
                )
            entry = self._stages.get(msg.stage_index)
            if entry is None:
                raise RuntimeError(f"stage {msg.stage_index} not configured")
            fn, variables = entry
            # Span tagged with the header's OWN request/attempt ids — the
            # key the dispatcher stitches this back into the originating
            # request's trace with (no side-channel correlation).
            t_exec = time.perf_counter()
            with global_tracer().span(
                "remote.stage_exec",
                request=msg.request_id,
                attempt=msg.attempt,
                stage=msg.stage_index,
            ) as sp:
                x = codec_lib.unpack(msg.payload)
                y = fn(variables, jax.device_put(x, self.device))
                y.block_until_ready()
                # Device array handed to the codec directly: int8dev
                # quantizes on-chip before the host fetch; host codecs
                # coerce themselves. pack_frames + the framing layer's
                # scatter write: the encoded payload goes to the kernel as
                # buffer views, never concatenated host-side (zero framing
                # copies per hop).
                out = codec_lib.pack_frames(self._codec, y)
            # Worker-process telemetry: counters + an exec-wall
            # histogram in THIS process's registry (federated to the
            # dispatcher as MSG_TELEMETRY reports) and a flight edge
            # naming the request — the worker's half of the
            # /debug/request/<id> forensics story.
            global_metrics().inc("remote.stage_execs")
            global_metrics().observe(
                "remote.stage_exec_s", time.perf_counter() - t_exec
            )
            global_flight_recorder().record(
                "remote_exec",
                request=msg.request_id,
                stage=msg.stage_index,
                attempt=msg.attempt,
            )
            # Trace annex: this hop's span, appended to any spans already
            # riding the inbound frame (mid-chain hops accumulate, so the
            # tail result delivers the WHOLE chain's spans hub-ward).
            annex = None
            if sp is not None or msg.annex:
                # A corrupt inbound annex must NEVER fail the stage (the
                # compute already succeeded): any parse surprise just
                # drops the upstream spans. Chains are at most num_stages
                # hops, so the re-parse per hop stays trivial.
                acc = []
                if msg.annex:
                    try:
                        parsed = json.loads(msg.annex.decode())
                        if isinstance(parsed, list):
                            acc = parsed
                    except (ValueError, UnicodeDecodeError):
                        pass
                acc.extend(export_spans([sp]))
                annex = json.dumps(acc).encode()
            if route is None:
                # Hub routing: the stage output returns whence it came.
                reply(
                    Message(
                        MSG_RESULT,
                        msg.stage_index,
                        msg.request_id,
                        msg.attempt,
                        out,
                        annex=annex,
                    )
                )
            elif route["next"] is None:
                # Chain tail: the FINAL result goes to the dispatcher link
                # (the request's data may have hopped in from a peer).
                (self._primary_reply or reply)(
                    Message(
                        MSG_RESULT,
                        msg.stage_index,
                        msg.request_id,
                        msg.attempt,
                        out,
                        annex=annex,
                    )
                )
            else:
                # Mid-chain: the activation goes straight to the next
                # worker — the hub never touches it (SURVEY §3.2's 2·S-hop
                # critique; reference Gen-1 ``src/node.py:163-179``).
                sock, lock = self._fwd_connect(route["next"])
                try:
                    with lock:
                        send_msg(
                            sock,
                            Message(
                                MSG_DATA_CHAINED,
                                route["next_stage"],
                                msg.request_id,
                                msg.attempt,
                                out,
                                annex=annex,
                            ),
                        )
                except (TimeoutError, OSError):
                    # Half-written frame: the stream is dead. Evict it so
                    # a chain re-enable re-dials, then report hub-ward.
                    self._fwd_drop(route["next"], sock)
                    raise
        except Exception as e:  # noqa: BLE001
            try:
                err_reply(
                    Message(
                        MSG_ERROR,
                        msg.stage_index,
                        msg.request_id,
                        msg.attempt,
                        str(e).encode(),
                    )
                )
            except Exception:  # noqa: BLE001 — error path must not recurse
                log.warning("could not report execute error hub-ward")

    def serve_forever(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self.port))
        srv.listen(4)
        log.info("remote stage server on %s:%d", self.host, self.port)
        while not self._crashed:
            try:
                srv.settimeout(0.5)
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Thread per connection: chain mode means a PEER worker dials
            # in with data while the dispatcher link is mid-service — a
            # serial accept loop would never serve the second link.
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()
        srv.close()

    def connect_and_serve(
        self,
        address: tuple[str, int],
        worker_id: str,
        retries: int = 20,
        secret: str | None = None,
    ) -> None:
        """Worker-initiated join: dial the dispatcher's WorkerGateway,
        announce ourselves, then serve the connection. The TPU-native
        re-expression of the reference worker self-registering in etcd
        (``/root/reference/src/node_state.py:17-20``) — here the dial +
        MSG_HELLO *is* the registration write, and the gateway-side lease
        renewal rides the same connection's pings. ``secret`` (if the
        gateway requires one) rides in the HELLO; a rejected join shows
        up as the gateway closing the link before any message.

        Joins RETRY (``join_retries``, 1 s apart): the legitimate rejoin
        race is a worker redialing after a link blip while the gateway's
        stale proxy for the SAME worker_id has not yet noticed its dead
        socket — the duplicate-live-id guard rejects the first attempt,
        the stale proxy deregisters within a ping interval, and the next
        attempt lands. A genuine rejection (bad secret, true duplicate)
        exhausts the budget and raises."""
        join_retries = 8
        # Joiners DO know their fleet identity — name telemetry reports
        # with it (dial-out servers fall back to host:port and let the
        # proxy-side ingest rename them).
        self.telemetry_worker = worker_id
        for join_attempt in range(join_retries):
            last: Exception | None = None
            for _ in range(retries):
                try:
                    conn = socket.create_connection(address, timeout=5.0)
                    break
                except OSError as e:
                    last = e
                    time.sleep(0.25)
            else:
                raise ConnectionError(
                    f"cannot reach gateway at {address}: {last}"
                )
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # create_connection's 5 s dial timeout must NOT linger on the
            # serving socket: a timed-out mid-frame result send would
            # desync the stream and a slow ping send would kill the
            # heartbeat thread. Serving uses blocking sends, like the
            # dial-in accept path.
            conn.settimeout(None)
            info = {"worker_id": worker_id}
            if secret is not None:
                info["secret"] = secret
            send_msg(
                conn, Message(MSG_HELLO, 0, 0, 0, json.dumps(info).encode())
            )
            log.info("dialed gateway %s:%d as %s", *address, worker_id)
            if self._handle(conn) > 0 or self._crashed:
                # A real session ran (or we were killed through it);
                # done. A later link drop is the gateway proxy's problem.
                return
            log.warning(
                "gateway closed the join as %s without serving "
                "(rejected or stale-duplicate race), attempt %d/%d",
                worker_id,
                join_attempt + 1,
                join_retries,
            )
            time.sleep(1.0)
        raise ConnectionError(
            f"gateway refused join as {worker_id!r} "
            f"after {join_retries} attempts"
        )


# --------------------------------------------------------------------------
# Dispatcher side
# --------------------------------------------------------------------------


class RemoteWorkerProxy:
    """Drives a RemoteStageServer; presents the StageWorker interface."""

    def __init__(
        self,
        worker_id: str,
        address: tuple[str, int],
        registry: WorkerRegistry,
        result_queue,
        model_config: dict,
        codec_name: str = "none",
        weights_codec: str = "lz",
        fault: FaultConfig | None = None,
        sock: socket.socket | None = None,
        blob_cache: dict | None = None,
    ):
        """``sock`` — an already-connected socket (gateway path: the worker
        dialed us); when None, :meth:`start` dials ``address``.

        ``blob_cache`` — optional dict shared across proxies (the gateway
        passes one): packed stage-weight frames are deterministic for a
        given (stage, codec), so N joining workers — or one recovery storm
        re-configuring the same stage — pay the compression pass once."""
        self.worker_id = worker_id
        self.address = address
        #: Dial-out proxies know the worker's LISTENING address — the one
        #: a chain peer can reach it at. Gateway joiners' ``address`` is
        #: an ephemeral client port, useless as a next hop.
        self._dialed_out = sock is None
        #: MSG_RESULT/MSG_ERROR frames this link delivered — lets tests
        #: (and the chain A/B) prove the hub never saw mid-chain traffic.
        self.results_received = 0
        self.result_bytes_received = 0
        self._registry = registry
        self._results = result_queue
        self._fault = fault or FaultConfig()
        self._model_config = model_config
        self._codec = codec_lib.get_codec(codec_name)
        self._codec_name = codec_name
        self._wcodec = codec_lib.get_codec(weights_codec)
        self._sock: socket.socket | None = sock
        self._send_lock = threading.Lock()
        self._configured: dict[int, int] = {}  # stage -> newest gen installed
        # Config handshake state keyed by (stage_index, generation): two
        # concurrent configures for the same stage (reachable from two
        # forward threads on the recovery path) get independent events
        # instead of clobbering each other's.
        self._config_gen = itertools.count(1)
        self._blob_cache = blob_cache
        self._ack_lock = threading.Lock()
        self._config_acks: dict[tuple[int, int], threading.Event] = {}
        self._config_errors: dict[tuple[int, int], str] = {}
        self._inflight_count = 0
        #: (stage, request, attempt) submits this proxy counted into
        #: _inflight_count — the only results allowed to decrement it
        #: (chain-tail results for head-submitted requests are not).
        self._counted: set[tuple[int, int, int]] = set()
        self._count_lock = threading.Lock()
        self._stop = threading.Event()
        self._reader: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "RemoteWorkerProxy":
        # Idempotent: recovery dials proxies one by one (skipping the
        # unreachable) before Dispatcher.start() walks the pool calling
        # start() again — a second call must not stack a second reader
        # thread or lease.
        if self._reader is not None:
            return self
        if self._sock is None:
            deadline = time.monotonic() + self._fault.startup_wait_s
            last: Exception | None = None
            while time.monotonic() < deadline:
                try:
                    self._sock = socket.create_connection(
                        self.address, timeout=5.0
                    )
                    self._sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                    break
                except OSError as e:
                    last = e
                    time.sleep(0.1)
            if self._sock is None:
                raise ConnectionError(
                    f"cannot reach remote worker at {self.address}: {last}"
                )
        # Socket timeout bounds blocked *sends* (wedged peer, full TCP
        # buffers); the reader side retries through timeouts (framing).
        self._sock.settimeout(self._fault.send_timeout_s)
        # Keep the ownership token: if THIS connection dies after a
        # replacement worker re-registered the same id, our deregister
        # must not evict the replacement's lease.
        self._lease_token = self._registry.register(
            self.worker_id,
            meta={"address": f"{self.address[0]}:{self.address[1]}"},
            ttl_s=self._fault.lease_ttl_s,
        )
        self._reader = threading.Thread(
            target=self._read_loop, name=f"{self.worker_id}-reader", daemon=True
        )
        self._reader.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._reader is not None:
            self._reader.join(timeout=2.0)
        self._registry.deregister(
            self.worker_id, token=getattr(self, "_lease_token", None)
        )

    def _mark_dead(self, why: str) -> None:
        """Tear the link down after a send timeout/failure: a partial send
        leaves the stream state unknowable, so the only safe move is to
        drop the connection and let membership re-dispatch our in-flight
        work (immediately, via deregister — no need to wait out the lease)."""
        if self._stop.is_set():
            return
        log.warning("remote %s link dropped: %s", self.worker_id, why)
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._registry.deregister(
            self.worker_id, token=getattr(self, "_lease_token", None)
        )

    def _send(self, msg: Message, lock_timeout: float | None = None) -> None:
        """Bounded send: both the wait for the channel lock and the socket
        write itself are time-limited (reference analog: non-blocking
        sends with select backpressure, ``src/node_state.py:39-89``). A
        lock timeout raises but keeps the link (the channel was merely
        busy); a *socket* timeout kills the link (bytes may be half-sent)."""
        if self._stop.is_set():
            raise ConnectionError(
                f"remote worker {self.worker_id} link is down"
            )
        timeout = (
            self._fault.send_timeout_s if lock_timeout is None else lock_timeout
        )
        if not self._send_lock.acquire(timeout=timeout):
            raise TimeoutError(
                f"{self.worker_id} send channel busy for {timeout}s"
            )
        try:
            send_msg(self._sock, msg)
        except TimeoutError:
            self._mark_dead("send timed out (peer not draining)")
            raise ConnectionError(
                f"send to {self.worker_id} timed out; link dropped"
            ) from None
        except OSError as e:
            self._mark_dead(f"send failed: {e}")
            raise
        finally:
            self._send_lock.release()

    # -- StageWorker interface ----------------------------------------------

    @property
    def state(self) -> WorkerState:
        if self._stop.is_set():
            return WorkerState.DEAD
        with self._count_lock:
            return (
                WorkerState.BUSY if self._inflight_count else WorkerState.IDLE
            )

    @property
    def queue_depth(self) -> int:
        with self._count_lock:
            return self._inflight_count

    def is_configured(self, stage_index: int) -> bool:
        return stage_index in self._configured

    def configure(
        self, stage_index: int, fn, host_variables, spec=None, abort=None
    ) -> int:
        """Ship (model name, cuts, stage index) + the stage weights as a
        count-prefixed stream of per-array compressed frames (reference:
        ``src/dispatcher.py:76-89``), then wait for the generation's ACK.
        ``fn`` is ignored — the remote compiles its own stage program.
        Each array frame takes the send lock independently, so data and
        probe traffic interleave with a large weights transfer instead of
        queueing behind one monolithic send."""
        del fn, spec
        if self._stop.is_set():
            raise ConnectionError(
                f"remote worker {self.worker_id} link is down"
            )
        gen = next(self._config_gen)
        key = (stage_index, gen)
        cache_key = (stage_index, self._wcodec.name)
        blobs = (
            self._blob_cache.get(cache_key)
            if self._blob_cache is not None
            else None
        )
        if blobs is None:
            leaves = jax.tree_util.tree_leaves(host_variables)
            blobs = [
                codec_lib.pack(self._wcodec, np.asarray(leaf))
                for leaf in leaves
            ]
            if self._blob_cache is not None:
                self._blob_cache[cache_key] = blobs
        header = json.dumps(
            {
                **self._model_config,
                "stage_index": stage_index,
                "codec": self._codec_name,
                "n_arrays": len(blobs),
            }
        ).encode()
        ack = threading.Event()
        with self._ack_lock:
            self._config_acks[key] = ack
        end_sent = False
        try:
            self._send(Message(MSG_CONFIG, stage_index, gen, 0, header))
            for i, blob in enumerate(blobs):
                if abort is not None and abort():
                    raise RuntimeError(
                        f"configure of stage {stage_index} aborted "
                        f"mid-stream (caller timed out)"
                    )
                self._send(
                    Message(MSG_CONFIG_ARRAY, stage_index, gen, i, blob)
                )
            end_sent = True
            self._send(Message(MSG_CONFIG_END, stage_index, gen, 0, b""))
            if not ack.wait(self._fault.configure_timeout_s):
                raise TimeoutError(
                    f"no config ACK for stage {stage_index} (gen {gen}) "
                    f"from {self.worker_id}"
                )
            with self._ack_lock:
                err = self._config_errors.pop(key, None)
            if err is not None:
                raise RuntimeError(f"remote configure failed: {err}")
            if abort is not None and abort():
                raise RuntimeError(
                    f"configure of stage {stage_index} aborted "
                    f"(caller timed out)"
                )
            self._configured[stage_index] = max(
                self._configured.get(stage_index, 0), gen
            )
            return gen
        except BaseException:
            # CONFIG_END already went out (or an abort fired late): the
            # server may install — or have installed — the stage for a
            # handshake we just declared failed. Revoke this generation so
            # the worker doesn't pin abandoned weights; the revoke is
            # gen-scoped, so a racing newer configure's binding survives.
            if end_sent:
                try:
                    self._send(
                        Message(MSG_UNCONFIGURE, stage_index, gen, 0, b"")
                    )
                except Exception:  # noqa: BLE001 — link may be down
                    pass
            raise
        finally:
            with self._ack_lock:
                self._config_acks.pop(key, None)
                self._config_errors.pop(key, None)

    @property
    def chain_address(self) -> tuple[str, int] | None:
        """Where a chain peer can dial this worker, or None when unknown
        (gateway joiners don't announce a listen port)."""
        return self.address if self._dialed_out else None

    def send_route(
        self,
        stage_index: int,
        next_addr: tuple[str, int] | None,
        next_stage: int = -1,
        clear: bool = False,
    ) -> None:
        """Install (or clear) the worker's direct next-hop for
        ``stage_index``. Installs wait for the ACK — reliable, like
        configure. CLEARS are fire-and-forget with a short lock wait:
        they run on the shared forward pool right when a chain just
        failed, and correctness never depends on them (hub traffic uses
        plain MSG_DATA, which ignores routes) — blocking recovery threads
        for configure_timeout_s per clear would starve the replay path.
        ``next_addr=None`` (without ``clear``) marks the chain tail."""
        gen = next(self._config_gen)
        key = (stage_index, gen)
        payload = json.dumps(
            {"clear": True}
            if clear
            else {
                "next": list(next_addr) if next_addr else None,
                "next_stage": next_stage,
            }
        ).encode()
        if clear:
            self._send(
                Message(MSG_SET_ROUTE, stage_index, gen, 0, payload),
                lock_timeout=1.0,
            )
            return
        ack = threading.Event()
        with self._ack_lock:
            self._config_acks[key] = ack
        try:
            self._send(Message(MSG_SET_ROUTE, stage_index, gen, 0, payload))
            if not ack.wait(self._fault.configure_timeout_s):
                raise TimeoutError(
                    f"no route ACK for stage {stage_index} from "
                    f"{self.worker_id}"
                )
            with self._ack_lock:
                err = self._config_errors.pop(key, None)
            if err is not None:
                raise RuntimeError(f"route install failed: {err}")
        finally:
            with self._ack_lock:
                self._config_acks.pop(key, None)
                self._config_errors.pop(key, None)

    def unconfigure(
        self, stage_index: int, generation: int | None = None
    ) -> None:
        """Drop the stage binding on the remote (and locally): interface
        parity with ``StageWorker.unconfigure``. With ``generation``, the
        revoke is scoped to that configure (gen 0 = unconditional) so a
        newer configure's binding survives an old undo."""
        if generation is None:
            self._configured.pop(stage_index, None)
        elif self._configured.get(stage_index) == generation:
            self._configured.pop(stage_index, None)
        try:
            self._send(
                Message(
                    MSG_UNCONFIGURE, stage_index, generation or 0, 0, b""
                )
            )
        except Exception:  # noqa: BLE001 — best effort; link may be down
            pass

    def submit(self, task) -> None:
        if task.stage_index < 0:
            # Canary probe (control.dispatcher watchdog): no payload, no
            # in-flight accounting — the dispatcher tracks it in _probes.
            # Extra-short lock wait: the watchdog thread calls this and a
            # dropped probe is recoverable (it just re-probes later).
            self._send(
                Message(
                    MSG_PROBE,
                    task.stage_index,
                    task.request_id,
                    task.attempt,
                    b"",
                ),
                lock_timeout=1.0,
            )
            return
        # Pass the payload through un-coerced: device-side codecs
        # (int8dev) quantize on-chip BEFORE the host fetch; host codecs
        # call np.ascontiguousarray themselves. pack_frames: the encoded
        # payload rides as buffer views into the framing layer's scatter
        # write — no host-side header+payload concatenation.
        payload = codec_lib.pack_frames(self._codec, task.payload)
        if getattr(task, "chained", False):
            # Chain-mode head submit: the RESULT arrives on the TAIL
            # worker's link, so counting it here would leak this proxy's
            # in-flight depth forever. The dispatcher tracks chain
            # requests in its own in-flight registry.
            self._send(
                Message(
                    MSG_DATA_CHAINED,
                    task.stage_index,
                    task.request_id,
                    task.attempt,
                    payload,
                )
            )
            return
        key = (task.stage_index, task.request_id, task.attempt)
        with self._count_lock:
            self._inflight_count += 1
            self._counted.add(key)
        try:
            self._send(
                Message(
                    MSG_DATA,
                    task.stage_index,
                    task.request_id,
                    task.attempt,
                    payload,
                )
            )
        except Exception:
            with self._count_lock:
                if key in self._counted:
                    self._counted.discard(key)
                    self._inflight_count = max(0, self._inflight_count - 1)
            raise

    def kill(self, mode: str = "crash") -> None:
        self._send(Message(MSG_KILL, 0, 0, 0, mode.encode()))

    # -- internals -----------------------------------------------------------

    def _read_loop(self) -> None:
        while not self._stop.is_set():
            try:
                msg = recv_msg(self._sock)
            except (ConnectionError, OSError):
                break
            if msg.msg_type == MSG_PING:
                self._registry.heartbeat(
                    self.worker_id, ttl_s=self._fault.lease_ttl_s
                )
            elif msg.msg_type == MSG_TELEMETRY:
                # Fold the worker's report into the process-global
                # federated store under THIS lease's worker id (the
                # report only knows its port). Malformed reports are
                # counted, never allowed to kill the read loop.
                try:
                    global_federated_store().ingest(
                        json.loads(payload_bytes(msg.payload).decode()),
                        worker=self.worker_id,
                    )
                    global_metrics().inc("fleet.reports_total")
                except Exception:  # noqa: BLE001
                    global_metrics().inc("fleet.report_rejected_total")
            elif msg.msg_type == MSG_PROBE_ACK:
                self._results.put(
                    TaskResult(
                        request_id=msg.request_id,
                        stage_index=msg.stage_index,
                        attempt=msg.attempt,
                        worker_id=self.worker_id,
                    )
                )
            elif msg.msg_type == MSG_ACK:
                with self._ack_lock:
                    ev = self._config_acks.get(
                        (msg.stage_index, msg.request_id)
                    )
                if ev is not None:
                    ev.set()
            elif msg.msg_type == MSG_CONFIG_ERR:
                key = (msg.stage_index, msg.request_id)
                with self._ack_lock:
                    self._config_errors[key] = payload_bytes(
                        msg.payload
                    ).decode()
                    ev = self._config_acks.get(key)
                if ev is not None:
                    ev.set()
            elif msg.msg_type in (MSG_RESULT, MSG_ERROR):
                self.results_received += 1
                self.result_bytes_received += len(msg.payload)
                if msg.annex:
                    # Remote-recorded spans for this request: stitch them
                    # into the local trace ring (they keep the worker's
                    # pid/tid, so /trace.json shows them on their own
                    # process row, correlated by args.request).
                    tracer = global_tracer()
                    if tracer.enabled:
                        # ingest() is garbage-tolerant (non-list JSON,
                        # malformed entries); only the decode itself can
                        # raise here. NOTHING may escape — an exception
                        # would kill the read loop without _mark_dead
                        # and silently strand every future result.
                        try:
                            tracer.ingest(json.loads(msg.annex.decode()))
                        except (ValueError, UnicodeDecodeError):
                            global_metrics().inc("tracer.ingest_rejected")
                # Only a result matching a submit THIS proxy counted may
                # decrement: a chain tail delivers results for requests
                # the HEAD proxy submitted (never counted here), and
                # blindly decrementing would deflate this link's
                # in-flight depth and skew least-loaded _acquire ranking
                # toward the tail worker (ADVICE r5).
                key = (msg.stage_index, msg.request_id, msg.attempt)
                with self._count_lock:
                    if key in self._counted:
                        self._counted.discard(key)
                        self._inflight_count = max(
                            0, self._inflight_count - 1
                        )
                if msg.msg_type == MSG_RESULT:
                    self._results.put(
                        TaskResult(
                            request_id=msg.request_id,
                            stage_index=msg.stage_index,
                            attempt=msg.attempt,
                            worker_id=self.worker_id,
                            output=codec_lib.unpack(msg.payload),
                        )
                    )
                else:
                    self._results.put(
                        TaskResult(
                            request_id=msg.request_id,
                            stage_index=msg.stage_index,
                            attempt=msg.attempt,
                            worker_id=self.worker_id,
                            error=payload_bytes(msg.payload).decode(),
                        )
                    )
        # Socket gone: mark the link dead so the scheduler stops picking
        # us and membership re-dispatches in-flight work immediately
        # (stopping lease renewal alone would add a full TTL of latency).
        self._mark_dead("connection closed")
        # Unblock any configure() still waiting on an ACK that can never
        # arrive now.
        with self._ack_lock:
            for key, ev in self._config_acks.items():
                self._config_errors.setdefault(key, "link down")
                ev.set()


class WorkerGateway:
    """Dispatcher-side listener for worker-initiated joins.

    The reference's pool can grow because the *worker* registers itself in
    etcd and the dispatcher discovers it (``/root/reference/src/
    node_state.py:17-20``, read at ``src/dispatcher.py:285-289``). Here a
    fresh worker dials this gateway (``python -m adapt_tpu.comm.remote
    --connect host:port``), announces MSG_HELLO, and the gateway wraps the
    accepted socket in a :class:`RemoteWorkerProxy`, registers its lease,
    and attaches it to the dispatcher — which fires the registry ``join``
    watch and prewarms the newcomer's executables
    (``control/dispatcher.py`` ``_on_membership``). From that point the
    joined worker is indistinguishable from a dial-out proxy: late
    binding, probes, quarantine, and re-dispatch all apply.

    Codec routing: the activation and weights codecs come from the
    dispatcher's ``ServeConfig.codec`` — the one knob configures every
    worker that joins.

    Hardening (above reference parity — the reference has no auth
    anywhere, SURVEY.md §2.8): a joiner announcing a ``worker_id`` that
    is currently LIVE is rejected (it would race the live proxy's lease
    and confuse result routing; lease tokens protect eviction, not
    identity), and an optional ``secret`` must match the HELLO's
    (constant-time compare) — closing the open-port spoof when the
    gateway listens beyond localhost."""

    def __init__(
        self,
        dispatcher,
        model_config: dict,
        host: str = "127.0.0.1",
        port: int = 0,
        secret: str | None = None,
    ):
        self._dispatcher = dispatcher
        self._model_config = model_config
        self._secret = secret
        codec_cfg = dispatcher.config.codec
        self._codec_name = codec_cfg.name
        self._weights_codec = codec_cfg.weights
        self._fault = dispatcher.config.fault
        self._host = host
        self._port = port
        self._srv: socket.socket | None = None
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._proxies: list[RemoteWorkerProxy] = []
        self._proxies_lock = threading.Lock()
        # Shared across all joined workers: the packed weight frames for a
        # stage are identical for every joiner, so compress once.
        self._blob_cache: dict = {}

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> "WorkerGateway":
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self._host, self._port))
        self._srv.listen(16)
        self._port = self._srv.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="gateway-accept", daemon=True
        )
        self._accept_thread.start()
        log.info("worker gateway listening on %s:%d", self._host, self._port)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        with self._proxies_lock:
            proxies = list(self._proxies)
        for p in proxies:
            p.stop()

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # Hard deadline on HELLO: this loop is serial, so a silent
                # dialer must not block every other join.
                conn.settimeout(10.0)
                msg = recv_msg(conn, retry_on_timeout=False)
                if msg.msg_type != MSG_HELLO:
                    raise ValueError(
                        f"expected HELLO, got msg type {msg.msg_type}"
                    )
                info = json.loads(payload_bytes(msg.payload).decode())
                worker_id = info["worker_id"]
                if self._secret is not None and not hmac.compare_digest(
                    str(info.get("secret", "")), self._secret
                ):
                    raise ValueError(
                        "join rejected: bad or missing gateway secret"
                    )
                if worker_id in self._dispatcher.registry.alive():
                    # A live duplicate would race the existing proxy's
                    # lease and interleave two links' results under one
                    # identity. (A JOINER replacing its own dead link is
                    # fine: the dead proxy deregistered on link close.)
                    raise ValueError(
                        f"join rejected: worker_id {worker_id!r} is "
                        "currently live"
                    )
                proxy = RemoteWorkerProxy(
                    worker_id,
                    addr,
                    self._dispatcher.registry,
                    self._dispatcher.result_queue,
                    model_config=self._model_config,
                    codec_name=self._codec_name,
                    weights_codec=self._weights_codec,
                    fault=self._fault,
                    sock=conn,
                    blob_cache=self._blob_cache,
                )
                proxy.start()  # registers lease -> registry 'join' fires
                self._dispatcher.attach_worker(proxy)
                proxy._send(Message(MSG_HELLO_ACK, 0, 0, 0, b""))
                with self._proxies_lock:
                    # Sweep proxies whose links died (worker churn): the
                    # gateway must not accumulate a dead proxy per join
                    # for its lifetime.
                    self._proxies = [
                        p for p in self._proxies if not p._stop.is_set()
                    ]
                    self._proxies.append(proxy)
                log.info("worker %s joined via gateway (%s)", worker_id, addr)
                global_metrics().inc("gateway.joins")
            except Exception as e:  # noqa: BLE001 — a bad joiner can't kill the loop
                log.warning("gateway join from %s failed: %s", addr, e)
                try:
                    conn.close()
                except OSError:
                    pass


def main() -> None:
    """CLI entry (the reference's ``python -m src.node``, README.md:44):

    - ``python -m adapt_tpu.comm.remote --port 7001`` — listen and wait
      for a dispatcher to dial in (dial-out proxy path).
    - ``python -m adapt_tpu.comm.remote --connect host:port`` — join a
      RUNNING pipeline through its WorkerGateway (worker-initiated
      registration, ``src/node_state.py:17-20``)."""
    import argparse
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=None)
    p.add_argument(
        "--connect",
        default=None,
        metavar="HOST:PORT",
        help="dial a dispatcher WorkerGateway and join its pool",
    )
    p.add_argument("--worker-id", default=None)
    p.add_argument("--device-index", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--heartbeat", type=float, default=0.5)
    p.add_argument(
        "--telemetry-s",
        type=float,
        default=2.0,
        help="telemetry-federation report cadence on the dispatcher "
        "link (seconds; 0 disables the push)",
    )
    p.add_argument(
        "--secret",
        default=os.environ.get("ADAPT_TPU_GATEWAY_SECRET"),
        help="gateway join secret (or env ADAPT_TPU_GATEWAY_SECRET)",
    )
    p.add_argument(
        "--no-registry",
        action="store_true",
        help="bare-image stance: serve only architecture-by-value "
        "(graph_spec) configures, never the local model registry",
    )
    args = p.parse_args()
    if (args.port is None) == (args.connect is None):
        p.error("exactly one of --port / --connect is required")
    server = RemoteStageServer(
        args.port or 0,
        device_index=args.device_index,
        heartbeat_s=args.heartbeat,
        host=args.host,
        allow_registry=not args.no_registry,
        telemetry_s=args.telemetry_s,
    )
    if args.connect is not None:
        host, _, port = args.connect.rpartition(":")
        worker_id = args.worker_id or f"remote-{os.getpid()}"
        server.connect_and_serve(
            (host, int(port)), worker_id, secret=args.secret
        )
    else:
        server.serve_forever()


if __name__ == "__main__":
    main()
