"""Build + load the native qcodec library (C++ via ctypes).

The reference leans on pip-native compression (lz4/zfpy C bindings,
``/root/reference/README.md:19``); our native piece is first-party:
``native/qcodec.cpp``, an LZ77 byte codec compiled on first use with g++
and loaded through ctypes (no pybind11 in this image). Falls back to
zlib (stdlib) if no toolchain is available, keeping the codec API usable
everywhere.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import global_metrics
from adapt_tpu.utils.tracing import global_flight_recorder

log = get_logger("native")

_REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "native" / "qcodec.cpp"
_SO = _REPO_ROOT / "native" / "build" / "libqcodec.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    _SO.parent.mkdir(parents=True, exist_ok=True)
    cmd = [
        "g++",
        "-O3",
        "-shared",
        "-fPIC",
        "-std=c++17",
        str(_SRC),
        "-o",
        str(_SO),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        log.warning("qcodec build failed (%s); falling back to zlib", e)
        return False


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                # Fallback visibility: a zlib-serving pool looks healthy
                # but pays different codec CPU — surface the downgrade on
                # /metrics and in the flight recorder, not just a log
                # line at import time.
                global_metrics().inc("native.qcodec_fallback")
                global_flight_recorder().record(
                    "native_codec", built=False, fallback="zlib"
                )
                return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError as e:
            log.warning("qcodec load failed: %s", e)
            global_metrics().inc("native.qcodec_fallback")
            global_flight_recorder().record(
                "native_codec", built=True, loaded=False, fallback="zlib"
            )
            return None
        lib.qz_bound.restype = ctypes.c_size_t
        lib.qz_bound.argtypes = [ctypes.c_size_t]
        lib.qz_compress.restype = ctypes.c_size_t
        lib.qz_compress.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.qz_decompress.restype = ctypes.c_size_t
        lib.qz_decompress.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        _lib = lib
        global_metrics().inc("native.qcodec_loaded")
        return _lib


def _c_src(buf):
    """ctypes-passable view of any bytes-like object WITHOUT copying when
    possible: bytes pass through (c_char_p accepts them) and writable
    buffers (ndarray.data, bytearray) wrap via from_buffer; only
    read-only non-bytes views pay a materializing copy."""
    if isinstance(buf, bytes):
        return buf, len(buf)
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    if mv.readonly:
        b = bytes(mv)
        return b, len(b)
    return (ctypes.c_char * mv.nbytes).from_buffer(mv), mv.nbytes


def compress(data) -> bytes:
    """LZ-compress any bytes-like object (bytes, bytearray, memoryview,
    ndarray buffer) — buffer inputs avoid a staging ``tobytes`` copy."""
    lib = load()
    if lib is None:
        import zlib

        return b"Z" + zlib.compress(data, 1)
    src, n_src = _c_src(data)
    bound = lib.qz_bound(n_src)
    dst = ctypes.create_string_buffer(bound)
    n = lib.qz_compress(src, n_src, dst, bound)
    if n == 0:
        raise RuntimeError("qz_compress failed")
    return b"Q" + dst.raw[:n]


def decompress(blob, raw_len: int) -> bytes:
    mv = blob if isinstance(blob, memoryview) else memoryview(blob)
    tag, body = bytes(mv[:1]), mv[1:]
    if tag == b"Z":
        import zlib

        return zlib.decompress(body)
    if tag != b"Q":
        raise ValueError(f"unknown qcodec tag {tag!r}")
    if raw_len == 0:
        return b""  # qz_decompress uses 0 for errors; disambiguate here
    lib = load()
    if lib is None:
        raise RuntimeError("native qcodec unavailable for 'Q' blob")
    src, n_src = _c_src(body)
    dst = ctypes.create_string_buffer(raw_len)
    n = lib.qz_decompress(src, n_src, dst, raw_len)
    if n == 0:
        raise ValueError("qz_decompress: malformed input")
    return dst.raw[:n]
