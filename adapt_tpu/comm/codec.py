"""Tensor codecs for host/DCN-boundary transport.

The reference compresses every tensor at every hop with zfp (lossy-capable
float compression) wrapped in lz4 (``/root/reference/src/dispatcher.py:
92-98``) — paying CPU on multi-MB activations even between colocated
processes. TPU-native framing: ICI hops need no codec (stages exchange
device arrays directly); codecs apply only when a tensor crosses a host
boundary. Offered codecs:

- ``raw``:   dtype-preserving bytes.
- ``bf16``:  cast f32 -> bfloat16 (2x smaller; TPU-native dtype, so the
             receiving stage computes on it directly).
- ``int8``:  per-tensor absmax affine quantization (4x smaller vs f32) —
             the zfp-tolerance analog for activations.
- ``zfp``:   int16 fixed-tolerance quantization + native LZ77 compression
             (``native/qcodec.cpp``) — the closest analog of the
             reference's zfp+lz4 stack, with a user tolerance like zfp's
             accuracy mode.
- ``lz``:    LOSSLESS native LZ77 over the raw bytes — the lz4-frame
             analog (the reference wraps every payload in lz4,
             ``src/dispatcher.py:92-93``); the default for *weights*,
             where lossy codecs are off the table.
- ``int8dev``: blockwise int8 via the on-device Pallas kernel
             (``ops/quantize.py``) — quantizes in VMEM *before* the
             host fetch, so the device->host copy itself is 4x smaller
             (SURVEY.md §2.3 "on-device quantization at DCN
             boundaries"). Host-side codecs above shrink only the wire;
             this one shrinks the PCIe/DMA hop too.

Zero-copy framing contract (the serving hot path):

- ``encode_view(x) -> (parts, meta)`` returns the payload as a list of
  buffer views with NO framing copy: ``raw`` hands out a memoryview of
  the (contiguous) array itself; the transforming codecs hand out views
  of the single array their transform materialized. ``encode`` remains
  the bytes-returning compat wrapper.
- ``pack_frames`` returns ``[length+header, *payload_views]`` for
  scatter writes (``socket.sendmsg``) — zero payload copies on the send
  path. ``pack`` assembles the same frames into ONE pre-sized buffer
  (exactly one payload copy, down from two in the old
  encode-then-concat scheme); ``pack_into`` reuses a caller-pooled
  ``bytearray``.
- ``unpack`` slices with memoryviews, so ``decode`` sees a view of the
  receive buffer and ``raw`` decode returns an array that SHARES memory
  with it (``np.frombuffer``) — no receive-side copy either.

Framing-layer payload copies are counted in module counters
(:func:`copy_stats` / :func:`reset_copy_stats`) so tests and
``benchmarks/micro/codec_framing.py`` can assert the ≤1-copy budget
instead of trusting the docstring.

All codecs are symmetric: ``decode(*encode(x))`` returns an array of the
original shape/dtype (within the codec's stated tolerance).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Protocol

import numpy as np

from adapt_tpu.comm import native
from adapt_tpu.utils.metrics import global_metrics

# -- framing-copy accounting -------------------------------------------------

#: Bytes/calls of PAYLOAD memcpy performed by the framing layer (frame
#: assembly and bytes-compat joins). Codec transforms (cast/quantize/
#: compress) are not copies — they produce the payload; what these count
#: is every time already-encoded payload bytes are moved again.
_COPY_BYTES = 0
_COPY_CALLS = 0
#: pack/unpack run concurrently (one hop thread per LocalPipeline stage,
#: one sender thread per remote proxy) — unsynchronized += would lose
#: increments exactly when the pipeline is actually pipelining.
_COPY_LOCK = threading.Lock()


def _count_copy(nbytes: int) -> None:
    global _COPY_BYTES, _COPY_CALLS
    with _COPY_LOCK:
        _COPY_BYTES += int(nbytes)
        _COPY_CALLS += 1


def copy_stats() -> dict:
    """Framing-layer payload-copy counters since the last reset."""
    with _COPY_LOCK:
        return {"bytes": _COPY_BYTES, "calls": _COPY_CALLS}


def reset_copy_stats() -> None:
    global _COPY_BYTES, _COPY_CALLS
    with _COPY_LOCK:
        _COPY_BYTES = 0
        _COPY_CALLS = 0


def _copy_stats_collector(registry) -> None:
    """Pull the module counters into the registry at scrape time —
    ``/metrics`` shows ``codec.copy_bytes``/``codec.copy_calls`` without
    a registry write on every pack/unpack."""
    s = copy_stats()
    registry.set_gauge("codec.copy_bytes", float(s["bytes"]))
    registry.set_gauge("codec.copy_calls", float(s["calls"]))


global_metrics().register_collector(_copy_stats_collector)


def _byte_view(buf) -> memoryview:
    """Flat uint8 view of any buffer-protocol object (no copy)."""
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    return mv


def _array_view(a: np.ndarray) -> memoryview:
    """Byte view of a contiguous ndarray's buffer — no copy. Extension
    dtypes without buffer support (ml_dtypes bfloat16) reinterpret as
    uint8 first (a view, still no copy)."""
    try:
        return _byte_view(a.data)
    except (ValueError, TypeError):
        return _byte_view(a.view(np.uint8).data)


def _parts_nbytes(parts) -> int:
    return sum(_byte_view(p).nbytes for p in parts)


def _join_parts(parts) -> bytes:
    """bytes-compat assembly of encode_view parts (counted as a copy)."""
    views = [_byte_view(p) for p in parts]
    _count_copy(sum(v.nbytes for v in views))
    if len(views) == 1:
        return views[0].tobytes()
    return b"".join(views)


class Codec(Protocol):
    name: str

    def encode(self, x: np.ndarray) -> tuple[bytes, dict]: ...

    def encode_view(self, x) -> tuple[list, dict]: ...

    def decode(self, blob, meta: dict) -> np.ndarray: ...


def _meta(x: np.ndarray, **extra) -> dict:
    return {"shape": list(x.shape), "dtype": str(x.dtype), **extra}


class _ViewEncodeMixin:
    """``encode`` as the compat wrapper over the zero-copy ``encode_view``."""

    def encode(self, x) -> tuple[bytes, dict]:
        parts, meta = self.encode_view(x)
        return _join_parts(parts), meta


@dataclass(frozen=True)
class RawCodec(_ViewEncodeMixin):
    name: str = "none"

    def encode_view(self, x) -> tuple[list, dict]:
        # Contiguous input: the "payload" IS the array's buffer — zero
        # copies (ascontiguousarray is the identity there).
        x = np.ascontiguousarray(x)
        return [_array_view(x)], _meta(x)

    def decode(self, blob, meta: dict) -> np.ndarray:
        # frombuffer VIEWS blob: with a memoryview of the receive buffer
        # this is the zero-copy receive path (read-only array when the
        # buffer is immutable bytes — serving never mutates activations
        # in place).
        return np.frombuffer(blob, dtype=meta["dtype"]).reshape(meta["shape"])


@dataclass(frozen=True)
class Bf16Codec(_ViewEncodeMixin):
    name: str = "bf16"

    def encode_view(self, x) -> tuple[list, dict]:
        import ml_dtypes

        x = np.ascontiguousarray(x)
        y = x.astype(ml_dtypes.bfloat16)  # the transform, not a copy
        return [_array_view(y)], _meta(x)

    def decode(self, blob, meta: dict) -> np.ndarray:
        import ml_dtypes

        y = np.frombuffer(blob, dtype=ml_dtypes.bfloat16)
        return y.astype(meta["dtype"]).reshape(meta["shape"])


@dataclass(frozen=True)
class Int8Codec(_ViewEncodeMixin):
    name: str = "int8"

    def encode_view(self, x) -> tuple[list, dict]:
        x = np.ascontiguousarray(x)
        scale = float(np.max(np.abs(x))) / 127.0 or 1.0
        q = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
        return [_array_view(q)], _meta(x, scale=scale)

    def decode(self, blob, meta: dict) -> np.ndarray:
        q = np.frombuffer(blob, dtype=np.int8).reshape(meta["shape"])
        return (q.astype(np.float32) * meta["scale"]).astype(meta["dtype"])


@dataclass(frozen=True)
class ZfpLikeCodec(_ViewEncodeMixin):
    """Fixed-tolerance int16 quantization + native LZ compression — the
    accuracy-mode zfp analog (reference default is reversible mode; our
    tolerance defaults are conservative)."""

    tolerance: float = 1e-3
    name: str = "zfp"

    def encode_view(self, x) -> tuple[list, dict]:
        x = np.ascontiguousarray(x, dtype=np.float32)
        # Quantization step sized so |err| <= tolerance/2; clamp the range
        # so int16 suffices (meta carries the actual scale).
        step = max(self.tolerance, float(np.max(np.abs(x))) / 32767.0, 1e-12)
        q = np.clip(np.round(x / step), -32767, 32767).astype(np.int16)
        raw_len = q.nbytes
        # The compressor reads the quantized array's buffer directly —
        # no tobytes staging copy.
        comp = native.compress(_array_view(q))
        return [comp], _meta(x, step=step, raw_len=raw_len)

    def decode(self, blob, meta: dict) -> np.ndarray:
        raw = native.decompress(blob, meta["raw_len"])
        q = np.frombuffer(raw, dtype=np.int16).reshape(meta["shape"])
        return (q.astype(np.float32) * meta["step"]).astype(meta["dtype"])


@dataclass(frozen=True)
class LzCodec(_ViewEncodeMixin):
    """Lossless: raw bytes through the native LZ77 compressor. Dtype- and
    bit-exact, so safe for weights and integer tensors."""

    name: str = "lz"

    def encode_view(self, x) -> tuple[list, dict]:
        x = np.ascontiguousarray(x)
        raw_len = x.nbytes
        return [native.compress(_array_view(x))], _meta(
            x, raw_len=raw_len
        )

    def decode(self, blob, meta: dict) -> np.ndarray:
        raw = native.decompress(blob, meta["raw_len"])
        return np.frombuffer(raw, dtype=meta["dtype"]).reshape(meta["shape"])


@dataclass(frozen=True)
class DeviceInt8Codec(_ViewEncodeMixin):
    """Blockwise int8 quantization executed *on device* (Pallas kernel,
    ``ops/quantize.py``): the tensor leaves the chip already 4x smaller.
    Encode accepts a jax.Array (host ndarrays are device_put first);
    decode dequantizes on the default device and returns a host array."""

    name: str = "int8dev"

    def encode_view(self, x) -> tuple[list, dict]:
        import jax.numpy as jnp

        from adapt_tpu.ops.quantize import quantize

        arr = x if hasattr(x, "devices") else jnp.asarray(x)
        qt = quantize(arr)
        vals = np.ascontiguousarray(qt.values)  # the 4x-smaller host fetch
        scales = np.ascontiguousarray(qt.scales)
        # Two natural payload parts (scatter write sends both without the
        # old vals+scales concat).
        return [_array_view(vals), _array_view(scales)], {
            "shape": list(qt.shape),
            "dtype": str(np.dtype(qt.dtype)),
            "rows": list(vals.shape),
            "nblocks": int(scales.shape[0]),
        }

    def decode(self, blob, meta: dict) -> np.ndarray:
        import jax.numpy as jnp

        from adapt_tpu.ops.quantize import QuantizedTensor, dequantize

        blob = _byte_view(blob)
        rows = tuple(meta["rows"])
        nvals = rows[0] * rows[1]
        vals = np.frombuffer(blob[:nvals], dtype=np.int8).reshape(rows)
        scales = np.frombuffer(blob[nvals:], dtype=np.float32).reshape(
            meta["nblocks"], 1
        )
        qt = QuantizedTensor(
            jnp.asarray(vals),
            jnp.asarray(scales),
            tuple(meta["shape"]),
            np.dtype(meta["dtype"]),
        )
        return np.asarray(dequantize(qt))


CODECS: dict[str, Codec] = {
    "none": RawCodec(),
    "bf16": Bf16Codec(),
    "int8": Int8Codec(),
    "zfp": ZfpLikeCodec(),
    "lz": LzCodec(),
    "int8dev": DeviceInt8Codec(),
}


def get_codec(name: str, tolerance: float | None = None) -> Codec:
    if name == "zfp" and tolerance is not None:
        return ZfpLikeCodec(tolerance=tolerance)
    try:
        return CODECS[name]
    except KeyError:
        raise ValueError(
            f"unknown codec {name!r}; have {sorted(CODECS)}"
        ) from None


def _encode_parts(codec: Codec, x) -> tuple[list, dict]:
    """encode_view when the codec offers it; bytes-compat fallback for
    third-party codecs that only implement ``encode``."""
    view = getattr(codec, "encode_view", None)
    if view is not None:
        return view(x)
    blob, meta = codec.encode(x)
    return [blob], meta


def pack_frames(codec: Codec, x) -> list:
    """The frame as scatter-write parts: ``[4-byte header length + JSON
    header, *payload buffer views]``. ZERO payload copies — hand the
    list to ``framing.send_msg`` (``socket.sendmsg``) or assemble it
    with :func:`pack`. The payload views may alias ``x``; send (or copy)
    before mutating it."""
    parts, meta = _encode_parts(codec, x)
    header = json.dumps({"codec": codec.name, **meta}).encode()
    return [len(header).to_bytes(4, "big") + header, *parts]


def frames_nbytes(frames) -> int:
    """Total wire size of a :func:`pack_frames` result."""
    return _parts_nbytes(frames)


def pack_into(codec: Codec, x, buf: bytearray) -> memoryview:
    """Assemble the frame into caller-pooled ``buf`` (grown in place,
    never shrunk) and return a view of the written region — exactly ONE
    payload copy and zero allocations once the pool is warm. The view
    aliases ``buf``: consume it before the next ``pack_into`` on the
    same pool."""
    frames = pack_frames(codec, x)
    total = _parts_nbytes(frames)
    if len(buf) < total:
        buf.extend(bytes(total - len(buf)))
    out = memoryview(buf)
    off = 0
    for part in frames:
        v = _byte_view(part)
        out[off : off + v.nbytes] = v
        off += v.nbytes
    _count_copy(total - len(frames[0]))  # header writes aren't payload
    return out[:total]


def pack(codec: Codec, x) -> bytearray:
    """codec name + meta + payload in one self-describing buffer.

    One payload copy (frame assembly into a pre-sized buffer), down
    from two in the old encode-``tobytes``-then-concat scheme; use
    :func:`pack_frames` for the zero-copy scatter-write path."""
    frames = pack_frames(codec, x)
    buf = bytearray(_parts_nbytes(frames))
    out = memoryview(buf)
    off = 0
    for part in frames:
        v = _byte_view(part)
        out[off : off + v.nbytes] = v
        off += v.nbytes
    _count_copy(len(buf) - len(frames[0]))
    return buf


def unpack_many(buf, lens: list[int],
                tolerance: float | None = None) -> list[np.ndarray]:
    """Decode CONCATENATED :func:`pack` frames whose per-frame byte
    lengths are carried out of band (``lens`` — e.g. the
    disaggregated-serving page-range annex). Each frame decodes through
    :func:`unpack`, so ``raw`` frames return arrays VIEWING ``buf``
    (the zero-copy receive contract, per frame). Raises
    ``ValueError`` when ``lens`` does not tile ``buf`` exactly — a
    truncated or padded stream must fail loudly, not decode garbage."""
    mv = _byte_view(buf)
    out, off = [], 0
    for n in lens:
        if n < 0 or off + n > mv.nbytes:
            raise ValueError(
                f"frame length {n} at offset {off} overruns the "
                f"{mv.nbytes}-byte buffer"
            )
        out.append(unpack(mv[off : off + n], tolerance))
        off += n
    if off != mv.nbytes:
        raise ValueError(
            f"frame lengths cover {off} of {mv.nbytes} payload bytes"
        )
    return out


def unpack(buf, tolerance: float | None = None) -> np.ndarray:
    """Decode a :func:`pack` frame. Slices with memoryviews, so the codec
    sees a VIEW of ``buf`` and ``raw`` decode returns an array sharing
    memory with the receive buffer (zero-copy receive)."""
    mv = _byte_view(buf)
    hlen = int.from_bytes(mv[:4], "big")
    meta = json.loads(bytes(mv[4 : 4 + hlen]).decode())
    codec = get_codec(meta.pop("codec"), tolerance)
    return codec.decode(mv[4 + hlen :], meta)
