"""Tensor codecs for host/DCN-boundary transport.

The reference compresses every tensor at every hop with zfp (lossy-capable
float compression) wrapped in lz4 (``/root/reference/src/dispatcher.py:
92-98``) — paying CPU on multi-MB activations even between colocated
processes. TPU-native framing: ICI hops need no codec (stages exchange
device arrays directly); codecs apply only when a tensor crosses a host
boundary. Offered codecs:

- ``raw``:   dtype-preserving bytes.
- ``bf16``:  cast f32 -> bfloat16 (2x smaller; TPU-native dtype, so the
             receiving stage computes on it directly).
- ``int8``:  per-tensor absmax affine quantization (4x smaller vs f32) —
             the zfp-tolerance analog for activations.
- ``zfp``:   int16 fixed-tolerance quantization + native LZ77 compression
             (``native/qcodec.cpp``) — the closest analog of the
             reference's zfp+lz4 stack, with a user tolerance like zfp's
             accuracy mode.
- ``lz``:    LOSSLESS native LZ77 over the raw bytes — the lz4-frame
             analog (the reference wraps every payload in lz4,
             ``src/dispatcher.py:92-93``); the default for *weights*,
             where lossy codecs are off the table.
- ``int8dev``: blockwise int8 via the on-device Pallas kernel
             (``ops/quantize.py``) — quantizes in VMEM *before* the
             host fetch, so the device->host copy itself is 4x smaller
             (SURVEY.md §2.3 "on-device quantization at DCN
             boundaries"). Host-side codecs above shrink only the wire;
             this one shrinks the PCIe/DMA hop too.

All codecs are symmetric: ``decode(*encode(x))`` returns an array of the
original shape/dtype (within the codec's stated tolerance).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Protocol

import numpy as np

from adapt_tpu.comm import native


class Codec(Protocol):
    name: str

    def encode(self, x: np.ndarray) -> tuple[bytes, dict]: ...

    def decode(self, blob: bytes, meta: dict) -> np.ndarray: ...


def _meta(x: np.ndarray, **extra) -> dict:
    return {"shape": list(x.shape), "dtype": str(x.dtype), **extra}


@dataclass(frozen=True)
class RawCodec:
    name: str = "none"

    def encode(self, x: np.ndarray) -> tuple[bytes, dict]:
        x = np.ascontiguousarray(x)
        return x.tobytes(), _meta(x)

    def decode(self, blob: bytes, meta: dict) -> np.ndarray:
        return np.frombuffer(blob, dtype=meta["dtype"]).reshape(meta["shape"])


@dataclass(frozen=True)
class Bf16Codec:
    name: str = "bf16"

    def encode(self, x: np.ndarray) -> tuple[bytes, dict]:
        import ml_dtypes

        y = np.ascontiguousarray(x).astype(ml_dtypes.bfloat16)
        return y.tobytes(), _meta(x)

    def decode(self, blob: bytes, meta: dict) -> np.ndarray:
        import ml_dtypes

        y = np.frombuffer(blob, dtype=ml_dtypes.bfloat16)
        return y.astype(meta["dtype"]).reshape(meta["shape"])


@dataclass(frozen=True)
class Int8Codec:
    name: str = "int8"

    def encode(self, x: np.ndarray) -> tuple[bytes, dict]:
        x = np.ascontiguousarray(x)
        scale = float(np.max(np.abs(x))) / 127.0 or 1.0
        q = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
        return q.tobytes(), _meta(x, scale=scale)

    def decode(self, blob: bytes, meta: dict) -> np.ndarray:
        q = np.frombuffer(blob, dtype=np.int8).reshape(meta["shape"])
        return (q.astype(np.float32) * meta["scale"]).astype(meta["dtype"])


@dataclass(frozen=True)
class ZfpLikeCodec:
    """Fixed-tolerance int16 quantization + native LZ compression — the
    accuracy-mode zfp analog (reference default is reversible mode; our
    tolerance defaults are conservative)."""

    tolerance: float = 1e-3
    name: str = "zfp"

    def encode(self, x: np.ndarray) -> tuple[bytes, dict]:
        x = np.ascontiguousarray(x, dtype=np.float32)
        # Quantization step sized so |err| <= tolerance/2; clamp the range
        # so int16 suffices (meta carries the actual scale).
        step = max(self.tolerance, float(np.max(np.abs(x))) / 32767.0, 1e-12)
        q = np.clip(np.round(x / step), -32767, 32767).astype(np.int16)
        raw = q.tobytes()
        comp = native.compress(raw)
        return comp, _meta(x, step=step, raw_len=len(raw))

    def decode(self, blob: bytes, meta: dict) -> np.ndarray:
        raw = native.decompress(blob, meta["raw_len"])
        q = np.frombuffer(raw, dtype=np.int16).reshape(meta["shape"])
        return (q.astype(np.float32) * meta["step"]).astype(meta["dtype"])


@dataclass(frozen=True)
class LzCodec:
    """Lossless: raw bytes through the native LZ77 compressor. Dtype- and
    bit-exact, so safe for weights and integer tensors."""

    name: str = "lz"

    def encode(self, x: np.ndarray) -> tuple[bytes, dict]:
        x = np.ascontiguousarray(x)
        raw = x.tobytes()
        return native.compress(raw), _meta(x, raw_len=len(raw))

    def decode(self, blob: bytes, meta: dict) -> np.ndarray:
        raw = native.decompress(blob, meta["raw_len"])
        return np.frombuffer(raw, dtype=meta["dtype"]).reshape(meta["shape"])


@dataclass(frozen=True)
class DeviceInt8Codec:
    """Blockwise int8 quantization executed *on device* (Pallas kernel,
    ``ops/quantize.py``): the tensor leaves the chip already 4x smaller.
    Encode accepts a jax.Array (host ndarrays are device_put first);
    decode dequantizes on the default device and returns a host array."""

    name: str = "int8dev"

    def encode(self, x) -> tuple[bytes, dict]:
        import jax.numpy as jnp

        from adapt_tpu.ops.quantize import quantize

        arr = x if hasattr(x, "devices") else jnp.asarray(x)
        qt = quantize(arr)
        vals = np.asarray(qt.values)  # the 4x-smaller host fetch
        scales = np.asarray(qt.scales)
        return vals.tobytes() + scales.tobytes(), {
            "shape": list(qt.shape),
            "dtype": str(np.dtype(qt.dtype)),
            "rows": list(vals.shape),
            "nblocks": int(scales.shape[0]),
        }

    def decode(self, blob: bytes, meta: dict) -> np.ndarray:
        import jax.numpy as jnp

        from adapt_tpu.ops.quantize import QuantizedTensor, dequantize

        rows = tuple(meta["rows"])
        nvals = rows[0] * rows[1]
        vals = np.frombuffer(blob[:nvals], dtype=np.int8).reshape(rows)
        scales = np.frombuffer(blob[nvals:], dtype=np.float32).reshape(
            meta["nblocks"], 1
        )
        qt = QuantizedTensor(
            jnp.asarray(vals),
            jnp.asarray(scales),
            tuple(meta["shape"]),
            np.dtype(meta["dtype"]),
        )
        return np.asarray(dequantize(qt))


CODECS: dict[str, Codec] = {
    "none": RawCodec(),
    "bf16": Bf16Codec(),
    "int8": Int8Codec(),
    "zfp": ZfpLikeCodec(),
    "lz": LzCodec(),
    "int8dev": DeviceInt8Codec(),
}


def get_codec(name: str, tolerance: float | None = None) -> Codec:
    if name == "zfp" and tolerance is not None:
        return ZfpLikeCodec(tolerance=tolerance)
    try:
        return CODECS[name]
    except KeyError:
        raise ValueError(
            f"unknown codec {name!r}; have {sorted(CODECS)}"
        ) from None


def pack(codec: Codec, x: np.ndarray) -> bytes:
    """codec name + meta + payload in one self-describing buffer."""
    blob, meta = codec.encode(x)
    header = json.dumps({"codec": codec.name, **meta}).encode()
    return len(header).to_bytes(4, "big") + header + blob


def unpack(buf: bytes, tolerance: float | None = None) -> np.ndarray:
    hlen = int.from_bytes(buf[:4], "big")
    meta = json.loads(buf[4 : 4 + hlen].decode())
    codec = get_codec(meta.pop("codec"), tolerance)
    return codec.decode(buf[4 + hlen :], meta)
