from adapt_tpu.comm.codec import CODECS, Codec, get_codec
from adapt_tpu.comm.framing import recv_msg, send_msg

__all__ = ["CODECS", "Codec", "get_codec", "send_msg", "recv_msg"]
