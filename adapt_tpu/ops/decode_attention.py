"""Decode-time (single-token) cached attention as a Pallas TPU kernel.

The serving hot path: at every decode step each query token attends over
the whole KV cache — a (b, kv_h, g, L) score row, no S x S anything —
and the step is HBM-bandwidth-bound (the cache is read end to end per
token). The XLA path (``decode_attention_reference``, the exact einsum
schedule ``models/transformer_lm.CausalSelfAttention.decode_step`` has
always run) handles the native-dtype cache well, but the r04 hardware
A/B (``benchmarks/results/r04/lm_decode_long_{native,int8}.json``)
showed the int8 cache ~12% SLOWER than bf16 despite carrying ~1.9x
fewer bytes: XLA does not reliably keep the per-step dequantize fused
to the HBM stream. This kernel exists to close that gap the TPU-native
way — the int8 values stream from HBM and dequantize in VMEM, so the
bytes that cross the HBM bus are the int8 bytes.

Layout (one kernel for native and int8 caches):

- grid = (batch * kv_heads, L / block_k); the cache-position axis is the
  innermost (sequential) dimension, online-softmax state (running max,
  denom, accumulator) persists across it in VMEM scratch — the same
  discipline as ``ops/attention``'s streaming kernel, with q a single
  (g, head_dim) tile (GQA query groups folded into query ROWS, matching
  ``CausalSelfAttention._group_q``; g is zero-padded to a sublane
  multiple).
- int8 scales (one f32 per cached key/value vector, the product
  quantization granularity) ride as a (b*kv_h, L/128, 128) chunked view
  — the same bytes as the (b, kv_h, L, 1) product layout, 1/16th of the
  int8 payload, never 8-row-broadcast — and are applied to the score /
  probability COLUMNS, so the only op on the big cache operand is the
  int8 contribution to the dot.
- the live window (positions <= index, >= valid_from for ragged rows)
  is masked via SMEM scalars; blocks entirely outside the window skip
  their compute (``pl.when``), which matters early in a long-max_len
  decode where most of the cache is still dead.

Dispatch: ``prefer=None`` ("auto") consults ``decode_kernel_wins`` —
measured on hardware like ``ops/attention``'s budget (artifact:
``benchmarks/results/r04/lm_decode_*``; see the function docstring for
the current rule). ``prefer="pallas"``/``"xla"`` force a path (tests,
the A/B driver). Off-TPU the kernel runs through the Pallas
interpreter, so the virtual-mesh tests exercise the same code path.

No reference analog (the reference is CNN-only, SURVEY.md §2.2) — this
is the framework's own serving frontier, the decode-side counterpart of
``ops/attention``'s long-context prefill kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover — jax builds without pallas-tpu
    pltpu = None
    _VMEM = None

from adapt_tpu.ops.quantize import unpack_int4

_NEG_INF = -1e30

# -- kernel-vs-oracle dispatch accounting ------------------------------------

#: Last-resolved path + lifetime counts per decode/verify/prefill op —
#: the ``_kernel_supported`` fallback used to degrade to the XLA oracle
#: SILENTLY (a perf cliff invisible in metrics). Every dispatcher
#: records its decision here at trace time; ``utils.profiling``'s
#: engine collector exports them as ``engine.kernel_dispatch.<op>``
#: gauges (1.0 = the Pallas kernel, 0.0 = the XLA oracle) plus
#: per-path totals. Counts move at TRACE time (dispatch is resolved
#: when the surrounding program lowers, not per executed tick), so the
#: gauge answers "which path is this serving program actually built
#: on", which is the question the fallback cliff poses.
_KERNEL_DISPATCHES: dict[str, dict[str, float]] = {}


def record_kernel_dispatch(op: str, path: str) -> None:
    """Record one dispatch resolution for ``op`` (``"pallas"`` or
    ``"xla"``)."""
    d = _KERNEL_DISPATCHES.setdefault(
        op, {"pallas": 0.0, "xla": 0.0, "last": 0.0}
    )
    d[path] += 1.0
    d["last"] = 1.0 if path == "pallas" else 0.0


def kernel_dispatch_stats() -> dict[str, dict[str, float]]:
    """Snapshot of the per-op dispatch books (copies — safe to mutate)."""
    return {op: dict(d) for op, d in _KERNEL_DISPATCHES.items()}


def default_decode_split(num_blocks: int) -> int:
    """Auto-derived flash-decoding split factor for a cache of
    ``num_blocks`` position blocks (pages, for the paged layout): the
    largest power of two <= 8 that still leaves every split at least
    two blocks of work. Short caches stay unsplit (the combine pass
    would cost more than the parallelism buys); long-context slots fan
    their KV stream across splits so the whole VPU/MXU participates
    instead of one sequential stream. ``config.KernelConfig.
    decode_split`` overrides it."""
    s = 1
    while s < 8 and num_blocks >= 4 * s:
        s *= 2
    return s


def resolve_decode_split(num_blocks: int, split: int | None) -> int:
    """THE split-resolution rule every kernel dispatcher shares (decode
    / paged decode / paged verify — one definition, so the auto rule
    cannot fork across them): an explicit ``split`` wins; None
    auto-derives on real TPUs and stays 1 off-TPU, where the
    interpreter gains nothing from fan-out."""
    if split is not None:
        return split
    return (
        default_decode_split(num_blocks)
        if jax.default_backend() == "tpu"
        else 1
    )

#: Cache-position block per grid step for QUANTIZED caches. 1024 = 8
#: sublanes x 128 lanes of the chunked scale view, the smallest block
#: whose scale tile satisfies TPU (8, 128) tiling without broadcast
#: padding — int8 caches therefore need max_len % 1024 == 0. NATIVE
#: caches carry no scale tiles, so their block can shrink to 256 and the
#: kernel serves short-context configs too (the headline max_len-256 row
#: streams its cache at ~0.26 efficiency on the XLA einsum path — the
#: analytic decomposition in benchmarks/README.md — which is exactly the
#: access pattern this kernel replaces).
DECODE_BLOCK_K = 1024
_MIN_NATIVE_BLOCK_K = 256


def check_head_parity(q_heads: int, cache_heads: int) -> None:
    """Every decode/verify primitive derives its grid, GQA fold and
    block sizes from the head count of the operands it is GIVEN — which
    under tensor parallelism is the PER-SHARD count (kv_heads / tp
    inside a shard_map body; the global count under GSPMD, where the
    partitioner divides it). The one mistake that silently breaks this
    is mixing a sharded cache with globally-shaped queries (or vice
    versa) across a partial TP migration: the einsums would
    broadcast-fail deep inside XLA. Fail here, by name, instead."""
    if q_heads != cache_heads:
        raise ValueError(
            f"q carries {q_heads} KV-head rows but the cache carries "
            f"{cache_heads}: both operands must use the same (per-shard) "
            "head count — under tensor parallelism shard queries and "
            "caches together (runtime/continuous shards both on the "
            "head axis)"
        )


def default_block_k(cache_len: int, quantized: bool) -> int:
    """Largest supported cache block for this (cache_len, dtype):
    quantized caches are pinned to the scale-tile block; native caches
    take the largest of 1024/512/256 dividing the cache."""
    if quantized:
        return DECODE_BLOCK_K
    for bk in (1024, 512, _MIN_NATIVE_BLOCK_K):
        if cache_len % bk == 0:
            return bk
    return DECODE_BLOCK_K  # leaves _supported() False -> XLA fallback


def decode_kernel_wins(cache_len: int, quantized: bool) -> bool:
    """THE auto-dispatch predicate for decode attention, in one place
    like ``ops/attention.scores_over_budget``. Current rule: XLA
    everywhere — the kernel ships behind ``prefer="pallas"`` until its
    hardware A/B (``benchmarks/lm_decode.py --decode-attn pallas``)
    lands; retune this predicate from that artifact, not from
    intuition."""
    del cache_len, quantized
    return False


def _supported(cache_len: int, block_k: int, quantized: bool) -> bool:
    if pltpu is None or cache_len % block_k:
        return False
    # int8 scale tiles need (block_k//128) >= 8 rows per (8, 128) tile.
    return not quantized or block_k % DECODE_BLOCK_K == 0


def _attend_tile(q, k, v, ksc, vsc, live, m_scr, l_scr, acc_scr,
                 sm_scale, packed):
    """One cache tile's online-softmax update — THE shared step body of
    every decode/verify/chunk kernel (split or not), so the int8 fused
    dequant, the int4 nibble unpack and the masking discipline cannot
    fork across grid layouts. ``q`` (gq, hd); ``k``/``v`` (block_k, hd)
    native/int8, or (block_k, hd // 2) packed int4 (``packed``);
    ``ksc``/``vsc`` (1, block_k) f32 column scales or None; ``live``
    (gq, block_k) bool mask. Mutates the (gq, 1)/(gq, 1)/(gq, hd)
    scratch refs in place."""
    if packed:
        # Unpack two nibbles per streamed int8 lane in VMEM — the HBM
        # stream stays 4-bit; only the registers see head_dim lanes.
        k = unpack_int4(k)
        v = unpack_int4(v)
    q = q.astype(jnp.float32)
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    s = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * sm_scale
    )  # (gq, block_k)
    if ksc is not None:
        # One f32 scale per column of this block: the per-vector scale
        # factors exactly OUT of the dot, applied to the small score
        # row instead of the big cache operand.
        s = s * ksc
    s = jnp.where(live, s, _NEG_INF)
    m = m_scr[...]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    m_scr[...] = m_new
    l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = p * vsc if vsc is not None else p
    acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
        pv, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _init_softmax_scratch(m_scr, l_scr, acc_scr):
    m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
    l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
    acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)


def _decode_kernel(
    q_ref,
    k_ref,
    v_ref,
    idx_ref,
    *refs,
    block_k,
    num_kv,
    sm_scale,
    quantized,
    has_vf,
    packed=False,
):
    """One (batch, kv_head) row: stream cache blocks innermost, online
    softmax in scratch. ``q_ref`` (1, gq, hd) — gq = GQA group rows,
    sublane-padded; ``k_ref``/``v_ref`` (1, block_k, hd) int8 or native
    (``packed``: (1, block_k, hd // 2) int4 nibbles, unpacked in VMEM);
    scale tiles (1, 8, 128) f32 chunked views covering this block's
    positions row-major; ``idx_ref``/``vf_ref`` (1,) SMEM scalars."""
    refs = list(refs)
    ksc_ref = refs.pop(0) if quantized else None
    vsc_ref = refs.pop(0) if quantized else None
    vf_ref = refs.pop(0) if has_vf else None
    o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(1)
    gq = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        _init_softmax_scratch(m_scr, l_scr, acc_scr)

    def _step():
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gq, block_k), 1
        )
        live = cols <= idx_ref[0]
        if has_vf:
            live = jnp.logical_and(live, cols >= vf_ref[0])
        _attend_tile(
            q_ref[0], k_ref[0], v_ref[0],
            ksc_ref[0].reshape(1, block_k) if quantized else None,
            vsc_ref[0].reshape(1, block_k) if quantized else None,
            live, m_scr, l_scr, acc_scr, sm_scale, packed,
        )

    # Blocks entirely past the write index (the still-dead cache tail)
    # or entirely inside ragged left padding contribute nothing.
    live_block = j * block_k <= idx_ref[0]
    if has_vf:
        live_block = jnp.logical_and(
            live_block, (j + 1) * block_k > vf_ref[0]
        )
    pl.when(live_block)(_step)

    @pl.when(j == num_kv - 1)
    def _emit():
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


def _decode_split_kernel(
    q_ref,
    k_ref,
    v_ref,
    idx_ref,
    *refs,
    block_k,
    num_kv,
    bps,
    sm_scale,
    quantized,
    has_vf,
    packed=False,
):
    """Flash-decoding split variant of :func:`_decode_kernel`: grid
    (b * kv_h, split, bps) — each (row, split) streams ITS ``bps``
    cache blocks with its own online-softmax scratch and emits
    UNNORMALIZED partials (f32 accumulator + running max + denominator)
    instead of a normalized output; the caller's single-pass rescale
    combine (:func:`_combine_splits`) reduces them. Splits are
    independent, so the grid's split axis is ``parallel`` — a
    long-context row's KV stream fans across compute units instead of
    one sequential walk. The last split may be RAGGED (``split * bps >
    num_kv``): its out-of-range blocks clamp in the index maps and mask
    here, contributing nothing."""
    refs = list(refs)
    ksc_ref = refs.pop(0) if quantized else None
    vsc_ref = refs.pop(0) if quantized else None
    vf_ref = refs.pop(0) if has_vf else None
    o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = refs
    s_id = pl.program_id(1)
    j = pl.program_id(2)
    jg = s_id * bps + j  # global block index
    gq = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        _init_softmax_scratch(m_scr, l_scr, acc_scr)

    def _step():
        cols = jg * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gq, block_k), 1
        )
        live = cols <= idx_ref[0]
        if has_vf:
            live = jnp.logical_and(live, cols >= vf_ref[0])
        _attend_tile(
            q_ref[0], k_ref[0], v_ref[0],
            ksc_ref[0].reshape(1, block_k) if quantized else None,
            vsc_ref[0].reshape(1, block_k) if quantized else None,
            live, m_scr, l_scr, acc_scr, sm_scale, packed,
        )

    live_block = jnp.logical_and(
        jg < num_kv, jg * block_k <= idx_ref[0]
    )
    if has_vf:
        live_block = jnp.logical_and(
            live_block, (jg + 1) * block_k > vf_ref[0]
        )
    pl.when(live_block)(_step)

    @pl.when(j == bps - 1)
    def _emit():
        hd = o_ref.shape[-1]
        o_ref[0, 0] = acc_scr[...]
        # m/l broadcast across the lane axis so the partial outputs
        # share the accumulator's (gq, hd) tiling; the combine reads
        # lane 0.
        m_ref[0, 0] = jnp.broadcast_to(m_scr[...], (gq, hd))
        l_ref[0, 0] = jnp.broadcast_to(l_scr[...], (gq, hd))


def _combine_splits(o_parts, m_parts, l_parts, out_dtype):
    """Single-pass rescale combine of flash-decoding split partials:
    ``o`` (rows, split, gq, hd) unnormalized f32 accumulators, ``m``/
    ``l`` running max / denominator broadcast over the lane axis (lane
    0 read). A split whose every block was dead carries (m = -inf,
    l = 0) and contributes nothing; an all-dead row emits finite
    garbage (0) exactly like the unsplit kernel's ``acc / max(l,
    eps)``."""
    m = m_parts[..., :1]  # (rows, split, gq, 1)
    l = l_parts[..., :1]
    m_star = jnp.max(m, axis=1, keepdims=True)
    alpha = jnp.exp(m - m_star)
    denom = jnp.sum(l * alpha, axis=1)  # (rows, gq, 1)
    out = jnp.sum(o_parts * alpha, axis=1)
    return (out / jnp.maximum(denom, 1e-30)).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("block_k", "split"))
def _decode_impl(q, k_vals, v_vals, k_scales, v_scales, index, valid_from,
                 block_k, split=1):
    b, kvh, g, hd = q.shape
    cache_len = k_vals.shape[2]
    hdk = k_vals.shape[3]  # head_dim // 2 for packed int4 pools
    num_kv = cache_len // block_k
    quantized = k_scales is not None
    packed = quantized and hdk * 2 == hd
    has_vf = valid_from is not None
    pad_g = (-g) % 8  # sublane-pad the query rows
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gq = g + pad_g

    qf = q.reshape(b * kvh, gq, hd)
    kf = k_vals.reshape(b * kvh, cache_len, hdk)
    vf = v_vals.reshape(b * kvh, cache_len, hdk)
    idx = jnp.repeat(
        jnp.broadcast_to(jnp.asarray(index, jnp.int32).reshape(-1), (b,)),
        kvh,
    )
    sm_scale = 1.0 / (hd ** 0.5)
    bps = -(-num_kv // split)  # blocks per split; last split may be ragged

    def blk(bh, *js):
        # Global block index from the (possibly split) grid point,
        # clamped for the ragged tail (masked in-kernel).
        if split == 1:
            (j,) = js
            return j
        s_id, j = js
        return jnp.minimum(s_id * bps + j, num_kv - 1)

    def row_map(bh, *js):
        del js
        return (bh, 0, 0)

    def kv_map(bh, *js):
        return (bh, blk(bh, *js), 0)

    def smem_map(bh, *js):
        del js
        return (bh,)

    in_specs = [
        pl.BlockSpec((1, gq, hd), row_map, memory_space=_VMEM),
        pl.BlockSpec((1, block_k, hdk), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1, block_k, hdk), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
    ]
    operands = [qf, kf, vf, idx]
    if quantized:
        # (b, kvh, L, 1) f32 -> (b*kvh, L/128, 128) chunked view: the
        # same bytes row-major (position = row*128 + lane), one (1, 8,
        # 128) tile per 1024-position block — no broadcast inflation.
        chunk = lambda s: s.reshape(b * kvh, cache_len // 128, 128)
        rows_per_block = block_k // 128
        for s in (k_scales, v_scales):
            operands.append(chunk(s.astype(jnp.float32)))
            in_specs.append(
                pl.BlockSpec(
                    (1, rows_per_block, 128), kv_map, memory_space=_VMEM
                )
            )
    if has_vf:
        operands.append(jnp.repeat(jnp.asarray(valid_from, jnp.int32), kvh))
        in_specs.append(
            pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM)
        )

    on_tpu = jax.default_backend() == "tpu"
    scratch = [
        pltpu.VMEM((gq, 1), jnp.float32),
        pltpu.VMEM((gq, 1), jnp.float32),
        pltpu.VMEM((gq, hd), jnp.float32),
    ]
    if split == 1:
        out = pl.pallas_call(
            functools.partial(
                _decode_kernel,
                block_k=block_k,
                num_kv=num_kv,
                sm_scale=sm_scale,
                quantized=quantized,
                has_vf=has_vf,
                packed=packed,
            ),
            grid=(b * kvh, num_kv),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, gq, hd), row_map, memory_space=_VMEM
            ),
            out_shape=jax.ShapeDtypeStruct((b * kvh, gq, hd), q.dtype),
            scratch_shapes=scratch,
            compiler_params=(
                pltpu.CompilerParams(
                    dimension_semantics=("parallel", "arbitrary")
                )
                if on_tpu
                else None
            ),
            interpret=not on_tpu,
        )(*operands)
        return out.reshape(b, kvh, gq, hd)[:, :, :g, :]

    # Flash-decoding split: (row, split) partials + single-pass rescale.
    def part_map(bh, s_id, j):
        del j
        return (bh, s_id, 0, 0)

    o_p, m_p, l_p = pl.pallas_call(
        functools.partial(
            _decode_split_kernel,
            block_k=block_k,
            num_kv=num_kv,
            bps=bps,
            sm_scale=sm_scale,
            quantized=quantized,
            has_vf=has_vf,
            packed=packed,
        ),
        grid=(b * kvh, split, bps),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, gq, hd), part_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, gq, hd), part_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, gq, hd), part_map, memory_space=_VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * kvh, split, gq, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * kvh, split, gq, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * kvh, split, gq, hd), jnp.float32),
        ),
        scratch_shapes=scratch,
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(*operands)
    out = _combine_splits(o_p, m_p, l_p, q.dtype)
    return out.reshape(b, kvh, gq, hd)[:, :, :g, :]


def append_kv(cache, new, index):
    """Multi-token-per-slot cache append — THE cached-decode write
    primitive, shared by single-token ``decode_step`` (K == 1) and the
    speculative verify paths (K == draft_k + 1).

    cache (b, h, L, hd); new (b, h, K, hd); ``index`` scalar (whole
    batch writes at one position — the ``generate()`` lockstep and the
    single-request verify) or (b,) (each ROW writes its K tokens at its
    own position — batched speculation, where slots desynchronize; a
    vmapped ``dynamic_update_slice``, one fused scatter under XLA, not
    b copies). XLA clamps the start index, so callers must reserve
    K - 1 slack positions past the largest live index (the trash-slack
    discipline ``runtime/continuous`` and ``models/speculative`` cache
    allocations follow) — a clamped garbage write lands in masked space
    instead of silently shifting onto live positions."""
    if jnp.ndim(index):
        return jax.vmap(
            lambda c, n, i: lax.dynamic_update_slice(c, n, (0, i, 0))
        )(cache, new, index)
    return lax.dynamic_update_slice(cache, new, (0, 0, index, 0))


def verify_attention(q, cache_k, cache_v, index, chunk: int, window=None,
                     tree_tail: int = 0):
    """Multi-token VERIFY attention: K chunk rows per slot, each
    attending the cache up to its OWN position — the speculative-decode
    primitive (K causal logits for one weight stream).

    q (b, kv_h, g*chunk, hd) group-folded with K-major rows (row =
    member*chunk + t, ``CausalSelfAttention._group_q`` on a (b, h, K,
    hd) query); caches (b, kv_h, L, hd) with the chunk's K/V already
    appended (``append_kv``); ``index`` scalar or (b,) is the cache
    position of chunk token 0, so row t's live window is
    ``col <= index + t`` (banded below by ``window`` when set). A
    negative per-row index marks a DEAD row (idle slot): every position
    masks out and the output is finite garbage nothing reads — the same
    discipline as the batcher's trash slot.

    Caches may be ``(int8 values, f32 scales)`` pairs — the SAME
    quantized layout (and the same score/probability-column scale
    application, in the same op order) as
    ``decode_attention_reference``, so a quantized verify chunk's K
    logits equal what K sequential quantized ``decode_step`` calls
    produce: the speculative-verify path over an int8 cache.

    ``tree_tail`` = w > 0 marks the chunk's LAST w rows as TREE LEAVES
    (grouped draft proposals sharing the chain prefix — speculative
    tree drafts): leaf row r attends the whole chain (cols <= index +
    chain, chain = chunk - 1 - w) PLUS its own physical slot (col ==
    index + r) and nothing of its siblings, so one verify pass scores
    every leaf candidate for logical position chain + 1 at once. Chain
    rows keep the ordinary per-row diagonal (their own slot is inside
    it).

    The einsum schedule is ``decode_attention_reference``'s with a
    per-row diagonal instead of a shared newest position; XLA-only for
    now (``decode_kernel_wins`` rules the streaming kernel out
    everywhere until its hardware A/B lands, and verify amortizes the
    cache stream over K rows already)."""
    quantized = isinstance(cache_k, tuple)
    sm = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    if quantized:
        (kvl, ksc), (vvl, vsc) = cache_k, cache_v
        check_head_parity(q.shape[1], kvl.shape[1])
        if kvl.shape[-1] * 2 == q.shape[-1]:  # packed int4 nibbles
            kvl, vvl = unpack_int4(kvl), unpack_int4(vvl)
        # Scales factor OUT of the per-vector dot: apply them to the
        # score columns in decode_attention_reference's exact op order,
        # so per-row values match the sequential quantized decode.
        s = jnp.einsum(
            "bhqd,bhkd->bhqk",
            q.astype(jnp.float32),
            kvl.astype(jnp.float32),
        ) * jnp.swapaxes(ksc, 2, 3) * sm
        n_pos = kvl.shape[2]
    else:
        check_head_parity(q.shape[1], cache_k.shape[1])
        s = (
            jnp.einsum(
                "bhqd,bhkd->bhqk",
                q.astype(jnp.float32),
                cache_k.astype(jnp.float32),
            )
            * sm
        )  # (b, kv_h, g*chunk, L)
        n_pos = cache_k.shape[2]
    cols = jnp.arange(n_pos)
    rows = jnp.arange(q.shape[2]) % chunk  # row -> chunk position t
    # Tree leaves attend up to the CHAIN edge (depth), chain rows up to
    # their own diagonal; every row's own physical slot is always live
    # (for chain rows it already is — own <= edge).
    depth = (
        jnp.minimum(rows, chunk - 1 - tree_tail) if tree_tail else rows
    )
    if jnp.ndim(index):
        edge = index[:, None, None] + depth[None, :, None]  # (b, g*K, 1)
        live = cols[None, None, :] <= edge
        if window is not None:
            live = live & (cols[None, None, :] > edge - window)
        if tree_tail:
            own = index[:, None, None] + rows[None, :, None]
            live = live | (cols[None, None, :] == own)
        s = jnp.where(live[:, None], s, _NEG_INF)
    else:
        edge = index + depth[:, None]  # (g*K, 1)
        live = cols[None, :] <= edge
        if window is not None:
            live = live & (cols[None, :] > edge - window)
        if tree_tail:
            own = index + rows[:, None]
            live = live | (cols[None, :] == own)
        s = jnp.where(live[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if quantized:
        o = jnp.einsum(
            "bhqk,bhkd->bhqd",
            p * jnp.swapaxes(vsc, 2, 3),
            vvl.astype(jnp.float32),
        )
    else:
        o = jnp.einsum(
            "bhqk,bhkd->bhqd", p, cache_v.astype(jnp.float32)
        )
    return o.astype(q.dtype)


def decode_attention_reference(q, cache_k, cache_v, index, valid_from=None):
    """The XLA oracle — the exact einsum schedule ``decode_step`` has
    always run (f32 scores, position mask over the full buffer, scales
    applied to the score/probability rows for int8 caches), lifted here
    so both paths share one definition.

    q: (b, kv_h, g, hd) group-folded queries; caches (b, kv_h, L, hd)
    arrays or ``(int8 values, f32 scales)`` pairs; ``index`` scalar or
    (b,); returns (b, kv_h, g, hd) in q's dtype."""
    quantized = isinstance(cache_k, tuple)
    sm = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    if quantized:
        (kvl, ksc), (vvl, vsc) = cache_k, cache_v
        if kvl.shape[-1] * 2 == q.shape[-1]:  # packed int4 nibbles
            kvl, vvl = unpack_int4(kvl), unpack_int4(vvl)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk",
            q.astype(jnp.float32),
            kvl.astype(jnp.float32),
        ) * jnp.swapaxes(ksc, 2, 3) * sm
        n_pos = kvl.shape[2]
    else:
        s = (
            jnp.einsum(
                "bhqd,bhkd->bhqk",
                q.astype(jnp.float32),
                cache_k.astype(jnp.float32),
            )
            * sm
        )
        n_pos = cache_k.shape[2]
    positions = jnp.arange(n_pos)
    live = positions[None, :] <= (
        index[:, None] if jnp.ndim(index) else index
    )
    if valid_from is not None:
        live = live & (positions[None, :] >= valid_from[:, None])
    s = jnp.where(live[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if quantized:
        o = jnp.einsum(
            "bhqk,bhkd->bhqd",
            p * jnp.swapaxes(vsc, 2, 3),
            vvl.astype(jnp.float32),
        )
    else:
        o = jnp.einsum(
            "bhqk,bhkd->bhqd", p, cache_v.astype(jnp.float32)
        )
    return o.astype(q.dtype)


def decode_attention(
    q: jax.Array,
    cache_k,
    cache_v,
    index,
    valid_from=None,
    prefer: str | None = None,
    block_k: int | None = None,
    split: int | None = None,
) -> jax.Array:
    """Cached decode attention over the live window ``[valid_from,
    index]`` of a KV cache.

    q: (b, kv_h, g, hd) — GQA groups already folded into query rows
    (``CausalSelfAttention._group_q``; g = heads//kv_h x tokens).
    Caches: (b, kv_h, L, hd) arrays, or ``(int8 values, f32 scales)``
    pairs with one scale per cached vector. ``index`` (scalar or (b,))
    is the newest live position — the caller has already written this
    step's K/V there. Returns (b, kv_h, g, hd).

    ``prefer``: None = auto (``decode_kernel_wins``, the measured rule),
    ``"xla"`` = the einsum oracle, ``"pallas"`` = the streaming kernel
    (falls back to the oracle off-pallas or when L doesn't divide into
    supported blocks: native caches need L % 256 == 0, int8 caches
    L % 1024 == 0 — the scale-tile layout). ``block_k`` None picks the
    largest supported block (``default_block_k``). ``split`` is the
    flash-decoding KV-length split factor: None auto-derives
    (``default_decode_split`` of the block count on real TPUs; 1
    off-TPU, where the interpreter gains nothing from fan-out), 1 runs
    the original single-stream kernel bit-exactly, > 1 fans the cache
    stream across independent grid splits with a single-pass rescale
    combine. Caches may also be PACKED int4 pairs (values
    ``head_dim // 2`` wide — ``ops.quantize.quantize_kv_vectors(...,
    "int4")``); the kernels unpack nibbles in VMEM so the HBM stream
    stays 4-bit. Every grid/fold/block
    derives from the shapes GIVEN — the per-shard head count under
    tensor parallelism — so a q/cache head mismatch fails loud
    (``check_head_parity``)."""
    quantized = isinstance(cache_k, tuple)
    check_head_parity(
        q.shape[1], (cache_k[0] if quantized else cache_k).shape[1]
    )
    cache_len = (cache_k[0] if quantized else cache_k).shape[2]
    if block_k is None:
        block_k = default_block_k(cache_len, quantized)
    if prefer is None:
        prefer = (
            "pallas" if decode_kernel_wins(cache_len, quantized) else "xla"
        )
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and _supported(cache_len, block_k, quantized):
        split = resolve_decode_split(cache_len // block_k, split)
        record_kernel_dispatch("decode", "pallas")
        if quantized:
            (kvl, ksc), (vvl, vsc) = cache_k, cache_v
            return _decode_impl(
                q, kvl, vvl, ksc, vsc, index, valid_from, block_k, split
            )
        return _decode_impl(
            q, cache_k, cache_v, None, None, index, valid_from, block_k,
            split,
        )
    record_kernel_dispatch("decode", "xla")
    return decode_attention_reference(
        q, cache_k, cache_v, index, valid_from
    )
