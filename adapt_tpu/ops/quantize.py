"""Blockwise int8 quantization as a Pallas TPU kernel.

The TPU-native re-expression of the reference's per-hop lossy codec
(zfp+lz4 on every activation and weight crossing a socket,
``/root/reference/src/dispatcher.py:92-98``, ``src/node.py:122-125``).
On TPU the codec's job moves on-device: quantize in VMEM right before a
DCN-boundary transfer (4x smaller payload off-chip), dequantize on the
other side — ICI hops need no codec at all (SURVEY.md §2.3).

Layout: the flat tensor is viewed as (rows, 128) lanes and split into
row-blocks; each block of ``block_rows * 128`` elements gets one f32
scale (absmax / 127). Blockwise scales bound the quantization error per
block — the same locality argument zfp's 4^d blocks make.

Off-TPU (tests, CPU sim-mesh) the same kernels run through the Pallas
interpreter, so behavior is identical everywhere; ``*_reference`` are the
pure-jnp oracles used by the unit tests.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces are importable everywhere jax is, but be safe
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
    _SMEM = pltpu.SMEM
except Exception:  # pragma: no cover - exotic builds
    pltpu = None
    _VMEM = None
    _SMEM = None

LANES = 128
BLOCK_ROWS = 64  # one scale per 64*128 = 8192 elements


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """int8 payload + per-block scales + logical shape/dtype."""

    values: jax.Array  # (rows, 128) int8, padded
    scales: jax.Array  # (num_blocks, 1) f32
    shape: tuple[int, ...]
    dtype: jnp.dtype

    def tree_flatten(self):
        return (self.values, self.scales), (self.shape, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        values, scales = children
        shape, dtype = aux
        return cls(values, scales, shape, dtype)

    @property
    def nbytes_payload(self) -> int:
        return self.values.size + self.scales.size * 4


def _quant_kernel(x_ref, vals_ref, scale_ref):
    # scale_ref holds the FULL (num_blocks, 1) scales array in SMEM (TPU
    # tiling forbids (1, 1) VMEM blocks); each grid step writes its slot.
    amax = jnp.max(jnp.abs(x_ref[:]))
    scale = jnp.maximum(amax, 1e-12) / 127.0
    scale_ref[pl.program_id(0), 0] = scale
    q = jnp.clip(jnp.round(x_ref[:] / scale), -127.0, 127.0)
    vals_ref[:] = q.astype(jnp.int8)


def _dequant_kernel(vals_ref, scale_ref, out_ref):
    out_ref[:] = vals_ref[:].astype(jnp.float32) * scale_ref[pl.program_id(0), 0]


def _to_rows(x: jax.Array) -> tuple[jax.Array, int]:
    """Flatten to (rows, LANES) f32, zero-padded to whole blocks."""
    flat = x.astype(jnp.float32).reshape(-1)
    block = BLOCK_ROWS * LANES
    pad = (-flat.size) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, LANES), flat.size // block


@jax.jit
def quantize(x: jax.Array) -> QuantizedTensor:
    """Blockwise int8-quantize any-shape tensor (Pallas kernel)."""
    rows, num_blocks = _to_rows(x)
    vals, scales = pl.pallas_call(
        _quant_kernel,
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec(
                (BLOCK_ROWS, LANES), lambda i: (i, 0), memory_space=_VMEM
            )
        ],
        out_specs=(
            pl.BlockSpec(
                (BLOCK_ROWS, LANES), lambda i: (i, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(
                (num_blocks, 1), lambda i: (0, 0), memory_space=_SMEM
            ),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(rows.shape, jnp.int8),
            jax.ShapeDtypeStruct((num_blocks, 1), jnp.float32),
        ),
        interpret=_interpret(),
    )(rows)
    return QuantizedTensor(vals, scales, tuple(x.shape), x.dtype)


@jax.jit
def dequantize(qt: QuantizedTensor) -> jax.Array:
    """Inverse of :func:`quantize` (Pallas kernel).

    Deliberately NOT donated: the int8 values can never alias the f32
    output (dtype width mismatch), so donation here would be a
    per-compile XLA warning and nothing else — the decode path's real
    donation lives where buffers CAN alias (``ContinuousBatcher``'s
    caches and device-resident slot state)."""
    num_blocks = qt.scales.shape[0]
    out = pl.pallas_call(
        _dequant_kernel,
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec(
                (BLOCK_ROWS, LANES), lambda i: (i, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(
                (num_blocks, 1), lambda i: (0, 0), memory_space=_SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (BLOCK_ROWS, LANES), lambda i: (i, 0), memory_space=_VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(qt.values.shape, jnp.float32),
        interpret=_interpret(),
    )(qt.values, qt.scales)
    size = math.prod(qt.shape)
    return out.reshape(-1)[:size].reshape(qt.shape).astype(qt.dtype)


def quantize_kv_vectors(t: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-vector absmax int8 over the trailing (head_dim) axis — THE
    KV-cache quantization scheme (one f32 scale per cached key/value
    vector), shared by ``CausalSelfAttention``, the decode-attention
    kernel tests and the on-chip smoke so the definition cannot fork.
    Returns ``(int8 values, f32 scales with keepdims)``."""
    scale = (
        jnp.max(jnp.abs(t.astype(jnp.float32)), axis=-1, keepdims=True)
        / 127.0
    )
    scale = jnp.maximum(scale, 1e-8)
    vals = (
        jnp.round(t.astype(jnp.float32) / scale)
        .clip(-127, 127)
        .astype(jnp.int8)
    )
    return vals, scale


def quantize_params(tree):
    """int8-quantize every float MATRIX leaf (ndim >= 2) of a param
    pytree into :class:`QuantizedTensor` (the blockwise Pallas scheme
    above). 1-D leaves — biases, LayerNorm scales — stay native: they
    are O(dim) bytes (nothing to save) and their per-channel dynamic
    range is exactly where blockwise absmax hurts most. The use case is
    the speculative DRAFT model's weights
    (``SpeculativeConfig.draft_weight_dtype="int8"``): the draft
    replicates under tensor parallelism, so quantizing its resident
    weights cuts the per-chip cost of speculation ~4x (f32) while
    :func:`dequantize_params` restores f32 inside the draft programs."""

    def q(leaf):
        # leaf.dtype directly — jnp.asarray here would stage every
        # leaf (including the untouched 1-D ones) to device just to
        # read a dtype.
        if (
            hasattr(leaf, "ndim")
            and leaf.ndim >= 2
            and jnp.issubdtype(leaf.dtype, jnp.floating)
        ):
            return quantize(leaf)
        return leaf

    return jax.tree.map(q, tree)


def dequantize_params(tree):
    """Inverse of :func:`quantize_params`: dequantize every
    :class:`QuantizedTensor` leaf in place of itself, pass everything
    else through. Call INSIDE the consuming jitted program (the draft
    scan / draft prefill), so the persistent HBM residency stays int8
    and the f32 weights exist only for the program's lifetime."""
    return jax.tree.map(
        lambda l: dequantize(l) if isinstance(l, QuantizedTensor) else l,
        tree,
        is_leaf=lambda l: isinstance(l, QuantizedTensor),
    )


# -- pure-jnp oracles (unit-test ground truth) -------------------------------


def quantize_reference(x: jax.Array) -> QuantizedTensor:
    rows, num_blocks = _to_rows(x)
    blocks = rows.reshape(num_blocks, -1)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scales = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(blocks / scales), -127.0, 127.0).astype(jnp.int8)
    return QuantizedTensor(
        q.reshape(rows.shape), scales, tuple(x.shape), x.dtype
    )


def dequantize_reference(qt: QuantizedTensor) -> jax.Array:
    num_blocks = qt.scales.shape[0]
    blocks = qt.values.reshape(num_blocks, -1).astype(jnp.float32)
    out = (blocks * qt.scales).reshape(-1)
    size = math.prod(qt.shape)
    return out[:size].reshape(qt.shape).astype(qt.dtype)
