"""Blockwise int8 quantization as a Pallas TPU kernel.

The TPU-native re-expression of the reference's per-hop lossy codec
(zfp+lz4 on every activation and weight crossing a socket,
``/root/reference/src/dispatcher.py:92-98``, ``src/node.py:122-125``).
On TPU the codec's job moves on-device: quantize in VMEM right before a
DCN-boundary transfer (4x smaller payload off-chip), dequantize on the
other side — ICI hops need no codec at all (SURVEY.md §2.3).

Layout: the flat tensor is viewed as (rows, 128) lanes and split into
row-blocks; each block of ``block_rows * 128`` elements gets one f32
scale (absmax / 127). Blockwise scales bound the quantization error per
block — the same locality argument zfp's 4^d blocks make.

Off-TPU (tests, CPU sim-mesh) the same kernels run through the Pallas
interpreter, so behavior is identical everywhere; ``*_reference`` are the
pure-jnp oracles used by the unit tests.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces are importable everywhere jax is, but be safe
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
    _SMEM = pltpu.SMEM
except Exception:  # pragma: no cover - exotic builds
    pltpu = None
    _VMEM = None
    _SMEM = None

LANES = 128
BLOCK_ROWS = 64  # one scale per 64*128 = 8192 elements


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """int8 payload + per-block scales + logical shape/dtype."""

    values: jax.Array  # (rows, 128) int8, padded
    scales: jax.Array  # (num_blocks, 1) f32
    shape: tuple[int, ...]
    dtype: jnp.dtype

    def tree_flatten(self):
        return (self.values, self.scales), (self.shape, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        values, scales = children
        shape, dtype = aux
        return cls(values, scales, shape, dtype)

    @property
    def nbytes_payload(self) -> int:
        return self.values.size + self.scales.size * 4


def _quant_kernel(x_ref, vals_ref, scale_ref):
    # scale_ref holds the FULL (num_blocks, 1) scales array in SMEM (TPU
    # tiling forbids (1, 1) VMEM blocks); each grid step writes its slot.
    amax = jnp.max(jnp.abs(x_ref[:]))
    scale = jnp.maximum(amax, 1e-12) / 127.0
    scale_ref[pl.program_id(0), 0] = scale
    q = jnp.clip(jnp.round(x_ref[:] / scale), -127.0, 127.0)
    vals_ref[:] = q.astype(jnp.int8)


def _dequant_kernel(vals_ref, scale_ref, out_ref):
    out_ref[:] = vals_ref[:].astype(jnp.float32) * scale_ref[pl.program_id(0), 0]


def _to_rows(x: jax.Array) -> tuple[jax.Array, int]:
    """Flatten to (rows, LANES) f32, zero-padded to whole blocks."""
    flat = x.astype(jnp.float32).reshape(-1)
    block = BLOCK_ROWS * LANES
    pad = (-flat.size) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, LANES), flat.size // block


@jax.jit
def quantize(x: jax.Array) -> QuantizedTensor:
    """Blockwise int8-quantize any-shape tensor (Pallas kernel)."""
    rows, num_blocks = _to_rows(x)
    vals, scales = pl.pallas_call(
        _quant_kernel,
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec(
                (BLOCK_ROWS, LANES), lambda i: (i, 0), memory_space=_VMEM
            )
        ],
        out_specs=(
            pl.BlockSpec(
                (BLOCK_ROWS, LANES), lambda i: (i, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(
                (num_blocks, 1), lambda i: (0, 0), memory_space=_SMEM
            ),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(rows.shape, jnp.int8),
            jax.ShapeDtypeStruct((num_blocks, 1), jnp.float32),
        ),
        interpret=_interpret(),
    )(rows)
    return QuantizedTensor(vals, scales, tuple(x.shape), x.dtype)


@jax.jit
def dequantize(qt: QuantizedTensor) -> jax.Array:
    """Inverse of :func:`quantize` (Pallas kernel).

    Deliberately NOT donated: the int8 values can never alias the f32
    output (dtype width mismatch), so donation here would be a
    per-compile XLA warning and nothing else — the decode path's real
    donation lives where buffers CAN alias (``ContinuousBatcher``'s
    caches and device-resident slot state)."""
    num_blocks = qt.scales.shape[0]
    out = pl.pallas_call(
        _dequant_kernel,
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec(
                (BLOCK_ROWS, LANES), lambda i: (i, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(
                (num_blocks, 1), lambda i: (0, 0), memory_space=_SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (BLOCK_ROWS, LANES), lambda i: (i, 0), memory_space=_VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(qt.values.shape, jnp.float32),
        interpret=_interpret(),
    )(qt.values, qt.scales)
    size = math.prod(qt.shape)
    return out.reshape(-1)[:size].reshape(qt.shape).astype(qt.dtype)


def pack_int4(q: jax.Array) -> jax.Array:
    """Pack an even-width trailing axis of int values in [-8, 7] into
    int8 bytes, two NIBBLES per lane: element ``2i`` lands in the low
    nibble of byte ``i``, element ``2i + 1`` in the high nibble — the
    int4 KV pool layout (the HBM stream is half the int8 bytes).
    Returns ``(..., w // 2)`` int8."""
    q = q.astype(jnp.int32)
    lo, hi = q[..., 0::2], q[..., 1::2]
    p = (lo & 15) | ((hi & 15) << 4)
    # Explicit two's-complement wrap before the int8 cast: the packed
    # byte pattern is what matters, not its signed value.
    return jnp.where(p >= 128, p - 256, p).astype(jnp.int8)


def unpack_int4(packed: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_int4`: ``(..., w)`` int8 packed bytes ->
    ``(..., 2w)`` int32 nibble values in [-8, 7], interleaved back into
    element order. Pure lane arithmetic (mask / shift / stack), so the
    Pallas kernels run it in VMEM on the streamed int8 tile — the fused
    int4 dequant's unpack half."""
    p = packed.astype(jnp.int32)
    lo = ((p & 15) ^ 8) - 8  # sign-extend the low nibble
    hi = p >> 4  # arithmetic shift sign-extends the high nibble
    return jnp.stack([lo, hi], axis=-1).reshape(
        p.shape[:-1] + (p.shape[-1] * 2,)
    )


def quantize_kv_vectors(
    t: jax.Array, dtype: str = "int8"
) -> tuple[jax.Array, jax.Array]:
    """Per-vector absmax quantization over the trailing (head_dim) axis
    — THE KV-cache quantization scheme (one f32 scale per cached
    key/value vector), shared by ``CausalSelfAttention``, the
    decode-attention kernel tests and the on-chip smoke so the
    definition cannot fork.

    ``dtype="int8"`` returns ``(int8 values, f32 scales with
    keepdims)``. ``dtype="int4"`` quantizes to the 15-level [-7, 7]
    lattice and PACKS two nibbles per int8 lane (:func:`pack_int4`) —
    values ``(..., head_dim // 2)`` int8, scales unchanged — so the
    resident bytes are 4-bit while the scale plane keeps the int8
    layout (page tables, head sharding and handoff plans see the same
    pytree shape discipline)."""
    if dtype not in ("int8", "int4"):
        raise ValueError(
            f"dtype={dtype!r}: expected 'int8' or 'int4'"
        )
    qmax = 127.0 if dtype == "int8" else 7.0
    scale = (
        jnp.max(jnp.abs(t.astype(jnp.float32)), axis=-1, keepdims=True)
        / qmax
    )
    scale = jnp.maximum(scale, 1e-8)
    vals = jnp.round(t.astype(jnp.float32) / scale).clip(-qmax, qmax)
    if dtype == "int4":
        if t.shape[-1] % 2:
            raise ValueError(
                f"int4 KV packing needs an even head_dim, got "
                f"{t.shape[-1]}"
            )
        return pack_int4(vals), scale
    return vals.astype(jnp.int8), scale


def quantize_params(tree):
    """int8-quantize every float MATRIX leaf (ndim >= 2) of a param
    pytree into :class:`QuantizedTensor` (the blockwise Pallas scheme
    above). 1-D leaves — biases, LayerNorm scales — stay native: they
    are O(dim) bytes (nothing to save) and their per-channel dynamic
    range is exactly where blockwise absmax hurts most. The use case is
    the speculative DRAFT model's weights
    (``SpeculativeConfig.draft_weight_dtype="int8"``): the draft
    replicates under tensor parallelism, so quantizing its resident
    weights cuts the per-chip cost of speculation ~4x (f32) while
    :func:`dequantize_params` restores f32 inside the draft programs."""

    def q(leaf):
        # leaf.dtype directly — jnp.asarray here would stage every
        # leaf (including the untouched 1-D ones) to device just to
        # read a dtype.
        if (
            hasattr(leaf, "ndim")
            and leaf.ndim >= 2
            and jnp.issubdtype(leaf.dtype, jnp.floating)
        ):
            return quantize(leaf)
        return leaf

    return jax.tree.map(q, tree)


def dequantize_params(tree):
    """Inverse of :func:`quantize_params`: dequantize every
    :class:`QuantizedTensor` leaf in place of itself, pass everything
    else through. Call INSIDE the consuming jitted program (the draft
    scan / draft prefill), so the persistent HBM residency stays int8
    and the f32 weights exist only for the program's lifetime."""
    return jax.tree.map(
        lambda l: dequantize(l) if isinstance(l, QuantizedTensor) else l,
        tree,
        is_leaf=lambda l: isinstance(l, QuantizedTensor),
    )


# -- page codec stack (hierarchical KV cache tiers, runtime/paged) -----------
#
# Host-side codecs for KV PAGES crossing a memory-hierarchy boundary:
# spills to the host-DRAM tier (``runtime/paged.HostKVTier``), readmits
# back into the pool, and the disaggregated MSG_KV_PAGES wire
# (``runtime/disagg.pack_handoff``) — the TPU-era re-expression of the
# reference's per-transfer lz4+zfp stack at page granularity. These run
# on numpy by construction: every call site already holds host bytes
# (a spilled page, a wire frame), so a device kernel would only add a
# round trip. The kernels' half of this DNA is the fused int8/int4
# dequant in ``ops/paged_attention`` — pages readmitted from a lossy
# tier flow straight back through it.
#
# Codec contract: ``decode_page(encode_page(x, c)) `` returns x's exact
# shape and dtype; "raw"/"lz" are BIT-EXACT (the WARM-tier / lossless
# wire setting), "int8"/"int4" are the repo's per-vector absmax
# schemes (one f32 scale per trailing-axis vector — the same lattice
# the quantized pools use), "zfp" is zfp-style mantissa truncation
# (keep sign/exponent/top mantissa bits, then lz the zero-heavy tail).
# Lossy codecs apply to FLOAT arrays only; on integer arrays (int8
# value planes of quantized pools, prompt ids on the wire) they
# degrade to "lz" — bit-exact — so a lossy tier can never corrupt
# already-quantized payloads.

PAGE_CODECS = ("raw", "lz", "int8", "int4", "zfp")
LOSSLESS_PAGE_CODECS = ("raw", "lz")
#: zfp-style truncation: mantissa bits KEPT (of f32's 23). 10 bits
#: bounds relative error at ~2^-11 per element — comfortably inside
#: the int8 per-vector scheme's error, and the truncated tail is what
#: makes the trailing lz pass actually save bytes.
ZFP_KEEP_BITS = 10


def _np():
    import numpy as np

    return np


def _np_pack_int4(q):
    """numpy twin of :func:`pack_int4` (same nibble layout)."""
    np = _np()
    q = q.astype(np.int32)
    lo, hi = q[..., 0::2] & 15, q[..., 1::2] & 15
    p = lo | (hi << 4)
    return np.where(p >= 128, p - 256, p).astype(np.int8)


def _np_unpack_int4(packed):
    """numpy twin of :func:`unpack_int4`."""
    np = _np()
    p = packed.astype(np.int32)
    lo = ((p & 15) ^ 8) - 8
    hi = p >> 4
    return np.stack([lo, hi], axis=-1).reshape(
        p.shape[:-1] + (p.shape[-1] * 2,)
    )


def encode_page(arr, codec: str) -> tuple[bytes, dict]:
    """Encode one host array for a tier boundary. Returns
    ``(payload, meta)``; ``meta`` carries everything
    :func:`decode_page` needs (shape, dtype, the codec actually
    applied — lossy requests on integer arrays record the "lz" they
    degraded to) plus ``raw_nbytes`` for compression accounting."""
    import zlib

    np = _np()
    if codec not in PAGE_CODECS:
        raise ValueError(
            f"codec={codec!r}: expected one of {PAGE_CODECS}"
        )
    arr = np.ascontiguousarray(arr)
    meta = {
        "shape": tuple(int(s) for s in arr.shape),
        "dtype": str(arr.dtype),
        "codec": codec,
        "raw_nbytes": int(arr.nbytes),
    }
    lossy = codec in ("int8", "int4", "zfp")
    if lossy and (
        not np.issubdtype(arr.dtype, np.floating)
        or (codec in ("int8", "int4") and arr.shape[-1] < 2)
    ):
        # Lossy on non-float degrades to lossless packing — a lossy
        # tier must never perturb already-quantized int payloads. The
        # per-vector absmax codecs also degrade on (..., 1) arrays
        # (quantized pools' SCALE planes): one f32 scale per single
        # element saves nothing and perturbs every later dequant.
        codec = "lz"
        meta["codec"] = "lz"
    if codec == "raw":
        return arr.tobytes(), meta
    if codec == "lz":
        return zlib.compress(arr.tobytes(), 1), meta
    if codec == "zfp":
        u = arr.astype(np.float32).view(np.uint32)
        mask = np.uint32(
            (0xFFFFFFFF << (23 - ZFP_KEEP_BITS)) & 0xFFFFFFFF
        )
        trunc = (u & mask).tobytes()
        return zlib.compress(trunc, 1), meta
    # int8 / int4: per-vector absmax over the trailing axis — the KV
    # quantization scheme (quantize_kv_vectors) on host numpy.
    qmax = 127.0 if codec == "int8" else 7.0
    f = arr.astype(np.float32)
    scale = np.maximum(
        np.abs(f).max(axis=-1, keepdims=True) / qmax, 1e-8
    ).astype(np.float32)
    q = np.clip(np.round(f / scale), -qmax, qmax)
    if codec == "int4":
        if arr.shape[-1] % 2:
            raise ValueError(
                f"int4 page codec needs an even trailing axis, got "
                f"{arr.shape[-1]}"
            )
        vals = _np_pack_int4(q)
    else:
        vals = q.astype(np.int8)
    return scale.tobytes() + vals.tobytes(), meta


def decode_page(payload, meta: dict):
    """Inverse of :func:`encode_page`: payload (bytes-like) + meta ->
    array of the original shape/dtype. Bit-exact for raw/lz; the lossy
    codecs return the dequantized/truncated values cast back."""
    import zlib

    np = _np()
    shape = tuple(meta["shape"])
    dtype = np.dtype(meta["dtype"])
    codec = meta["codec"]
    buf = bytes(payload)
    if codec == "raw":
        return np.frombuffer(buf, dtype).reshape(shape).copy()
    if codec == "lz":
        return (
            np.frombuffer(zlib.decompress(buf), dtype).reshape(shape).copy()
        )
    if codec == "zfp":
        u = np.frombuffer(zlib.decompress(buf), np.uint32).reshape(shape)
        return u.view(np.float32).astype(dtype)
    n_vec = 1
    for s in shape[:-1]:
        n_vec *= s
    scale = np.frombuffer(buf[: n_vec * 4], np.float32).reshape(
        shape[:-1] + (1,)
    )
    if codec == "int4":
        vals = np.frombuffer(buf[n_vec * 4:], np.int8).reshape(
            shape[:-1] + (shape[-1] // 2,)
        )
        q = _np_unpack_int4(vals)
    else:
        q = np.frombuffer(buf[n_vec * 4:], np.int8).reshape(shape)
    return (q.astype(np.float32) * scale).astype(dtype)


def page_codec_roundtrip(arr, codec: str):
    """``decode(encode(arr))`` — the one-call roundtrip tests and the
    kv_tiers micro driver pin bit-exactness (lossless) or error
    bounds (lossy) against."""
    payload, meta = encode_page(arr, codec)
    return decode_page(payload, meta)


# -- pure-jnp oracles (unit-test ground truth) -------------------------------


def quantize_reference(x: jax.Array) -> QuantizedTensor:
    rows, num_blocks = _to_rows(x)
    blocks = rows.reshape(num_blocks, -1)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scales = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(blocks / scales), -127.0, 127.0).astype(jnp.int8)
    return QuantizedTensor(
        q.reshape(rows.shape), scales, tuple(x.shape), x.dtype
    )


def dequantize_reference(qt: QuantizedTensor) -> jax.Array:
    num_blocks = qt.scales.shape[0]
    blocks = qt.values.reshape(num_blocks, -1).astype(jnp.float32)
    out = (blocks * qt.scales).reshape(-1)
    size = math.prod(qt.shape)
    return out[:size].reshape(qt.shape).astype(qt.dtype)
